"""Autoregressive KV-cache decoding through the pipeline (GPT-2 family).

NEW capability beyond the reference (whose model list is encoder-only and
whose runtime is single-shot batch inference). TPU-first design:

- **Static shapes everywhere**: the KV cache is a fixed [n_blocks, B,
  max_len, H, Dh] buffer per stage; the current length rides as a traced
  scalar `pos`, future positions are masked. One compiled prefill program +
  one compiled decode-step program per stage serve the whole generation —
  no per-step recompilation (the reference's dynamic-shape wire protocol
  has no answer to this; SURVEY.md §7 'hard parts').
- **Block-aligned pipeline stages**: each stage holds its blocks' cache,
  consumes the previous stage's hidden state for the current token, and
  returns its own — the same stage-edge discipline as the forward
  pipeline (quantizable, device-placeable). Autoregression serializes
  decode steps, so parallelism comes from the batch dimension; stages
  still split the model across devices for memory capacity.
- Attention over the cache streams as one [B, H, 1, T_max] masked matmul —
  MXU-shaped, no gather.

Greedy decoding matches HF `GPT2LMHeadModel.generate(do_sample=False)`
token-for-token (tests/test_decode.py).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..utils import jax_compat

from ..models import ShardConfig, plan_shard
from ..models.layers import (TransformerConfig, dense, gelu_new, layer_norm)

Cache = Dict[str, jax.Array]   # {'k': [L, B, T, H, Dh], 'v': [L, B, T, H, Dh]}
# int8 variant adds per-(block, batch, position, head) scale/shift rows —
# the head axis shards over 'tp' with the K/V buffers:
#   {'k': int8, 'v': int8, 'k_scale'/'k_shift'/'v_scale'/'v_shift': [L, B, T, H]}


def init_cache(cfg: TransformerConfig, n_blocks: int, batch: int,
               max_len: int, dtype=jnp.float32,
               cache_bits: int = 0) -> Cache:
    """Zeroed stacked KV cache for `n_blocks` blocks.

    `cache_bits=8` stores K/V as int8 with per-(position, head) affine
    scales (QuantPipe's activation-compression idea applied to the decode
    cache): cache reads dominate decode-step HBM traffic, so int8 halves
    the bandwidth bound vs bfloat16 at negligible logit error. Scales are
    per HEAD (not per position only) so the scale rows carry a head axis
    and shard over 'tp' exactly like the K/V buffers — int8 caches
    compose with tensor-parallel decode, and the finer granularity also
    tightens the quantization error.

    The head axis is `cfg.kv_heads` — equal to the query head count for
    every family except GQA decoders (llama), whose cache is kv_heads/
    num_attention_heads times smaller (the point of GQA)."""
    shape = (n_blocks, batch, max_len, cfg.kv_heads, cfg.head_dim)
    if cache_bits == 0:
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if cache_bits != 8:
        raise ValueError(f"cache_bits must be 0 (off) or 8, got {cache_bits}")
    rows = shape[:4]                       # [..., T, H] per-head scales
    cache = {"k": jnp.zeros(shape, jnp.int8),
             "v": jnp.zeros(shape, jnp.int8)}
    for t in ("k", "v"):
        cache[f"{t}_scale"] = jnp.zeros(rows, jnp.float32)
        cache[f"{t}_shift"] = jnp.zeros(rows, jnp.float32)
    return cache


def _quantize_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Affine-quantize [B, S, H, Dh] to int8 per (batch, position, head)."""
    lo = jnp.min(x, axis=3).astype(jnp.float32)             # [B, S, H]
    hi = jnp.max(x, axis=3).astype(jnp.float32)
    scale = jnp.maximum(hi - lo, 1e-8) / 255.0
    q = jnp.round((x.astype(jnp.float32) - lo[..., None])
                  / scale[..., None]) - 128.0
    return q.astype(jnp.int8), scale, lo


def _dequantize_rows(q: jax.Array, scale: jax.Array, shift: jax.Array,
                     dtype) -> jax.Array:
    """Invert `_quantize_rows`: [B, T, H, Dh] int8 + [B, T, H] -> dtype."""
    return ((q.astype(jnp.float32) + 128.0) * scale[..., None]
            + shift[..., None]).astype(dtype)


def _qkv(p: Dict, normed: jax.Array, cfg: TransformerConfig):
    b, s, _ = normed.shape
    h, hd = cfg.num_attention_heads, cfg.head_dim
    return (dense(p["q"], normed).reshape(b, s, h, hd),
            dense(p["k"], normed).reshape(b, s, h, hd),
            dense(p["v"], normed).reshape(b, s, h, hd))


def _attend(q: jax.Array, k: jax.Array, v: jax.Array, keep: jax.Array,
            cfg: TransformerConfig) -> jax.Array:
    """Masked attention of q [B,S,H,Dh] over k/v [B,T,H,Dh]; `keep`
    [S, T] marks key positions each query may attend to."""
    b, s, h, hd = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hd))
    scores = jnp.where(keep[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return ctx.reshape(b, s, h * hd)


def _attend_width(bcache: Cache, read_len: Optional[int]) -> int:
    """Static attend-window width: the full cache, truncated to the
    bucketed `read_len` when one is bound — THE window formula, shared
    by the XLA read path and the Pallas kernel route so they can never
    attend different windows."""
    t_max = bcache["k"].shape[1]
    return t_max if read_len is None else min(read_len, t_max)


def _cache_write_quantized(bcache: Cache, k_new: jax.Array,
                           v_new: jax.Array, start) -> Cache:
    """Quantize the new K/V rows and write them (plus their per-(position,
    head) scale/shift rows) at `start` — the single int8 write path,
    shared by the XLA read path and the fused Pallas decode kernel."""
    bcache = dict(bcache)
    for t, new in (("k", k_new), ("v", v_new)):
        qv, scale, shift = _quantize_rows(new)
        bcache[t] = jax.lax.dynamic_update_slice(bcache[t], qv, start)
        bcache[f"{t}_scale"] = jax.lax.dynamic_update_slice(
            bcache[f"{t}_scale"], scale, start[:3])
        bcache[f"{t}_shift"] = jax.lax.dynamic_update_slice(
            bcache[f"{t}_shift"], shift, start[:3])
    return bcache


# per-tensor int8 window bytes the kernel may stage in VMEM: the window
# is loaded whole per batch cell (grid is (batch,)), so huge unbucketed
# windows must stay on the XLA path instead of dying in Mosaic lowering.
# 1 MB = the measured-good regime (width 1024 at 16x64 heads ran on
# chip; width 4096 hit a 36 MB scoped-vmem stack vs the 16 MB limit)
_INT8_KERNEL_VMEM_CAP = 1 << 20


def _int8_kernel_env() -> int:
    """Resolve the PIPEEDGE_INT8_DECODE_ATTEND opt-in (empty/0/false/no/off
    all mean off; '2' forces the batch-as-sublane kernel variant, 'auto'
    applies the measured routing policy — kernel v2 only for attend
    windows <= 256 where it beat XLA in three separate chip sessions,
    XLA everywhere else (docs/DECODE.md) — and any other truthy value
    forces variant 1). Callers resolve this ONCE at pipeline
    construction and bind the answer into the stage programs — compiled
    decode steps are cached per shape/read_len, so a trace-time env read
    would silently ignore later toggles for already-compiled shapes
    (round-4 advice)."""
    import os
    env = (os.getenv("PIPEEDGE_INT8_DECODE_ATTEND") or "").strip().lower()
    if not env or env in ("0", "false", "no", "off"):
        return 0
    if env == "auto":
        return 3
    return 2 if env == "2" else 1


def _resolve_int8_optin(override=None) -> int:
    """Construction-time resolution of the int8 decode-attend routing
    (the promotion seam, ISSUE 19): an explicit override — constructor
    arg `int8_decode_attend` / `--int8-decode-attend` — wins, then the
    PIPEEDGE_INT8_DECODE_ATTEND env (including an explicit '0' off),
    then the `QuantizeCompute` compute-path config: enabling int8
    compute promotes the decode attend under the measured 'auto' width
    policy (kernel v2 at attend windows <= 256, XLA above). Idempotent
    on already-resolved ints."""
    if override is not None:
        if isinstance(override, str):
            s = override.strip().lower()
            if s == "auto":
                return 3
            if not s or s in ("0", "false", "no", "off"):
                return 0
            return 2 if s == "2" else 1
        return int(override)
    import os
    if os.getenv("PIPEEDGE_INT8_DECODE_ATTEND") is not None:
        return _int8_kernel_env()
    from ..models.layers import quantize_compute
    if quantize_compute().enabled:
        return 3
    return 0


# the measured crossover: kernel v2 beat XLA at attend widths <= 256 in
# every chip session (3/3); XLA won at 1024 in every session. 'auto'
# routes the kernel only below this width.
_INT8_AUTO_MAX_WIDTH = 256


def _use_int8_decode_kernel(bcache: Cache, s: int, cfg: TransformerConfig,
                            width: int, optin: int, batch: int = 1) \
        -> Optional[Tuple[bool, int]]:
    """Route the classic int8 single-token decode step through the fused
    Pallas kernel (ops/decode_attention.py): MHA only (kv_heads == query
    heads), no sliding window, attend window small enough for VMEM —
    GQA/windowed/span/huge-window cases stay on the XLA
    dequantize-then-attend path. Static (trace-time) decision.

    Returns None (use the XLA path) or (interpret, variant): interpret
    True forces interpret mode on a non-TPU backend (tests); variant 1
    is the per-cell grid, 2 the batch-as-sublane grid. `optin` is the
    construction-time resolution of PIPEEDGE_INT8_DECODE_ATTEND
    (`_int8_kernel_env`): an isolated chip microbench measured variant 1
    at parity-to-slower vs XLA's dequantize-then-attend (docs/DECODE.md),
    so the default stays on the XLA path; the kernels are kept,
    exactness-tested, as the experimental base for the fusion."""
    if not optin:
        return None
    if s != 1 or "k_scale" not in bcache:
        return None
    if cfg.kv_heads != cfg.num_attention_heads or cfg.sliding_window:
        return None
    if width * cfg.kv_heads * cfg.head_dim > _INT8_KERNEL_VMEM_CAP:
        return None
    from ..ops.decode_attention import (int8_decode_attention_supported,
                                        int8_v2_fits)
    variant = int(optin)
    if variant == 3:     # 'auto': the measured width-crossover policy
        if width > _INT8_AUTO_MAX_WIDTH or not int8_v2_fits(
                width, batch, cfg.kv_heads, cfg.head_dim):
            return None  # XLA wins at wide windows (3/3 chip sessions)
        variant = 2
    elif variant == 2 and not int8_v2_fits(width, batch, cfg.kv_heads,
                                           cfg.head_dim):
        variant = 1      # v2's whole-batch block can't fit VMEM here
    return (not int8_decode_attention_supported(), variant)


def _cache_update_and_read(bcache: Cache, k_new: jax.Array, v_new: jax.Array,
                           pos, prefill: bool, s: int, dtype,
                           read_len: Optional[int] = None) \
        -> Tuple[jax.Array, jax.Array, jax.Array, Cache]:
    """Write the new K/V rows at [pos, pos+S) and return (k, v, keep, cache)
    for attention over the cache window.

    `read_len` (STATIC) truncates the attend window to cache positions
    [0, read_len): the caller guarantees pos < read_len, and positions
    beyond it were fully masked anyway (their softmax columns are exact
    zeros), so truncation is numerically identical while the attend
    matmul and (for int8 caches) the dequantize shrink from max_len to
    read_len — the bucketed decode-step optimization
    (DecodePipeline::attend_bucket)."""
    width = _attend_width(bcache, read_len)
    quantized = "k_scale" in bcache
    start = (0, 0, 0, 0) if prefill else (0, pos, 0, 0)
    if quantized:
        bcache = _cache_write_quantized(bcache, k_new, v_new, start)
        # dequantize only the attended window
        k = _dequantize_rows(bcache["k"][:, :width],
                             bcache["k_scale"][:, :width],
                             bcache["k_shift"][:, :width], dtype)
        v = _dequantize_rows(bcache["v"][:, :width],
                             bcache["v_scale"][:, :width],
                             bcache["v_shift"][:, :width], dtype)
        # the freshly computed rows are in hand — attend over them exactly;
        # quantization error applies only to genuinely cached positions
        k = jax.lax.dynamic_update_slice(k, k_new.astype(dtype), start)
        v = jax.lax.dynamic_update_slice(v, v_new.astype(dtype), start)
    else:
        bcache = dict(bcache)   # don't mutate the caller's dict
        for t, new in (("k", k_new), ("v", v_new)):
            bcache[t] = jax.lax.dynamic_update_slice(
                bcache[t], new.astype(bcache[t].dtype), start)
        k = bcache["k"][:, :width].astype(dtype)
        v = bcache["v"][:, :width].astype(dtype)
    if prefill:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (s, width), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (s, width), 1)
        keep = k_pos <= q_pos          # causal within the prompt
    else:
        # s == 1: the classic decode step (attend [0, pos]); s > 1: a
        # SPAN step (speculative-decoding verify) — query i sits at
        # absolute position pos + i and attends [0, pos + i], causal
        # within the span exactly like prefill but offset by pos
        q_pos = pos + jax.lax.broadcasted_iota(jnp.int32, (s, width), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (s, width), 1)
        keep = k_pos <= q_pos
    return k, v, keep, bcache


def _block_tail(p: Dict, x: jax.Array, ctx: jax.Array,
                cfg: TransformerConfig, ffn_delta=None) -> jax.Array:
    """Post-attention half of a GPT-2 block (output proj + residual, FFN +
    residual) — shared by the cached decode step, the sp prefill, and the
    ep decode step. `ffn_delta(p, normed) -> delta` overrides the FFN
    (expert-parallel execution plugs in the ep-sharded routed FFN)."""
    x = dense(p["attn_out"], ctx) + x
    normed = layer_norm(p["ln_after"], x, cfg.layer_norm_eps)
    if ffn_delta is not None:
        return x + ffn_delta(p, normed)
    if cfg.n_experts:
        # Capacity routing is NOT causal: a full-sequence forward lets
        # tokens compete for expert slots across the whole sequence, which
        # a cached decode step (routing only the current tokens) cannot
        # reproduce. With capacity_factor >= n_experts (no drops) routing
        # is a pure per-token gate and decode matches the forward exactly;
        # capacity-bounded models route each step's token set on its own.
        from .expert import moe_ffn_delta
        return x + moe_ffn_delta(p["moe"], normed, cfg.n_experts,
                                 cfg.capacity_factor, act=gelu_new)
    return dense(p["mlp_down"], gelu_new(dense(p["mlp_up"], normed))) + x


def _attention_core(p: Dict, x: jax.Array, bcache: Cache, pos,
                    cfg: TransformerConfig, prefill: bool,
                    read_len: Optional[int] = None,
                    int8_optin: int = 0) \
        -> Tuple[jax.Array, Cache]:
    """ln + qkv + cache update + masked attend: the cached attention half
    shared by the plain and expert-parallel decode steps. `int8_optin` is
    the construction-time PIPEEDGE_INT8_DECODE_ATTEND resolution (bound
    into the stage programs by _make_stage_run): 0 off, 1/2 = forced
    kernel variant, 3 = 'auto' (the measured width-crossover policy —
    see _use_int8_decode_kernel)."""
    normed = layer_norm(p["ln_before"], x, cfg.layer_norm_eps)
    q, k_new, v_new = _qkv(p, normed, cfg)
    w = _attend_width(bcache, read_len) if "k" in bcache else 0
    route = (None if prefill
             else _use_int8_decode_kernel(bcache, x.shape[1], cfg, w,
                                          int8_optin, batch=x.shape[0]))
    if route is not None:
        from ..ops.decode_attention import int8_decode_attention
        interpret, variant = route
        bcache = _cache_write_quantized(bcache, k_new, v_new,
                                        (0, pos, 0, 0))
        ctx = int8_decode_attention(
            q, bcache["k"][:, :w], bcache["k_scale"][:, :w],
            bcache["k_shift"][:, :w], bcache["v"][:, :w],
            bcache["v_scale"][:, :w], bcache["v_shift"][:, :w],
            k_new, v_new, pos, interpret=interpret, variant=variant)
        return ctx, bcache
    k, v, keep, bcache = _cache_update_and_read(
        bcache, k_new, v_new, pos, prefill, x.shape[1], q.dtype,
        read_len=read_len)
    return _attend(q, k, v, keep, cfg), bcache


def _block_step(p: Dict, x: jax.Array, bcache: Cache, pos,
                cfg: TransformerConfig, prefill: bool,
                read_len: Optional[int] = None,
                int8_optin: int = 0) -> Tuple[jax.Array, Cache]:
    """One GPT-2 block over current token(s) with cache read/update.

    Prefill: x is the full prompt [B, S, D] written at positions [0, S);
    decode: x is one token [B, 1, D] written at position `pos`. `bcache`
    is this block's cache slice {k, v[, *_scale, *_shift]}. `read_len`:
    static attend-window truncation (see _cache_update_and_read)."""
    ctx, bcache = _attention_core(p, x, bcache, pos, cfg, prefill,
                                  read_len=read_len, int8_optin=int8_optin)
    return _block_tail(p, x, ctx, cfg), bcache


def _block_step_tp(p: Dict, x: jax.Array, bcache: Cache, pos,
                   cfg: TransformerConfig, prefill: bool,
                   axis: str, act=gelu_new, ffn_delta=None,
                   read_len: Optional[int] = None) -> Tuple[jax.Array, Cache]:
    """Megatron tensor-parallel block step under `shard_map`: the shared
    projection/psum/MLP body from parallel/tensor.py with the attention
    core swapped for a cache-attend over the head-sharded KV cache.
    `ffn_delta` replaces the dense MLP (the tp x ep MoE composition);
    `read_len` is the static bucketed attend window (the position axis is
    unsharded, so truncation is per-shard local)."""
    from .tensor import _tp_block_local

    new_cache = {}

    def cache_attend(q, k_new, v_new):
        k, v, keep, bc = _cache_update_and_read(
            bcache, k_new, v_new, pos, prefill, x.shape[1], q.dtype,
            read_len=read_len)
        new_cache.update(bc)
        return _attend(q, k, v, keep, cfg)      # [b, s, h_local * hd]

    y = _tp_block_local(p, x, cfg, axis, act=act,
                        qkv_to_ctx=cache_attend, ffn_delta=ffn_delta)
    return y, new_cache


def single_token_embed(pe: Dict, tok: jax.Array, pos) -> jax.Array:
    """Embed one decode-step token [B] at traced position `pos` ->
    [B, 1, D]: wte row + dynamic-sliced wpe row. THE single-token
    embedding rule — shared by the host stage runner and the SPMD wave
    decoder so they cannot diverge."""
    wpe = jax.lax.dynamic_slice_in_dim(pe["wpe"], pos, 1)
    return jnp.take(pe["wte"], tok.reshape(-1), axis=0)[:, None] + wpe[None]


def span_embed(pe: Dict, tok: jax.Array, pos) -> jax.Array:
    """Embed a K-token span [B, K] at positions [pos, pos+K) ->
    [B, K, D] (the speculative-decoding verify step's embedding;
    K is static, `pos` traced)."""
    wpe = jax.lax.dynamic_slice_in_dim(pe["wpe"], pos, tok.shape[1])
    return jnp.take(pe["wte"], tok, axis=0) + wpe[None]


def stage_blocks(params: Dict) -> jax.Array:
    """The stacked blocks pytree of a decode stage (block-aligned shard)."""
    blocks = params.get("blocks")
    if blocks is None:
        raise ValueError("decode stages must contain full blocks "
                         "(block-aligned partition)")
    if isinstance(blocks, (tuple, list)):  # unrolled layout -> restack
        blocks = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return blocks


def attend_bucket(pos_next: int, max_len: int, floor: int = 64) -> int:
    """Static attend-window size for a decode step with `pos_next` valid
    cache rows: the smallest power-of-2 >= pos_next (>= floor), capped at
    max_len. Powers of two bound the compiled-variant count to
    log2(max_len/floor) + 1 while the attend matmul and int8 dequant
    track the LIVE cache length instead of max_len — the longer the
    max_len headroom, the bigger the decode-step saving."""
    if pos_next > max_len:
        raise ValueError(f"pos_next {pos_next} exceeds max_len {max_len}")
    b = max(1, floor)
    while b < pos_next:
        b *= 2
    return min(b, max_len)


def _run_blocks(blocks, x, cache: Cache, pos, cfg: TransformerConfig,
                prefill: bool, block_fn=_block_step) -> Tuple[jax.Array, Cache]:
    def body(carry, xs):
        bp, bc = xs
        y, bc = block_fn(bp, carry, bc, pos, cfg, prefill)
        return y, bc

    return jax.lax.scan(body, x, (blocks, cache))


def make_stage_fns(family, cfg: TransformerConfig, shard_config: ShardConfig,
                   int8_optin=None):
    """(prefill_fn, decode_fn) for one block-aligned pipeline stage.

    prefill_fn(params, data, cache)        -> (out, cache)   data: ids|hidden
    decode_fn(params, data, cache, pos)    -> (out, cache)   data: ids|hidden

    First stage embeds token ids (decode positions offset by `pos`); last
    stage applies the final LN + LM head and returns per-token logits.
    `int8_optin` is the resolved int8 decode-attend routing
    (`_resolve_int8_optin`; None re-resolves from env/config).
    """
    run = _make_stage_run(family, cfg, shard_config, int8_optin=int8_optin)
    prefill_fn = jax.jit(partial(run, pos=0, prefill=True))
    # read_len is STATIC: each attend-window bucket compiles its own
    # decode-step program (a handful of power-of-2 variants, the same
    # compile-per-discrete-value pattern as the quantized edge bitwidths)
    decode_fn = jax.jit(partial(run, prefill=False),
                        static_argnames=("read_len",))
    return prefill_fn, decode_fn


def _make_stage_run(family, cfg: TransformerConfig,
                    shard_config: ShardConfig, block_fn=None,
                    finalize_fn=None, embed_fn=None, int8_optin=None):
    plan = plan_shard(shard_config)
    if plan.head is not None or plan.tail is not None:
        raise ValueError("decode requires a block-aligned partition "
                         f"(layers [{shard_config.layer_start}, "
                         f"{shard_config.layer_end}] cut mid-block)")
    if block_fn is None:
        # family-dispatched cached block (llama supplies RoPE/GQA/SwiGLU);
        # the default is the GPT-2-shaped step, with the int8-kernel
        # opt-in resolved HERE — at stage-program construction
        # (DecodePipeline.__init__) — so toggling the env var after
        # programs compile cannot leave stale shapes on the old setting
        block_fn = getattr(family, "cached_block_step", None)
        if block_fn is None:
            block_fn = partial(_block_step,
                               int8_optin=_resolve_int8_optin(int8_optin))

    def run(params, data, cache, pos, prefill, read_len=None):
        if shard_config.is_first:
            if embed_fn is not None:
                data = embed_fn(params["embeddings"], data)
            elif prefill:
                data = family.embed(params["embeddings"], data, cfg)
            elif data.ndim == 2 and data.shape[1] > 1:
                # span step (speculative verify): K tokens at [pos, pos+K)
                tok_embed = getattr(family, "span_embed", None) or span_embed
                data = tok_embed(params["embeddings"], data, pos)
            else:
                tok_embed = getattr(family, "decode_embed", None) \
                    or single_token_embed
                data = tok_embed(params["embeddings"], data, pos)
        # bind the static attend window only when bucketing is active —
        # the ep block step is the one variant without the kwarg, and its
        # path never binds a bucket (DecodePipeline._bucketed)
        bf = block_fn if read_len is None \
            else partial(block_fn, read_len=read_len)
        data, cache = _run_blocks(stage_blocks(params), data, cache, pos,
                                  cfg, prefill, block_fn=bf)
        if shard_config.is_last:
            data = (finalize_fn or family.finalize)(params["final"], data,
                                                    cfg)
        return data, cache

    return run


def _tp_shards_head(cfg: TransformerConfig, n: int) -> bool:
    """Vocab-shard the LM head when the tp degree divides the vocab size —
    at decode the head matmul is a third of GPT-2's per-token FLOPs, so
    leaving it replicated would cap the tp speedup around 3x. A
    non-divisible combination (e.g. gpt2's 50257 at tp=2/4/8) falls back
    to a replicated head."""
    return cfg.vocab_size > 0 and n > 1 and cfg.vocab_size % n == 0


def tp_param_specs(params: Dict, cfg: TransformerConfig, n: int,
                   axis: str = "tp"):
    """Partition-spec pytree for one decode stage's params under Megatron
    TP (degree `n`): blocks per the family spec table (leading block axis
    replicated), embeddings replicated, LM head vocab-sharded when
    divisible (`_tp_shards_head`)."""
    from jax.sharding import PartitionSpec as P

    from .tensor import _rename_axis, family_tp_plan
    table, _ = family_tp_plan(cfg)
    table = _rename_axis(table, axis)
    specs = {k: jax.tree_util.tree_map(lambda _: P(), v)
             for k, v in params.items() if k != "blocks"}
    specs["blocks"] = jax.tree_util.tree_map(
        lambda _, s: P(*((None,) + tuple(s))), params["blocks"], table)
    if "final" in params and "head" in params["final"] \
            and _tp_shards_head(cfg, n):
        specs["final"]["head"] = {"w": P(None, axis), "b": P(axis)}
    return specs


def tp_vocab_head_finalize(pf: Dict, hidden, cfg: TransformerConfig,
                           axis: str, norm_fn):
    """Vocab-sharded LM head under tp — THE shared finalize for tp decode
    stages: `norm_fn` (layer_norm for GPT-2, rms_norm for llama) runs
    replicated, the head matmul produces local logit slices, one tiled
    all_gather restores the full [B, S, V]."""
    hidden = norm_fn(pf["ln"], hidden, cfg.layer_norm_eps)
    y = jnp.dot(hidden, pf["head"]["w"].astype(hidden.dtype),
                preferred_element_type=jnp.float32) + pf["head"]["b"]
    return jax.lax.all_gather(y.astype(hidden.dtype), axis,
                              axis=y.ndim - 1, tiled=True)


def tp_cache_specs(cache: Cache, axis: str = "tp"):
    """Head-shard the cache leaves: axis 3 of the K/V buffers
    [L, B, T, H, Dh] AND of the per-head scale/shift rows [L, B, T, H]
    (the head axis on the scales is what lets int8 caches compose with
    tp — each device quantizes/dequantizes its own head slice)."""
    from jax.sharding import PartitionSpec as P
    return {k: P(*([None, None, None, axis]
                   + [None] * (v.ndim - 4))) for k, v in cache.items()}


def make_tp_stage_fns(family, cfg: TransformerConfig,
                      shard_config: ShardConfig, mesh, params: Dict,
                      axis: str = "tp", cache_bits: int = 0):
    """Tensor-parallel variant of `make_stage_fns`: the stage executes under
    `shard_map` over `axis` with head-sharded KV cache and the 2-psum
    Megatron block body — decode-step latency scales with the tp degree.
    `params` (stacked-blocks layout) supplies the pytree structure for the
    partition specs; `cache_bits=8` composes int8 caches with tp (the
    per-head scale rows shard over `axis` with the K/V buffers)."""
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    if cfg.num_attention_heads % n or cfg.kv_heads % n:
        raise ValueError(f"tp={n} requires head count "
                         f"({cfg.num_attention_heads}) and kv head count "
                         f"({cfg.kv_heads}) divisible by tp")
    if cfg.n_experts:
        raise NotImplementedError(
            "tensor-parallel decode does not cover MoE blocks (experts "
            "shard over 'ep', not 'tp') — use make_tp_ep_stage_fns / "
            "DecodePipeline(tp_ep_mesh=...) for the tp x ep composition")
    fam_tp_step = getattr(family, "tp_cached_block_step", None)
    if fam_tp_step is None \
            and getattr(family, "cached_block_step", None) is not None:
        raise NotImplementedError(
            f"tensor-parallel decode pairs the default (GPT-2-shaped) "
            f"cached step with the Megatron body; the {family.name} "
            "family supplies a custom cached block step but no tp variant "
            "(forward TP — make_tp_block_fn / --spmd-tp — does cover it)")

    fam_tp_fin = getattr(family, "tp_finalize", None)
    fin = None
    if _tp_shards_head(cfg, n):
        fin = partial(fam_tp_fin, axis=axis) if fam_tp_fin \
            else partial(tp_vocab_head_finalize, axis=axis,
                         norm_fn=layer_norm)
    run = _make_stage_run(family, cfg, shard_config,
                          block_fn=partial(fam_tp_step or _block_step_tp,
                                           axis=axis),
                          finalize_fn=fin)
    p_specs = tp_param_specs(params, cfg, n, axis)
    c_specs = tp_cache_specs(init_cache(cfg, 1, 1, 1,
                                        cache_bits=cache_bits), axis)

    prefill_fn = jax.jit(jax_compat.shard_map(
        partial(run, pos=0, prefill=True), mesh=mesh,
        in_specs=(p_specs, P(), c_specs), out_specs=(P(), c_specs)))

    # the bucketed attend window is bound into the shard_map closure per
    # static read_len value — jit re-traces per bucket, same
    # compile-per-discrete-value pattern as the plain path
    @partial(jax.jit, static_argnames=("read_len",))
    def decode_fn(params, data, cache, pos, read_len=None):
        return jax_compat.shard_map(
            partial(run, prefill=False, read_len=read_len), mesh=mesh,
            in_specs=(p_specs, P(), c_specs, P()),
            out_specs=(P(), c_specs))(
                params, data, cache, pos)

    # p_specs is returned so callers place params with the SAME specs the
    # program compiled against (drift would silently reshard every call)
    return prefill_fn, decode_fn, p_specs


def validate_partition(partition: Sequence[Tuple[int, int]],
                       total: int) -> None:
    """Require `partition` to contiguously cover [1, total] in order."""
    expect = 1
    for l, r in partition:
        if l != expect:
            raise ValueError(f"partition {list(partition)} does not "
                             f"contiguously cover [1, {total}]")
        expect = r + 1
    if expect != total + 1:
        raise ValueError(f"partition {list(partition)} does not "
                         f"contiguously cover [1, {total}]")


def round_partition_to_blocks(partition: Sequence[Tuple[int, int]],
                              total: int) -> List[Tuple[int, int]]:
    """Round a sublayer-granular partition (e.g. from the native
    sched-pipeline scheduler, which cuts at quarter-block granularity) to
    the block-aligned cuts decoding requires: each interior cut moves to
    the nearest block boundary (multiple of 4; a cut exactly halfway
    between boundaries rounds UP — an explicit tie rule, where Python's
    round() would banker's-round to the even block), empty stages are
    dropped. Coverage of [1, total] is preserved."""
    if total % 4:
        raise ValueError(f"total sublayers {total} not a multiple of 4")
    cuts = [r for (_, r) in partition[:-1]]
    rounded = sorted({min(total - 4, max(4, int(c / 4 + 0.5) * 4))
                      for c in cuts})
    bounds = [0] + [c for c in rounded if c < total] + [total]
    return [(bounds[i] + 1, bounds[i + 1]) for i in range(len(bounds) - 1)
            if bounds[i + 1] > bounds[i]]


def validate_capacity(cfg: TransformerConfig, max_len: int,
                      prompt_len: int = 0, new_tokens: int = 0) -> None:
    """Reject cache/position overflows up front: dynamic_update_slice
    clamps out-of-range starts, so an overflow would silently corrupt the
    last cache row instead of erroring."""
    if cfg.max_position_embeddings and max_len > cfg.max_position_embeddings:
        raise ValueError(f"max_len {max_len} exceeds the model's "
                         f"{cfg.max_position_embeddings} positions")
    if prompt_len + new_tokens > max_len:
        raise ValueError(f"prompt {prompt_len} + {new_tokens} new tokens "
                         f"exceeds max_len {max_len}")


def _repeat_batch(tree, k: int):
    """Tile the batch axis (axis 1 of [L, B, ...] cache leaves) k times:
    beam b of batch i occupies row i*k + b."""
    return jax.tree_util.tree_map(
        lambda x: jnp.repeat(x, k, axis=1), tree)


def _gather_batch(tree, rows: jax.Array):
    """Reorder the batch axis of cache leaves by `rows` [B*k]."""
    return jax.tree_util.tree_map(lambda x: jnp.take(x, rows, axis=1), tree)


@partial(jax.jit, static_argnames=("temperature", "top_k"))
def _pick_token(logits, rng, temperature: float, top_k: int):
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.float32(temperature)
    if top_k > 0:
        # keep EXACTLY top_k candidates: scatter the top_k values back by
        # index. A threshold compare (scaled >= kth) admits every logit
        # tied with the k-th value, growing the candidate set on ties.
        vals, idx = jax.lax.top_k(scaled, top_k)
        rows = jnp.arange(scaled.shape[0])[:, None]
        scaled = jnp.full_like(scaled, -jnp.inf).at[rows, idx].set(vals)
    return jax.random.categorical(rng, scaled, axis=-1)


def make_token_picker(temperature: float = 0.0, top_k: int = 0):
    """`pick(logits [B, V], rng) -> tokens [B]`: greedy argmax at
    temperature 0, else categorical sampling over logits/temperature,
    optionally truncated to exactly the `top_k` most likely (ties at the
    k-th value broken by index order, matching `jax.lax.top_k`).

    Binds a module-level jitted function with static (temperature, top_k),
    so repeated generate() calls with the same settings hit the jit cache
    instead of retracing a fresh closure."""
    return partial(_pick_token, temperature=float(temperature),
                   top_k=int(top_k))


def make_ep_stage_fns(family, cfg: TransformerConfig,
                      shard_config: ShardConfig, mesh, params: Dict,
                      axis: str = "ep", cache_bits: int = 0):
    """Expert-parallel variant of `make_stage_fns` for MoE stages: the
    routed FFN's experts shard over `axis` (each device computes its local
    experts' tokens, one psum combines — parallel/expert.py's layout inside
    the decode step). Attention and the KV cache are replicated across the
    ep axis (experts hold the dominant parameter mass in an MoE decoder).
    Returns (prefill_fn, decode_fn, param_specs) — place params with the
    returned specs."""
    from jax.sharding import PartitionSpec as P

    from .expert import ep_ffn_delta

    if not cfg.n_experts:
        raise ValueError("make_ep_stage_fns requires an MoE config "
                         "(cfg.n_experts > 0)")
    n = mesh.shape[axis]
    if cfg.n_experts % n:
        raise ValueError(f"ep={n} must divide n_experts ({cfg.n_experts})")

    def ffn_delta(p, normed):
        return ep_ffn_delta(p["moe"], normed, cfg.n_experts,
                            cfg.capacity_factor, axis, act=gelu_new)

    # kernel opt-in resolved at stage-fn construction, same rule as
    # _make_stage_run (the int8-cache MHA ep composition routes too)
    int8_optin = _int8_kernel_env()

    def block_step_ep(p, x, bcache, pos, cfg_, prefill):
        ctx, bcache = _attention_core(p, x, bcache, pos, cfg_, prefill,
                                      int8_optin=int8_optin)
        return _block_tail(p, x, ctx, cfg_, ffn_delta=ffn_delta), bcache

    run = _make_stage_run(family, cfg, shard_config, block_fn=block_step_ep)
    # experts shard on their leading axis (under the stacked block axis);
    # everything else — attention weights, cache (incl. int8 scale rows:
    # replicated cache means identical quantization on every device) —
    # replicated
    p_specs = jax.tree_util.tree_map(lambda _: P(), params)
    p_specs["blocks"]["moe"]["experts"] = jax.tree_util.tree_map(
        lambda _: P(None, axis), params["blocks"]["moe"]["experts"])
    c_specs = {k: P() for k in init_cache(cfg, 1, 1, 1,
                                          cache_bits=cache_bits)}

    prefill_fn = jax.jit(jax_compat.shard_map(
        partial(run, pos=0, prefill=True), mesh=mesh,
        in_specs=(p_specs, P(), c_specs), out_specs=(P(), c_specs)))
    decode_fn = jax.jit(jax_compat.shard_map(
        partial(run, prefill=False), mesh=mesh,
        in_specs=(p_specs, P(), c_specs, P()), out_specs=(P(), c_specs)))
    return prefill_fn, decode_fn, p_specs


def make_tp_ep_stage_fns(family, cfg: TransformerConfig,
                         shard_config: ShardConfig, mesh, params: Dict,
                         tp_axis: str = "tp", ep_axis: str = "ep"):
    """The MoE serving composition: attention tensor-parallel over
    `tp_axis` AND experts expert-parallel over `ep_axis`, in ONE mesh and
    one shard_map program per stage.

    This is the layout a real MoE serving stack needs — attention (and its
    KV cache) head-sharded so decode-step latency scales with tp, experts
    sharded so the dominant parameter mass splits across ep — and it is
    exact: attention psums over tp reproduce the dense result, routing
    sees the full (replicated) token set so top-1 capacity semantics are
    untouched, and the expert psum over ep adds exactly one nonzero term
    per token (parallel/expert.py). Cache rows shard over tp and
    replicate over ep; embeddings, router, and LM head stay replicated.

    Returns (prefill_fn, decode_fn, param_specs) — place params with the
    returned specs. int8 caches are excluded for the same per-device
    scale-row reason as plain tp decode."""
    from jax.sharding import PartitionSpec as P

    from .expert import ep_ffn_delta
    from .tensor import _rename_axis, family_tp_ep_plan

    if not cfg.n_experts:
        raise ValueError("make_tp_ep_stage_fns requires an MoE config "
                         "(cfg.n_experts > 0); use make_tp_stage_fns for "
                         "dense models")
    ntp, nep = mesh.shape[tp_axis], mesh.shape[ep_axis]
    if cfg.num_attention_heads % ntp:
        raise ValueError(f"tp={ntp} requires head count "
                         f"({cfg.num_attention_heads}) divisible by tp")
    if cfg.n_experts % nep:
        raise ValueError(f"ep={nep} must divide n_experts "
                         f"({cfg.n_experts})")
    # single family-dispatch point (tensor.py), like family_tp_plan for
    # dense TP: attention spec table + the family's FFN activation
    fam_specs, act = family_tp_ep_plan(cfg)

    def ffn_delta(p, normed):
        return ep_ffn_delta(p["moe"], normed, cfg.n_experts,
                            cfg.capacity_factor, ep_axis, act=act)

    # the tp decode block step, with the dense MLP swapped for the
    # ep-sharded routed FFN — one cache-attend implementation for both
    run = _make_stage_run(family, cfg, shard_config,
                          block_fn=partial(_block_step_tp, axis=tp_axis,
                                           act=act, ffn_delta=ffn_delta))

    # blocks: attention per the family's Megatron spec table over tp
    # (stacked block axis leading), router replicated, expert slabs over ep
    att_specs = _rename_axis(fam_specs, tp_axis)
    p_specs = {k: jax.tree_util.tree_map(lambda _: P(), v)
               for k, v in params.items() if k != "blocks"}
    bspecs = {}
    for k, v in params["blocks"].items():
        if k == "moe":
            bspecs[k] = {
                "router": jax.tree_util.tree_map(lambda _: P(None),
                                                 v["router"]),
                "experts": jax.tree_util.tree_map(
                    lambda _: P(None, ep_axis), v["experts"]),
            }
        else:
            bspecs[k] = jax.tree_util.tree_map(
                lambda _, s: P(*((None,) + tuple(s))), v, att_specs[k])
    p_specs["blocks"] = bspecs
    # same head-axis convention _fresh_caches places with (tp_cache_specs)
    c_specs = tp_cache_specs(init_cache(cfg, 1, 1, 1), tp_axis)

    prefill_fn = jax.jit(jax_compat.shard_map(
        partial(run, pos=0, prefill=True), mesh=mesh,
        in_specs=(p_specs, P(), c_specs), out_specs=(P(), c_specs)))
    decode_fn = jax.jit(jax_compat.shard_map(
        partial(run, prefill=False), mesh=mesh,
        in_specs=(p_specs, P(), c_specs, P()), out_specs=(P(), c_specs)))
    return prefill_fn, decode_fn, p_specs


def make_sp_prefill_fn(family, cfg: TransformerConfig,
                       shard_config: ShardConfig, mesh, axis: str = "sp",
                       sp_kind: str = "ring"):
    """Sequence-parallel prefill for decoding: the O(S^2) prompt pass —
    the long-context bottleneck — runs with activations sequence-sharded
    over `axis` and an exact causal attention core per block chosen by
    `sp_kind` (parallel/sequence.py::resolve_sp_core — 'ring' streams K/V
    chunks via ppermute with blockwise softmax and skips ring steps
    outside a sliding window, the long-context choice; 'ulysses'
    all-to-all reshards heads<->sequence with blockwise local attention
    and requires heads divisible by the sp degree). Sliding-window
    families (Mistral) bind cfg.sliding_window into the core, so sp
    prefill is windowed exactly like the non-sp path. Each block's K/V
    rows are all-gathered into the stage
    cache, which comes back replicated so the per-token decode steps run
    unchanged. Stage edges carry only the local sequence chunk.

    Requires a block-aligned stage and prompt length divisible by the sp
    degree. MoE stages are covered when routing is dropless
    (capacity_factor >= n_experts — then routing is a per-token gate and
    chunk-local execution is exact); capacity-bounded MoE refuses."""
    from jax.sharding import PartitionSpec as P

    from .sequence import resolve_sp_core

    if cfg.n_experts and cfg.capacity_factor < cfg.n_experts:
        # dropless MoE (capacity_factor >= n_experts) routes as a pure
        # per-token gate, so chunk-local routing is exact and the default
        # block path below covers it; a capacity-BOUNDED router competes
        # tokens for expert slots across the whole sequence, which
        # chunk-local capacity cannot reproduce
        raise NotImplementedError(
            "sequence-parallel prefill covers dropless MoE only "
            "(capacity_factor >= n_experts); capacity-bounded routing "
            "is sequence-global and would change drop semantics per chunk")
    fam_sp_block = getattr(family, "sp_prefill_block_step", None)
    if getattr(family, "position_dependent_attention", False) \
            and fam_sp_block is None:
        raise NotImplementedError(
            f"sequence-parallel prefill does not cover the {family.name} "
            "family (its attention is position-dependent — RoPE — and it "
            "supplies no sp_prefill_block_step hook to pre-rotate at "
            "global chunk positions)")
    n = mesh.shape[axis]
    # Mistral-style models bind their sliding window into the core: the
    # ring schedule then SKIPS K/V blocks wholly behind every local
    # query's window (sequence.py::ring_attention n_steps bound)
    core = resolve_sp_core(sp_kind, cfg.num_attention_heads, n,
                           window=cfg.sliding_window or None)

    def cache_gather(bcache, k_new, v_new):
        """All-gather this chunk's K/V rows into the (replicated) stage
        cache — shared by the default and family sp block steps."""
        bcache = dict(bcache)
        for t, new in (("k", k_new), ("v", v_new)):
            full = jax.lax.all_gather(new, axis, axis=1, tiled=True)
            bcache[t] = jax.lax.dynamic_update_slice(
                bcache[t], full.astype(bcache[t].dtype), (0, 0, 0, 0))
        return bcache

    if fam_sp_block is not None:
        def block_prefill(p, x, bcache, pos, cfg_, prefill):
            return fam_sp_block(p, x, bcache, cfg_, axis, core,
                                cache_gather)
    else:
        def block_prefill(p, x, bcache, pos, cfg_, prefill):
            """One block over the local chunk [B, S/n, D]: causal ring/
            Ulysses attention for the output, all-gathered K/V into the
            cache; the post-attention half is the shared _block_tail."""
            normed = layer_norm(p["ln_before"], x, cfg_.layer_norm_eps)
            q, k_new, v_new = _qkv(p, normed, cfg_)
            ctx = core(q, k_new, v_new, axis, causal=True)
            b, s_local, h, hd = q.shape
            x = _block_tail(p, x, ctx.reshape(b, s_local, h * hd), cfg_)
            return x, cache_gather(bcache, k_new, v_new)

    def sp_embed(pe, ids):
        """Embed this device's prompt chunk at its global positions
        (learned position table added only for families that have one —
        RoPE families carry positions in the attention rotation)."""
        idx = jax.lax.axis_index(axis)
        chunk = ids.shape[1] // n
        local = jax.lax.dynamic_slice_in_dim(ids, idx * chunk, chunk, 1)
        out = jnp.take(pe["wte"], local, axis=0)
        if "wpe" in pe:
            wpe = jax.lax.dynamic_slice_in_dim(pe["wpe"], idx * chunk, chunk)
            out = out + wpe[None]
        return out

    def sp_finalize(pf, hidden, cfg_):
        hidden = jax.lax.all_gather(hidden, axis, axis=1, tiled=True)
        return family.finalize(pf, hidden, cfg_)

    run = _make_stage_run(family, cfg, shard_config, block_fn=block_prefill,
                          finalize_fn=sp_finalize, embed_fn=sp_embed)
    edge_in = P() if shard_config.is_first else P(None, axis)
    edge_out = P() if shard_config.is_last else P(None, axis)
    return jax.jit(jax_compat.shard_map(
        partial(run, pos=0, prefill=True), mesh=mesh,
        in_specs=(P(), edge_in, P()), out_specs=(edge_out, P())))


def build_decode_pipeline(model_name: str,
                          partition: Optional[Sequence] = None,
                          max_len: int = 1024, dtype=jnp.float32,
                          cache_bits: int = 0, attend_floor: int = 64,
                          model_file: Optional[str] = None,
                          stage_params: Optional[Sequence] = None,
                          **pipe_kw) -> "DecodePipeline":
    """Registry-driven `DecodePipeline` construction — THE shared build
    path for the CLIs (tools/generate.py, tools/serve.py, bench_decode),
    so model lookup, per-stage weight loading, and the position-capacity
    clamp cannot drift between tools. `stage_params` supplies already-
    loaded per-stage pytrees (callers that also need them for other
    drivers); extra kwargs (mesh=/sp_mesh=/ep_mesh=/tp_ep_mesh=/devices=/
    int8_decode_attend=) pass through."""
    from ..models import registry
    cfg = registry.get_model_config(model_name)
    total = registry.get_model_layers(model_name)
    partition = list(partition) if partition else [(1, total)]
    if cfg.max_position_embeddings:
        max_len = min(max_len, cfg.max_position_embeddings)
    if stage_params is None:
        stage_params = [registry.module_shard_factory(
            model_name, model_file, l, r, stage=i, dtype=dtype,
            unroll=False)[1] for i, (l, r) in enumerate(partition)]
    family = registry.get_model_entry(model_name).family.FAMILY
    return DecodePipeline(family, cfg, partition, stage_params,
                          max_len=max_len, dtype=dtype,
                          cache_bits=cache_bits,
                          attend_floor=attend_floor, **pipe_kw)


class DecodePipeline:
    """Host-driven pipelined greedy decoding over block-aligned stages.

    `stage_params[i]` are forward-pipeline shard params (the same pytrees
    `module_shard_factory` builds); caches are per-stage. Decode steps are
    serial (autoregression), so batch is the throughput axis; stages
    partition the model across devices for capacity, exactly like the
    forward pipeline. `devices` optionally places each stage (device_put,
    mirroring the host pipeline driver).
    """

    def __init__(self, family, cfg: TransformerConfig,
                 partition: Sequence[Tuple[int, int]],
                 stage_params: Sequence[Dict], max_len: int,
                 devices: Optional[Sequence] = None, dtype=jnp.float32,
                 cache_bits: int = 0, mesh=None, tp_axis: str = "tp",
                 sp_mesh=None, sp_axis: str = "sp", sp_kind: str = "ring",
                 ep_mesh=None, ep_axis: str = "ep", tp_ep_mesh=None,
                 attend_floor: int = 64, int8_decode_attend=None):
        total = 4 * cfg.num_hidden_layers
        validate_partition(partition, total)
        validate_capacity(cfg, max_len)
        if mesh is not None and devices is not None:
            raise ValueError("pass either per-stage `devices` or a tp "
                             "`mesh`, not both")
        if sp_mesh is not None and (mesh is not None or cache_bits
                                    or devices is not None):
            raise ValueError("sp_mesh (sequence-parallel prefill) does not "
                             "compose with tp mesh/int8 cache/devices")
        if ep_mesh is not None and (mesh is not None or sp_mesh is not None
                                    or devices is not None):
            raise ValueError("ep_mesh (expert-parallel MoE decode) does not "
                             "compose with tp/sp meshes or devices")
        if tp_ep_mesh is not None and (mesh is not None or ep_mesh is not None
                                       or sp_mesh is not None or cache_bits
                                       or devices is not None):
            raise ValueError("tp_ep_mesh (tp x ep MoE decode) replaces the "
                             "single-axis meshes; it does not compose with "
                             "mesh/ep_mesh/sp_mesh, int8 cache, or devices")
        self.cfg = cfg
        self.max_len = max_len
        self.mesh, self.tp_axis = mesh, tp_axis
        self.tp_ep_mesh = tp_ep_mesh
        self.ep_mesh = ep_mesh
        # int8 decode-attend routing, resolved ONCE here (constructor
        # arg > env > QuantizeCompute promotion — `_resolve_int8_optin`)
        # and bound into the stage programs below; later env/config
        # toggles don't affect this pipeline (round-4 advice)
        optin = _resolve_int8_optin(int8_decode_attend)
        self.stages = []
        for i, (l, r) in enumerate(partition):
            sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
            params = dict(stage_params[i])
            # restack an unrolled block layout ONCE here, not per traced call
            params["blocks"] = stage_blocks(params)
            if tp_ep_mesh is not None:
                from jax.sharding import NamedSharding
                pre, dec, p_specs = make_tp_ep_stage_fns(
                    family, cfg, sc, tp_ep_mesh, params,
                    tp_axis=tp_axis, ep_axis=ep_axis)
                params = jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(
                        x, NamedSharding(tp_ep_mesh, s)), params, p_specs)
                n_blocks = (r - l + 1) // 4
                self.stages.append({"prefill": pre, "decode": dec,
                                    "params": params, "n_blocks": n_blocks,
                                    "device": None})
                continue
            sharded = ((make_tp_stage_fns, mesh, tp_axis)
                       if mesh is not None else
                       (make_ep_stage_fns, ep_mesh, ep_axis)
                       if ep_mesh is not None else None)
            if sharded is not None:
                from jax.sharding import NamedSharding
                maker, m, ax = sharded
                kw = ({"cache_bits": cache_bits}
                      if maker in (make_tp_stage_fns, make_ep_stage_fns)
                      else {})
                pre, dec, p_specs = maker(family, cfg, sc, m, params,
                                          axis=ax, **kw)
                params = jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(x, NamedSharding(m, s)),
                    params, p_specs)
            else:
                pre, dec = make_stage_fns(family, cfg, sc, int8_optin=optin)
                if sp_mesh is not None:
                    pre = make_sp_prefill_fn(family, cfg, sc, sp_mesh,
                                             axis=sp_axis, sp_kind=sp_kind)
                if devices is not None:
                    params = jax.device_put(params, devices[i])
            n_blocks = (r - l + 1) // 4
            self.stages.append({"prefill": pre, "decode": dec,
                                "params": params, "n_blocks": n_blocks,
                                "device": None if devices is None or
                                mesh is not None else devices[i]})
        self.dtype = dtype
        self.cache_bits = cache_bits
        # the value bound into the stage programs above, exposed for
        # introspection
        self.int8_decode_optin = optin
        self.sp_degree = sp_mesh.shape[sp_axis] if sp_mesh is not None else 1
        # bucketed decode-step attention rides the plain stage programs
        # AND the tp variant (static read_len arg; the tp shard_map
        # closure re-binds per bucket); the ep/tp x ep variants attend
        # over the full window — their signatures don't take the bucket
        self._bucketed = ep_mesh is None and tp_ep_mesh is None
        if attend_floor < 1:
            raise ValueError(f"attend_floor must be >= 1, got {attend_floor}")
        self.attend_floor = attend_floor

    def _read_len(self, pos: int, span: int = 1):
        """Static attend window for a decode/span step whose last query
        row sits at host-known pos + span - 1 (None when this pipeline's
        stage programs aren't bucketed)."""
        if not self._bucketed:
            return None
        return attend_bucket(pos + span, self.max_len, self.attend_floor)

    def _fresh_caches(self, batch: int) -> List[Cache]:
        caches = []
        cache_mesh = self.mesh if self.mesh is not None else self.tp_ep_mesh
        for st in self.stages:
            c = init_cache(self.cfg, st["n_blocks"], batch, self.max_len,
                           self.dtype, cache_bits=self.cache_bits)
            if cache_mesh is not None:
                from jax.sharding import NamedSharding
                # head axis over tp; replicated over ep when present
                specs = tp_cache_specs(c, self.tp_axis)
                c = {k: jax.device_put(v, NamedSharding(cache_mesh, specs[k]))
                     for k, v in c.items()}
            elif st["device"] is not None:
                c = jax.device_put(c, st["device"])
            caches.append(c)
        return caches

    def _decode_step(self, st, data, cache, pos: int, span: int = 1):
        """Dispatch one stage's decode program at host-known `pos`,
        binding the static attend bucket when this pipeline is bucketed
        (the batcher dispatches through here too). `span` > 1 runs the
        same program shape over a K-token span [pos, pos+K) — the
        speculative-decoding verify step."""
        rl = self._read_len(pos, span)
        if rl is None:
            return st["decode"](st["params"], data, cache, pos)
        return st["decode"](st["params"], data, cache, pos, read_len=rl)

    def _prefill(self, ids, prefill_ubatch: Optional[int] = None):
        """Run the prompt through all stages; returns (last-stage output,
        per-stage caches).

        `prefill_ubatch` splits the batch into chunks so prefill PIPELINES
        across stages: JAX dispatch is asynchronous, so stage i's program
        runs on chunk c+1 while stage i+1 processes chunk c — the standard
        fill/drain overlap, with per-chunk caches concatenated on the batch
        axis afterwards. (For capacity-bounded MoE models chunking changes
        the routed token set, like any batch-size change.)"""
        batch = ids.shape[0]

        def run_stages(data):
            caches = self._fresh_caches(data.shape[0])
            for i, st in enumerate(self.stages):
                if st["device"] is not None:
                    data = jax.device_put(data, st["device"])
                data, caches[i] = st["prefill"](st["params"], data,
                                                caches[i])
            return data, caches

        if prefill_ubatch is None or prefill_ubatch >= batch:
            return run_stages(ids)
        if prefill_ubatch <= 0:
            raise ValueError(f"prefill_ubatch must be positive, got "
                             f"{prefill_ubatch}")
        if batch % prefill_ubatch:
            raise ValueError(f"batch {batch} not divisible by "
                             f"prefill_ubatch {prefill_ubatch}")
        outs, chunk_caches = [], []
        for c0 in range(0, batch, prefill_ubatch):
            data, caches = run_stages(ids[c0:c0 + prefill_ubatch])
            outs.append(data)
            chunk_caches.append(caches)
        merged = [jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=1), *[cc[i] for cc in
                                                       chunk_caches])
            for i in range(len(self.stages))]
        return jnp.concatenate(outs, axis=0), merged

    def extend(self, tokens, caches, pos: int):
        """Run a K-token span [B, K] through every stage at cache offset
        `pos`: K/V rows [pos, pos+K) are written and span row i attends
        cache positions [0, pos+i] (causal within the span, full history
        before it). Returns (last-stage output [B, K, ...], caches).

        This is the speculative-decoding VERIFY primitive: one pipelined
        forward scores K proposed tokens instead of K serial decode
        steps. K is static per call site (one compiled program per
        distinct span length x attend bucket). With an int8 cache the
        in-span rows are attended unquantized (exactly like the current
        row of a plain decode step), so span scoring of K tokens is not
        bit-identical to K serial int8 steps — fp caches are exact."""
        tokens = jnp.asarray(tokens, jnp.int32)
        _, k = tokens.shape
        if pos + k > self.max_len:
            raise ValueError(f"span [{pos}, {pos + k}) exceeds max_len "
                             f"{self.max_len}")
        data = tokens
        for i, st in enumerate(self.stages):
            if st["device"] is not None:
                data = jax.device_put(data, st["device"])
            data, caches[i] = self._decode_step(st, data, caches[i], pos,
                                                span=k)
        return data, caches

    def precompute_prefix(self, prefix_ids) -> Dict:
        """Prefill a shared prompt PREFIX once, for reuse across requests
        (prompt caching): returns an opaque handle for `generate(...,
        prefix=)`. `prefix_ids` is [P] or [1, P]; the cached K/V rows are
        broadcast to each request batch at use. Exact for fp caches
        (suffix tokens attend prefix K/V exactly as a monolithic prefill
        would); with int8 caches the monolithic prefill attends its own
        prompt rows unquantized, so prefix reuse introduces the cached
        rows' quantization error — same caveat class as chunked
        prefill's routing note."""
        ids = jnp.asarray(prefix_ids, jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if ids.shape[0] != 1:
            raise ValueError("a shared prefix is one sequence; got batch "
                             f"{ids.shape[0]}")
        if ids.shape[1] % self.sp_degree:
            raise ValueError(f"prefix length {ids.shape[1]} not divisible "
                             f"by the sp prefill degree {self.sp_degree}")
        _, caches = self._prefill(ids)
        return {"caches": caches, "len": ids.shape[1],
                "sig": self._prefix_sig()}

    def _prefix_sig(self) -> Tuple:
        """Cache-compatibility signature stamped into prefix handles: a
        handle built by one pipeline is only valid on a pipeline whose
        per-stage cache layout (block split, max_len, quantization,
        dtype, KV geometry) matches — a mismatched handle would otherwise
        die deep inside jit with an opaque shape error or silently
        corrupt attend windows (round-4 advice)."""
        return ("decode-prefix-v1",
                tuple(st["n_blocks"] for st in self.stages),
                self.max_len, self.cache_bits,
                jax.dtypes.canonicalize_dtype(self.dtype).name,
                self.cfg.kv_heads, self.cfg.head_dim)

    def check_prefix(self, prefix: Dict) -> None:
        """Validate a `precompute_prefix` handle against THIS pipeline's
        cache layout (see `_prefix_sig`); raises ValueError with the two
        signatures on mismatch."""
        sig = prefix.get("sig") if isinstance(prefix, dict) else None
        if sig is None:
            raise ValueError(
                "prefix is not a precompute_prefix handle (no 'sig' "
                "stamp); build it with this pipeline's precompute_prefix")
        if sig != self._prefix_sig():
            raise ValueError(
                "prefix handle was built by an incompatible pipeline: "
                f"handle sig {sig} vs this pipeline {self._prefix_sig()} "
                "(fields: version, per-stage block counts, max_len, "
                "cache_bits, dtype, kv_heads, head_dim)")

    def generate(self, ids, new_tokens: int, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0, step_callback=None,
                 prefill_ubatch: Optional[int] = None,
                 prefix: Optional[Dict] = None):
        """Decode `new_tokens` continuations of prompt `ids` [B, S].

        `temperature=0` (default) is greedy argmax; otherwise tokens are
        sampled from logits/temperature, optionally truncated to the
        `top_k` most likely. `step_callback(step, tokens)` fires after each
        decode step (e.g. for monitoring heartbeats). `prefill_ubatch`
        pipelines the prompt pass across stages in batch chunks (see
        `_prefill`). `prefix` (from `precompute_prefix`) seeds the caches
        with a shared prompt prefix; `ids` is then each request's SUFFIX,
        run as one span at the prefix offset instead of a fresh prefill.
        Returns [B, S + new_tokens] token ids (the prefix is not
        included in the returned array)."""
        ids = jnp.asarray(ids, jnp.int32)
        batch, suffix_len = ids.shape
        prompt_len = suffix_len + (prefix["len"] if prefix else 0)
        if new_tokens <= 0:
            return ids
        validate_capacity(self.cfg, self.max_len, prompt_len, new_tokens)
        if prefix is None and prompt_len % self.sp_degree:
            raise ValueError(f"prompt length {prompt_len} not divisible by "
                             f"the sp prefill degree {self.sp_degree}")
        rng = jax.random.PRNGKey(seed)
        pick = make_token_picker(temperature, top_k)

        if prefix is not None:
            self.check_prefix(prefix)
            if prefill_ubatch is not None:
                raise ValueError("prefix reuse runs the suffix as one "
                                 "span; --prefill-ubatch does not apply")
            if suffix_len == 0:
                raise ValueError(
                    "prefix reuse needs a non-empty suffix (the span "
                    "produces the first token's logits); keep at least "
                    "the last prompt token out of the prefix")
            # broadcast the prefix's B=1 cache rows to this batch (the
            # beam-search batch-tiling rule), then run the whole suffix
            # as one span at the prefix offset
            caches = [_repeat_batch(c, batch) for c in prefix["caches"]]
            data, caches = self.extend(ids, caches, prefix["len"])
        else:
            data, caches = self._prefill(ids, prefill_ubatch)
        rng, sub = jax.random.split(rng)
        tokens = [pick(data[:, -1].astype(jnp.float32), sub)]
        if step_callback is not None:
            step_callback(0, tokens[-1])
        for step in range(1, new_tokens):
            pos = prompt_len + step - 1
            data = tokens[-1][:, None]
            for i, st in enumerate(self.stages):
                if st["device"] is not None:
                    data = jax.device_put(data, st["device"])
                data, caches[i] = self._decode_step(st, data, caches[i],
                                                    pos)
            rng, sub = jax.random.split(rng)
            tokens.append(pick(data[:, 0].astype(jnp.float32), sub))
            if step_callback is not None:
                step_callback(step, tokens[-1])
        return jnp.concatenate([ids, jnp.stack(tokens, axis=1)], axis=1)

    def generate_beam(self, ids, new_tokens: int, beams: int):
        """Beam-search decode: keep the `beams` highest log-probability
        continuations per prompt, return the best [B, S + new_tokens].

        Beams fold into the batch axis (row i*beams + b), so the compiled
        stage programs are reused unchanged at batch B*beams; on each
        reshuffle the per-stage caches are reordered along that axis to
        follow their surviving parent beams. Pure max-log-prob beam search:
        fixed horizon, no EOS/length normalization (all hypotheses share a
        length), matching the exhaustive oracle in tests/test_decode.py."""
        ids = jnp.asarray(ids, jnp.int32)
        batch, prompt_len = ids.shape
        if new_tokens <= 0:
            return ids
        if beams < 1:
            raise ValueError(f"beams must be >= 1, got {beams}")
        if beams == 1:
            # a width-1 beam IS greedy; skip the per-step cache gather
            return self.generate(ids, new_tokens)
        validate_capacity(self.cfg, self.max_len, prompt_len, new_tokens)
        if prompt_len % self.sp_degree:
            raise ValueError(f"prompt length {prompt_len} not divisible by "
                             f"the sp prefill degree {self.sp_degree}")

        # prefill once at batch B, then tile each prompt's cache per beam
        data, caches = self._prefill(ids)
        caches = [_repeat_batch(c, beams) for c in caches]

        logp = jax.nn.log_softmax(
            data[:, prompt_len - 1].astype(jnp.float32), axis=-1)  # [B, V]
        scores, first = jax.lax.top_k(logp, beams)        # [B, beams]
        history = first[..., None]                        # [B, beams, 1]

        for step in range(1, new_tokens):
            pos = prompt_len + step - 1
            data = history[:, :, -1].reshape(batch * beams, 1)
            for i, st in enumerate(self.stages):
                if st["device"] is not None:
                    data = jax.device_put(data, st["device"])
                data, caches[i] = self._decode_step(st, data, caches[i],
                                                    pos)
            logp = jax.nn.log_softmax(
                data[:, 0].astype(jnp.float32), axis=-1)  # [B*beams, V]
            vocab = logp.shape[-1]
            total = scores[..., None] + logp.reshape(batch, beams, vocab)
            scores, flat = jax.lax.top_k(total.reshape(batch, -1), beams)
            parent = flat // vocab                        # [B, beams]
            token = flat % vocab
            rows = (jnp.arange(batch)[:, None] * beams + parent).reshape(-1)
            caches = [_gather_batch(c, rows) for c in caches]
            history = jnp.concatenate(
                [jnp.take_along_axis(history, parent[..., None], axis=1),
                 token[..., None]], axis=2)

        best = jnp.argmax(scores, axis=1)
        best_hist = jnp.take_along_axis(
            history, best[:, None, None], axis=1)[:, 0]   # [B, new_tokens]
        return jnp.concatenate([ids, best_hist], axis=1)
