"""Pipeline-parallel TRAINING over the SPMD pipeline (beyond reference).

The reference is inference-only (every forward is `@torch.no_grad()`,
models/transformers/vit.py:55 there, and its gloo wire protocol moves
raw tensors with no autograd story). This framework's SPMD driver
compiles the whole pipeline — embed, stage blocks, ppermute edges,
fill/drain masking, final head — into ONE differentiable XLA program
(parallel/spmd.py), so training falls out of the design: `jax.grad`
transposes the program (ppermute reverses direction, psum becomes
broadcast, the scan runs backward), XLA re-partitions the backward over
the same ('dp', 'stage') mesh, and an optax optimizer updates the
stage-sharded parameters in place. No separate backward-pass
engineering — the TPU-first one-program decision is what buys this.

Scope: full-parameter training of the pipeline's stage-stacked
parameters (embed/final replicated, blocks stage-sharded), softmax
cross-entropy over the model's output head (classifier logits [M, B, C]
or LM logits [M, B, S, V]). Quantized stage edges are refused — integer
rounding on the wire is not differentiable (a straight-through
estimator would silently change semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .spmd import SpmdPipeline

__all__ = ["make_train_step", "softmax_xent", "save_train_state",
           "restore_train_state"]


def softmax_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean cross-entropy; `labels` are integer class ids with one fewer
    trailing axis than `logits` ([M, B] for classifiers, [M, B, S] for
    LM heads)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return -picked.mean()


def make_train_step(pipe: SpmdPipeline, optimizer, example_inputs,
                    loss_fn=softmax_xent, mixed_precision: bool = False):
    """Build (train_step, opt_state) for an SPMD pipeline.

    `train_step(params, opt_state, inputs, labels) -> (params, opt_state,
    loss)` is one jit-compiled step: pipelined forward, backward through
    the ppermute edges, optimizer update — all over the pipeline's mesh.
    `example_inputs` fixes the compiled microbatch shape ([M, B, ...raw
    input dims], the same stacked layout `SpmdPipeline.run` takes).

    `mixed_precision=True` is the TPU bf16-compute/f32-master recipe:
    the float32 params passed to `train_step` stay the optimizer's
    MASTER weights, but each step's forward (and therefore the MXU
    matmuls and the activations/ppermute edges of the backward) runs on
    a bfloat16 cast of them. Gradients flow back through the cast —
    XLA's transpose accumulates them into float32 — and the optimizer
    update applies at full precision, so tiny updates are never lost to
    bf16 rounding (the failure mode of pure-bf16 training). bfloat16
    keeps float32's exponent range, so no loss scaling is needed (the
    fp16 complication this recipe avoids). The pipeline must be built
    with float32 params — they ARE the masters.

    Returns opt_state initialized against the pipeline's (sharded)
    params. The integer block-count leaf is held static: it selects
    which padded blocks are real, and gets no gradient."""
    if any(pipe.stage_bits[:-1]):
        raise ValueError(
            "quantized stage edges are not differentiable; build the "
            "training pipeline with quant_bit=0 (QuantPipe compression "
            "is an inference-edge feature)")
    import optax

    example_inputs = jnp.asarray(example_inputs)
    fwd = pipe.compiled_for(example_inputs)   # shares run()'s cache
    n_blocks = pipe.params["n_blocks"]

    if mixed_precision:
        bad = [jnp.dtype(leaf.dtype).name
               for leaf in jax.tree_util.tree_leaves(pipe.params)
               if jnp.issubdtype(leaf.dtype, jnp.floating)
               and leaf.dtype != jnp.float32]
        if bad:
            raise ValueError(
                "mixed_precision keeps float32 MASTER weights and casts "
                "to bfloat16 per step; build the pipeline with float32 "
                f"params (found {sorted(set(bad))})")

    def _compute_cast(tree):
        """bf16 working copy for the forward/backward; inside jit, so
        XLA fuses the casts into the first consuming matmuls."""
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def compute_loss(trainable, inputs, labels):
        compute = _compute_cast(trainable) if mixed_precision else trainable
        if mixed_precision:
            inputs = _compute_cast(inputs)
        logits = fwd({**compute, "n_blocks": n_blocks}, inputs)
        return loss_fn(logits, labels)

    @jax.jit
    def _step(params, opt_state, inputs, labels):
        trainable = {k: v for k, v in params.items() if k != "n_blocks"}
        loss, grads = jax.value_and_grad(compute_loss)(
            trainable, inputs, labels)
        updates, opt_state = optimizer.update(grads, opt_state, trainable)
        new_params = optax.apply_updates(trainable, updates)
        return {**new_params, "n_blocks": n_blocks}, opt_state, loss

    def train_step(params, opt_state, inputs, labels):
        inputs = jnp.asarray(inputs)
        if inputs.shape != example_inputs.shape:
            # the pipelined program bakes the microbatch schedule into
            # its tick count; a mismatched shape would die deep inside
            # the traced scan instead of here
            raise ValueError(
                f"inputs shape {inputs.shape} != the compiled step's "
                f"{example_inputs.shape}; build a train step per "
                "input shape (make_train_step(pipe, opt, inputs))")
        return _step(params, opt_state, inputs, jnp.asarray(labels))

    trainable = {k: v for k, v in pipe.params.items() if k != "n_blocks"}
    opt_state = jax.jit(optimizer.init)(trainable)
    # momenta propagate the params' mesh shardings through jit, but
    # SCALAR optimizer leaves (adam's count) come out single-device —
    # mixing those with mesh-sharded params in one jitted step is a
    # device-mismatch error; replicate them over the pipeline's mesh
    from jax.sharding import NamedSharding, PartitionSpec, SingleDeviceSharding
    replicated = NamedSharding(pipe.mesh, PartitionSpec())

    def place(x):
        if isinstance(getattr(x, "sharding", None), SingleDeviceSharding) \
                and len(pipe.mesh.devices.flat) > 1:
            return jax.device_put(x, replicated)
        return x

    opt_state = jax.tree_util.tree_map(place, opt_state)
    return train_step, opt_state


def save_train_state(path: str, params, opt_state, step: int) -> None:
    """Checkpoint a training run (params + optimizer state + step count)
    as one Orbax pytree — the training extension of the per-stage
    checkpoint/resume axis (SURVEY.md §5.4; utils/checkpoint.py holds
    the inference-side per-stage npz/Orbax machinery)."""
    from ..utils.checkpoint import save_params
    save_params(path, {"params": params, "opt_state": opt_state,
                       "step": jnp.asarray(step, jnp.int32)})


def restore_train_state(path: str, like_params, like_opt_state):
    """Restore `save_train_state`'s pytree into the structures (and
    SHARDINGS — leaves restore straight onto their mesh placement) of a
    freshly initialized run: `like_params`/`like_opt_state` from
    `pipe.params` and `make_train_step`'s opt_state. Returns
    (params, opt_state, step)."""
    from ..utils.checkpoint import load_params
    state = load_params(path, like={
        "params": like_params, "opt_state": like_opt_state,
        "step": jnp.asarray(0, jnp.int32)})
    return state["params"], state["opt_state"], int(state["step"])
