"""Offline per-layer profiler: jit timing + compiled memory analysis.

Capability parity with /root/reference/profiler.py, redesigned for TPU/XLA:

- The reference times `module(*inputs)` wall-clock on CPU (profiler.py:73-79)
  and measures memory as the RSS delta around shard construction in a fresh
  subprocess (profiler.py:39-53, 93-118). Here each layer is a jit-compiled
  pure function: time comes from executing `iterations` steps inside ONE
  compiled `lax.scan` (per-iteration inputs are perturbed by the loop index
  so XLA cannot hoist the loop-invariant computation; a scalar readback
  fences the device), and memory comes from the compiled executable's
  `memory_analysis()` plus exact parameter-buffer bytes — no subprocesses
  or RSS heuristics needed since compilation is hermetic.
- Output schema is identical (profiler.py:234-240): {model_name, dtype,
  batch_size, layers, profile_data: [{layer, time, memory, shape_in,
  shape_out}]}, so the downstream converters and the native scheduler run
  unchanged. Layer l's outputs chain into layer l+1's inputs
  (profile_layers_individually, profiler.py:133-145).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .models import registry

logger = logging.getLogger(__name__)


def _payload_shapes(payload) -> List[List[int]]:
    """Per-item shapes (batch dim stripped), as the reference records them."""
    tensors = payload if isinstance(payload, tuple) else (payload,)
    return [list(t.shape[1:]) for t in tensors]


def _perturb(payload, i):
    """Make iteration i's input depend on the loop index (defeats hoisting)."""
    scale = 1.0 + i.astype(jnp.float32) * 1e-6
    if isinstance(payload, tuple):
        return tuple(t * scale.astype(t.dtype) if jnp.issubdtype(t.dtype, jnp.floating)
                     else t for t in payload)
    if jnp.issubdtype(payload.dtype, jnp.floating):
        return payload * scale.astype(payload.dtype)
    return payload  # integer inputs (BERT ids) can't be perturbed; layer 1
                    # embeddings are not loop-invariant w.r.t. the carry sum


def _scalar_probe(payload) -> jax.Array:
    tensors = payload if isinstance(payload, tuple) else (payload,)
    return sum(jnp.sum(t.astype(jnp.float32)) for t in tensors)


def time_shard_fn(fn, params, payload, iterations: int, warmup: bool = True) -> float:
    """Average seconds per execution of `fn(params, payload)`.

    All `iterations` run inside one compiled scan; a scalar readback fences
    (block_until_ready does not fence on tunneled TPU platforms).
    """
    @jax.jit
    def run(params, payload):
        def step(carry, i):
            out = fn(params, _perturb(payload, i))
            return carry + _scalar_probe(out), None

        total, _ = jax.lax.scan(step, jnp.float32(0), jnp.arange(iterations))
        return total

    if warmup:
        float(run(params, payload))  # compile + warm
    best = float("inf")
    for _ in range(3):
        tik = time.monotonic()
        float(run(params, payload))
        best = min(best, time.monotonic() - tik)
    return best / iterations


def shard_memory_bytes(fn, params, payload) -> int:
    """Memory footprint: exact parameter bytes + compiled temp buffers."""
    from .models import params_bytes
    total = params_bytes(params)
    try:
        compiled = jax.jit(fn).lower(params, payload).compile()
        analysis = compiled.memory_analysis()
        if analysis is not None:
            total += int(getattr(analysis, "temp_size_in_bytes", 0))
    except Exception as exc:  # memory_analysis availability varies by backend
        logger.debug("memory_analysis unavailable: %s", exc)
    return total


def default_inputs(model_name: str, batch_size: int,
                   dtype=jnp.float32) -> jax.Array:
    """Random model inputs matching the reference's defaults
    (profiler.py:204-220: random images; tokenized input ids for BERT)."""
    cfg = registry.get_model_config(model_name)
    rng = np.random.default_rng(0)
    if cfg.model_type == "bert":
        ids = rng.integers(0, cfg.vocab_size, size=(batch_size, 512))
        return jnp.asarray(ids, dtype=jnp.int32)
    return jnp.asarray(rng.normal(size=(
        batch_size, cfg.num_channels, cfg.image_size, cfg.image_size)),
        dtype=dtype)


def profile_layers_individually(model_name: str, model_file: Optional[str],
                                inputs, layer_start: int, layer_end: int,
                                warmup: bool, iterations: int,
                                dtype=jnp.float32) -> List[Dict[str, Any]]:
    """Profile each layer separately, chaining outputs into the next layer's
    inputs (reference profiler.py:133-145)."""
    results = []
    payload = inputs
    for layer in range(layer_start, layer_end + 1):
        fn, params, _ = registry.module_shard_factory(
            model_name, model_file, layer, layer, dtype=dtype)
        shape_in = _payload_shapes(payload)
        t = time_shard_fn(fn, params, payload, iterations, warmup=warmup)
        mem = shard_memory_bytes(fn, params, payload)
        out = fn(params, payload)
        results.append({
            "layer": layer,
            "time": float(t),
            "memory": float(mem) / 1024 / 1024,  # MB, like the reference
            "shape_in": shape_in,
            "shape_out": _payload_shapes(out),
        })
        logger.info("layer %d: %.6f s, %.2f MB", layer, t, results[-1]["memory"])
        payload = out
    return results


def validate_profile_results(profile_results: dict, model_name: str,
                             dtype_name: str, batch_size: int,
                             model_layers: int, layer_start: int,
                             layer_end: int) -> None:
    """Consistency checks against existing results (profiler.py:163-173)."""
    assert profile_results["model_name"] == model_name, \
        "model name mismatch with existing results"
    assert profile_results["dtype"] == dtype_name, \
        "dtype mismatch with existing results"
    assert profile_results["batch_size"] == batch_size, \
        "batch size mismatch with existing results"
    assert profile_results["layers"] == model_layers, \
        "layer count mismatch with existing results"
    for layer in range(layer_start, layer_end + 1):
        for pd in profile_results["profile_data"]:
            assert layer != pd["layer"], \
                "layer to be profiled already in existing results"
