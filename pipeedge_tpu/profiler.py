"""Offline per-layer profiler: jit timing + compiled memory analysis.

Capability parity with /root/reference/profiler.py, redesigned for TPU/XLA:

- The reference times `module(*inputs)` wall-clock on CPU (profiler.py:73-79)
  and measures memory as the RSS delta around shard construction in a fresh
  subprocess (profiler.py:39-53, 93-118). Here each layer is a jit-compiled
  pure function: time comes from executing `iterations` steps inside ONE
  compiled `lax.scan` (per-iteration inputs are perturbed by the loop index
  so XLA cannot hoist the loop-invariant computation; a scalar readback
  fences the device), and memory comes from the compiled executable's
  `memory_analysis()` plus exact parameter-buffer bytes — no subprocesses
  or RSS heuristics needed since compilation is hermetic.
- Output schema is identical (profiler.py:234-240): {model_name, dtype,
  batch_size, layers, profile_data: [{layer, time, memory, shape_in,
  shape_out}]}, so the downstream converters and the native scheduler run
  unchanged. Layer l's outputs chain into layer l+1's inputs
  (profile_layers_individually, profiler.py:133-145).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .models import registry

logger = logging.getLogger(__name__)


def _payload_shapes(payload) -> List[List[int]]:
    """Per-item shapes (batch dim stripped), as the reference records them."""
    tensors = payload if isinstance(payload, tuple) else (payload,)
    return [list(t.shape[1:]) for t in tensors]


def _perturb(payload, i):
    """Make iteration i's input depend on the loop index (defeats hoisting)."""
    scale = 1.0 + i.astype(jnp.float32) * 1e-6
    if isinstance(payload, tuple):
        return tuple(t * scale.astype(t.dtype) if jnp.issubdtype(t.dtype, jnp.floating)
                     else t for t in payload)
    if jnp.issubdtype(payload.dtype, jnp.floating):
        return payload * scale.astype(payload.dtype)
    return payload  # integer inputs (BERT ids) can't be perturbed; layer 1
                    # embeddings are not loop-invariant w.r.t. the carry sum


def _scalar_probe(payload) -> jax.Array:
    tensors = payload if isinstance(payload, tuple) else (payload,)
    return sum(jnp.sum(t.astype(jnp.float32)) for t in tensors)


def time_shard_fn(fn, params, payload, iterations: int, warmup: bool = True) -> float:
    """Average seconds per execution of `fn(params, payload)`.

    All `iterations` run inside one compiled scan; a scalar readback fences
    (block_until_ready does not fence on tunneled TPU platforms).
    """
    @jax.jit
    def run(params, payload):
        def step(carry, i):
            out = fn(params, _perturb(payload, i))
            return carry + _scalar_probe(out), None

        total, _ = jax.lax.scan(step, jnp.float32(0), jnp.arange(iterations))
        return total

    if warmup:
        float(run(params, payload))  # compile + warm
    best = float("inf")
    for _ in range(3):
        tik = time.monotonic()
        float(run(params, payload))
        best = min(best, time.monotonic() - tik)
    return best / iterations


def _compile_and_analyze(fn, params, payload) -> Tuple[Optional[Any], int]:
    """AOT-compile `fn` once (registry fns are already jitted); return the
    compiled executable (None if lowering unsupported) and its temp-buffer
    bytes. The caller can execute the returned executable directly, so the
    same compilation serves memory analysis and the forward pass."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    try:
        compiled = jitted.lower(params, payload).compile()
    except Exception as exc:  # AOT path availability varies by backend
        logger.debug("AOT compile unavailable: %s", exc)
        return None, 0
    temp = 0
    try:
        analysis = compiled.memory_analysis()
        if analysis is not None:
            temp = int(getattr(analysis, "temp_size_in_bytes", 0))
    except Exception as exc:  # memory_analysis availability varies by backend
        logger.debug("memory_analysis unavailable: %s", exc)
    return compiled, temp


def shard_memory_bytes(fn, params, payload) -> int:
    """Memory footprint: exact parameter bytes + compiled temp buffers."""
    from .models import params_bytes
    return params_bytes(params) + _compile_and_analyze(fn, params, payload)[1]


def default_inputs(model_name: str, batch_size: int,
                   dtype=jnp.float32) -> jax.Array:
    """Random model inputs matching the reference's defaults
    (profiler.py:204-220: random images; tokenized input ids for BERT)."""
    cfg = registry.get_model_config(model_name)
    rng = np.random.default_rng(0)
    if cfg.vocab_size:  # token models: BERT (512-token refs) and GPT-2
        seq = min(512, cfg.max_position_embeddings or 512)
        ids = rng.integers(0, cfg.vocab_size, size=(batch_size, seq))
        return jnp.asarray(ids, dtype=jnp.int32)
    return jnp.asarray(rng.normal(size=(
        batch_size, cfg.num_channels, cfg.image_size, cfg.image_size)),
        dtype=dtype)


def _struct_sig(tree) -> Tuple:
    """Hashable structural signature of a pytree: treedef + leaf shapes/dtypes."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, tuple((tuple(l.shape), str(l.dtype)) for l in leaves))


def _layer_cfg_sig(cfg, layer: int) -> Tuple:
    """Hashable per-layer signature of the model config: scalar fields as-is,
    sequence-valued fields indexed at this layer's block. All currently
    registered families have scalar (homogeneous) configs, but a future
    family with per-block heterogeneity (e.g. varying expert counts) must
    not silently reuse another block's timing/memory, so the block's own
    config slice is part of the reuse-cache key. Memoize per block
    (profile_layers_individually) — the sig is layer-invariant for the
    scalar configs every current family uses."""
    import dataclasses

    block = (layer - 1) // 4
    sig = []
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, (list, tuple)):
            sig.append((f.name, v[block] if block < len(v) else None))
        else:
            sig.append((f.name, v))
    return tuple(sig)


def _measure_layer(fn, params, payload, iterations: int, warmup: bool,
                   ) -> Tuple[float, int, Any]:
    """(avg seconds, memory bytes, output payload) for one layer shard.
    One timing compile (the scan) + one AOT compile shared between memory
    analysis and the chained forward."""
    from .models import params_bytes
    t = time_shard_fn(fn, params, payload, iterations, warmup=warmup)
    compiled, temp = _compile_and_analyze(fn, params, payload)
    mem = params_bytes(params) + temp
    out = compiled(params, payload) if compiled is not None else fn(params, payload)
    return t, mem, out


def profile_layers_individually(model_name: str, model_file: Optional[str],
                                inputs, layer_start: int, layer_end: int,
                                warmup: bool, iterations: int,
                                dtype=jnp.float32,
                                reuse_identical: bool = True,
                                ) -> List[Dict[str, Any]]:
    """Profile each layer separately, chaining outputs into the next layer's
    inputs (reference profiler.py:133-145).

    With `reuse_identical` (default), layers whose computation is structurally
    identical to an already-measured one — same sublayer kind ((layer-1) % 4,
    the repo-wide 4-sublayers-per-block convention), same head/tail role, and
    same input shapes — reuse that measurement instead of re-building,
    re-compiling, and re-timing. All registered models have homogeneous
    blocks (scalar HF hidden/intermediate sizes), so this key also pins the
    parameter shapes; a cache hit therefore skips the factory entirely (no
    per-layer weight materialization or host->device transfer). Transformer
    blocks repeat every 4 sublayers, so a 96-layer ViT-Large profile needs
    only ~6 real measurements. Timing on XLA is weight- and value-independent
    for these shards (no data-dependent control flow), so this is exact, and
    it matters on tunneled TPU backends where every avoided compile costs
    seconds. `--exhaustive` (CLI) restores the reference's measure-every-layer
    behavior.
    """
    results = []
    payload = inputs
    model_layers = registry.get_model_layers(model_name)
    cfg_entry = registry.get_model_config(model_name)
    cache: Dict[Tuple, Tuple[float, int, Any]] = {}
    block_sigs: Dict[int, Tuple] = {}
    for layer in range(layer_start, layer_end + 1):
        shape_in = _payload_shapes(payload)
        block = (layer - 1) // 4
        if block not in block_sigs:
            block_sigs[block] = _layer_cfg_sig(cfg_entry, layer)
        key = ((layer - 1) % 4, layer == 1, layer == model_layers,
               _struct_sig(payload), block_sigs[block])
        hit = cache.get(key) if reuse_identical else None
        if hit is not None:
            t, mem, out = hit
            note = " (reused: identical structure)"
        else:
            fn, params, _ = registry.module_shard_factory(
                model_name, model_file, layer, layer, dtype=dtype)
            t, mem, out = _measure_layer(fn, params, payload, iterations,
                                         warmup)
            cache[key] = (t, mem, out)
            note = ""
        results.append({
            "layer": layer,
            "time": float(t),
            "memory": float(mem) / 1024 / 1024,  # MB, like the reference
            "shape_in": shape_in,
            "shape_out": _payload_shapes(out),
        })
        logger.info("layer %d: %.6f s, %.2f MB%s", layer, t,
                    results[-1]["memory"], note)
        payload = out
    return results


def validate_profile_results(profile_results: dict, model_name: str,
                             dtype_name: str, batch_size: int,
                             model_layers: int, layer_start: int,
                             layer_end: int) -> None:
    """Consistency checks against existing results (profiler.py:163-173)."""
    assert profile_results["model_name"] == model_name, \
        "model name mismatch with existing results"
    assert profile_results["dtype"] == dtype_name, \
        "dtype mismatch with existing results"
    assert profile_results["batch_size"] == batch_size, \
        "batch size mismatch with existing results"
    assert profile_results["layers"] == model_layers, \
        "layer count mismatch with existing results"
    for layer in range(layer_start, layer_end + 1):
        for pd in profile_results["profile_data"]:
            assert layer != pd["layer"], \
                "layer to be profiled already in existing results"
