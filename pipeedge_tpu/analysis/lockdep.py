"""Runtime lock-order witness (lockdep): cycles + blocking-under-lock.

The AST half of pipelint (rules_locks.py) can only see LEXICAL locking —
`with self._lock:` blocks in one function. The interleavings that actually
deadlock a fleet are dynamic: reader thread takes `dcn.dead` then
`dcn.hb`, heartbeat thread takes `dcn.hb` then `dcn.dead`, and the run
that hits both orders at once is the one CI never reproduced. This module
witnesses the REAL acquisition orders while the tier-1 suite runs the real
code, kernel-lockdep style: one observed A->B ordering is enough to
convict a later B->A, no simultaneous collision required.

Mechanics:

- `utils/threads.py`'s `make_lock`/`make_rlock`/`make_condition`
  factories return `TrackedLock`s when the witness is enabled (env
  PIPEEDGE_LOCKDEP=1, or `enable()` in-process), plain stdlib primitives
  otherwise — the disabled hot path costs nothing.
- every successful acquire appends the lock's NAME to a per-thread held
  stack and records held->acquired edges into a global order graph, with
  a short witness stack captured the first time each edge is seen.
- `cycles()` runs Tarjan's SCC over the name graph: any SCC with more
  than one lock (or a self-edge between two instances of one name) is an
  order inversion that can deadlock.
- while enabled, `time.sleep` and blocking `queue.Queue.get/put` are
  wrapped to call `note_blocking`: executing one with any tracked lock
  held is a latency/deadlock hazard (the lock-holder stalls everyone)
  and is recorded with the held set + stack. Socket sends are left to
  the static rule PL102 — patching socket methods would perturb the very
  transport timings other tests measure.
- `report()`/`dump()` emit a one-JSON-line summary; with
  PIPEEDGE_LOCKDEP_OUT set, every witnessing process appends its line at
  exit (O_APPEND, one line per process — fleets of runtime.py
  subprocesses land in the same file).

Per-name, not per-instance: `dcn.conn[3]` and `dcn.conn[5]` are distinct
names, but every `DistDcnContext`'s `dcn.dead` is ONE node — the order
law is a property of the code path, and folding instances is what lets a
2-rank test convict an ordering that only deadlocks at rank 40.

Stdlib-only: imported by `utils/threads.py` at module load.
"""
from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

ENV_LOCKDEP = "PIPEEDGE_LOCKDEP"
ENV_LOCKDEP_OUT = "PIPEEDGE_LOCKDEP_OUT"

# witness bookkeeping caps: the graph itself is tiny (lock names are
# static), but blocking-violation records carry stacks and a pathological
# loop could grow them without bound
_MAX_BLOCKING_RECORDS = 256
_STACK_DEPTH = 6

# frames from these files are witness plumbing, not evidence — dropped
# from captured stacks so the top frame names the caller's code
_SELF_FILES = ("lockdep.py", "threads.py")


def _caller_stack() -> List[str]:
    frames = traceback.extract_stack()
    out = []
    for f in frames:
        fname = os.path.basename(f.filename)
        if fname in _SELF_FILES:
            continue
        out.append(f"{fname}:{f.lineno}:{f.name}")
    return out[-_STACK_DEPTH:]


class LockdepState:
    """One witness: order graph + per-thread held stacks + violations.

    The global singleton (`enable()`) is the production path; tests build
    private instances so a deliberately-constructed cycle never pollutes
    the suite-wide report (tests/test_pipelint.py).
    """

    def __init__(self):
        # guards graph/violation mutation only; a leaf lock — nothing is
        # acquired and no blocking call runs while it is held, so the
        # witness itself can never participate in an order cycle
        self._mu = threading.Lock()
        self._held = threading.local()
        # (held_name, acquired_name) -> {count, thread, stack}
        self._edges: Dict[Tuple[str, str], dict] = {}
        self._lock_names: set = set()
        self._threads_seen: set = set()
        self._blocking: List[dict] = []
        self._blocking_dropped = 0

    # -- per-thread held stack ----------------------------------------
    # entries are (name, instance id): re-entrancy is a property of ONE
    # lock object, but the order graph folds by name — so acquiring a
    # SECOND instance of the same name while the first is held records a
    # self-edge (name, name), the two-instances-one-site deadlock shape

    def _stack(self) -> List[Tuple[str, int]]:
        st = getattr(self._held, "names", None)
        if st is None:
            st = self._held.names = []
        return st

    def held(self) -> Tuple[str, ...]:
        """Lock names the CURRENT thread holds, outermost first."""
        return tuple(n for n, _ in self._stack())

    def note_acquire(self, name: str, oid: int = 0) -> None:
        st = self._stack()
        with self._mu:
            self._lock_names.add(name)
            self._threads_seen.add(threading.current_thread().name)
            for h, h_oid in st:
                if h == name and h_oid == oid:
                    continue     # re-entrant hold of THIS lock: not an edge
                rec = self._edges.get((h, name))
                if rec is None:
                    self._edges[(h, name)] = {
                        "count": 1,
                        "thread": threading.current_thread().name,
                        "stack": _caller_stack(),
                    }
                else:
                    rec["count"] += 1
        st.append((name, oid))

    def note_release(self, name: str, oid: int = 0) -> None:
        st = self._stack()
        # release order need not be LIFO (lock A, lock B, release A):
        # drop the most recent matching hold
        for i in range(len(st) - 1, -1, -1):
            if st[i] == (name, oid):
                del st[i]
                return

    def note_release_all(self, name: str, oid: int = 0) -> int:
        """Drop every recursion level of this lock (Condition.wait's full
        release); returns how many were held so restore can re-push."""
        st = self._stack()
        n = st.count((name, oid))
        if n:
            self._held.names = [s for s in st if s != (name, oid)]
        return n

    def note_blocking(self, desc: str) -> None:
        """A blocking call is starting on this thread: a violation iff any
        tracked lock is currently held."""
        st = self._stack()
        if not st:
            return
        with self._mu:
            if len(self._blocking) >= _MAX_BLOCKING_RECORDS:
                self._blocking_dropped += 1
                return
            self._blocking.append({
                "held": [n for n, _ in st],
                "call": desc,
                "thread": threading.current_thread().name,
                "stack": _caller_stack(),
            })

    # -- analysis ------------------------------------------------------

    def cycles(self) -> List[List[str]]:
        """Lock-name cycles in the observed order graph (Tarjan SCC):
        each returned list is one strongly-connected component of >= 2
        locks — an inversion some pair of threads can deadlock on — or a
        single name with a self-edge (two INSTANCES of one lock site
        nested, the shape note_acquire records when oids differ)."""
        with self._mu:
            adj: Dict[str, List[str]] = {}
            self_edges = set()
            for (a, b) in self._edges:
                if a == b:
                    self_edges.add(a)
                adj.setdefault(a, []).append(b)
                adj.setdefault(b, [])
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: set = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            # iterative Tarjan: witness graphs are small but a DFS over
            # a long chain must not hit the recursion limit mid-report
            work = [(v, 0)]
            while work:
                node, pi = work.pop()
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                succs = adj.get(node, [])
                for i in range(pi, len(succs)):
                    w = succs[i]
                    if w not in index:
                        work.append((node, i + 1))
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1 or comp[0] in self_edges:
                        sccs.append(sorted(comp))
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for v in list(adj):
            if v not in index:
                strongconnect(v)
        return sccs

    def edge_witnesses(self, names: List[str]) -> List[dict]:
        """The recorded witnesses for every edge between `names` — what a
        cycle report prints so the inversion is actionable."""
        wanted = set(names)
        with self._mu:
            return [dict(rec, held=a, acquired=b)
                    for (a, b), rec in self._edges.items()
                    if a in wanted and b in wanted]

    def report(self) -> dict:
        cycles = self.cycles()
        with self._mu:
            return {
                "pid": os.getpid(),
                "locks": sorted(self._lock_names),
                "edges": len(self._edges),
                "threads": len(self._threads_seen),
                "cycles": cycles,
                "blocking_violations": list(self._blocking),
                "blocking_dropped": self._blocking_dropped,
            }

    def dump(self, path: str) -> dict:
        """Append the report as ONE JSON line (O_APPEND: concurrent fleet
        processes each land their own line intact)."""
        rep = self.report()
        rep["cycle_witnesses"] = [self.edge_witnesses(c)
                                  for c in rep["cycles"]]
        line = json.dumps(rep, separators=(",", ":")) + "\n"
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)
        return rep


class TrackedLock:
    """`threading.Lock` wrapper feeding the witness on acquire/release.

    Name, not instance, is the graph node (see module docstring). The
    wrapper adds two method calls and one list append per acquisition —
    only ever paid when the witness is enabled.
    """

    _factory = staticmethod(threading.Lock)

    def __init__(self, state: LockdepState, name: str):
        self._state = state
        self.name = name
        self._lk = self._factory()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            self._state.note_acquire(self.name, id(self))
        return ok

    def release(self) -> None:
        self._lk.release()
        self._state.note_release(self.name, id(self))

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r}>"


class TrackedRLock(TrackedLock):
    """Re-entrant tracked lock, `threading.Condition`-compatible.

    Forwards the private wait protocol (`_is_owned`, `_release_save`,
    `_acquire_restore`) so `make_condition` can build a stdlib Condition
    on top: `wait()` fully releases the lock — and the witness's held
    stack — before parking, so time parked in a wait is correctly NOT
    "holding the lock across a blocking call".
    """

    _factory = staticmethod(threading.RLock)

    def _is_owned(self) -> bool:
        return self._lk._is_owned()

    def _release_save(self):
        token = self._lk._release_save()
        depth = self._state.note_release_all(self.name, id(self))
        return (token, depth)

    def _acquire_restore(self, saved) -> None:
        token, depth = saved
        self._lk._acquire_restore(token)
        for _ in range(max(depth, 1)):
            self._state.note_acquire(self.name, id(self))


# -- global witness ------------------------------------------------------

_STATE: Optional[LockdepState] = None
_orig_sleep = None
_orig_queue_get = None
_orig_queue_put = None


def enabled() -> bool:
    return _STATE is not None


def state() -> Optional[LockdepState]:
    return _STATE


def _patched_sleep(secs):
    st = _STATE
    if st is not None and secs > 0:
        st.note_blocking(f"time.sleep({secs:g})")
    return _orig_sleep(secs)


def _patched_queue_get(self, block=True, timeout=None):
    st = _STATE
    if st is not None and block:
        st.note_blocking("queue.Queue.get")
    return _orig_queue_get(self, block, timeout)


def _patched_queue_put(self, item, block=True, timeout=None):
    st = _STATE
    if st is not None and block:
        st.note_blocking("queue.Queue.put")
    return _orig_queue_put(self, item, block, timeout)


def enable(st: Optional[LockdepState] = None) -> LockdepState:
    """Switch the witness on process-wide (idempotent; `st` lets a test
    install a private state and restore the previous one after). Locks
    created BEFORE enabling stay untracked — enable first (conftest.py
    does, before any runtime import creates a lock)."""
    global _STATE, _orig_sleep, _orig_queue_get, _orig_queue_put
    prev = _STATE
    _STATE = st if st is not None else (prev or LockdepState())
    if _orig_sleep is None:
        _orig_sleep = time.sleep
        _orig_queue_get = queue.Queue.get
        _orig_queue_put = queue.Queue.put
        time.sleep = _patched_sleep
        queue.Queue.get = _patched_queue_get
        queue.Queue.put = _patched_queue_put
    return _STATE


def disable() -> None:
    """Switch the witness off and unpatch the blocking probes."""
    global _STATE, _orig_sleep, _orig_queue_get, _orig_queue_put
    _STATE = None
    if _orig_sleep is not None:
        time.sleep = _orig_sleep
        queue.Queue.get = _orig_queue_get
        queue.Queue.put = _orig_queue_put
        _orig_sleep = _orig_queue_get = _orig_queue_put = None


def _dump_at_exit() -> None:  # pragma: no cover - exercised by fleet runs
    out = os.getenv(ENV_LOCKDEP_OUT)
    if _STATE is not None and out:
        try:
            _STATE.dump(out)
        except OSError:
            pass


# env opt-in at import time: utils/threads.py imports this module before
# any runtime lock exists, so PIPEEDGE_LOCKDEP=1 witnesses EVERY process
# that imports pipeedge_tpu — including runtime.py fleet subprocesses,
# which append their own report lines via PIPEEDGE_LOCKDEP_OUT
if os.getenv(ENV_LOCKDEP) == "1":
    enable()
    atexit.register(_dump_at_exit)
