"""Telemetry rules: metric label matrices pre-declared, spans paired.

PR 3's /metrics plane renders only label sets it has SEEN — a counter
incremented lazily per edge/class materializes series one event at a
time, so dashboards and alerts watching the full matrix silently miss the
series that hasn't fired yet (the PR 7 shed matrix was pre-declared for
exactly this reason). PL501 requires every labeled counter family to
`declare()` its matrix somewhere in the linted tree. PL502 keeps span
probes exception-safe: `telemetry.span()` outside a `with` risks an
__enter__ with no __exit__ on the error path (unbalanced spans corrupt
the bubble math); cross-thread pairs belong to `telemetry.record()`.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from .lint import Finding, Module, Rule, SEVERITY_ERROR, SEVERITY_WARNING

# Counter.inc() kwargs that are NOT labels
_NON_LABEL_KWARGS = frozenset(("amount",))


def _counter_metric_name(node: ast.Call) -> Optional[str]:
    """Prometheus family name when `node` constructs a Counter:
    `reg.counter("name", ...)` or `reg.get_or_create(Counter, "name", ...)`."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == "counter" and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    if func.attr == "get_or_create" and len(node.args) >= 2 \
            and isinstance(node.args[0], ast.Name) \
            and node.args[0].id == "Counter" \
            and isinstance(node.args[1], ast.Constant):
        return node.args[1].value
    return None


class UndeclaredMetricLabels(Rule):
    id = "PL501"
    name = "undeclared-metric-labels"
    severity = SEVERITY_WARNING
    fix_hint = ("declare() the label matrix where the counter's label "
                "domain becomes known (per-edge at context init, "
                "class x reason at controller construction)")
    rationale = ("a labeled counter that never declare()s its matrix "
                 "materializes series one increment at a time — scrapers "
                 "and alerts miss the series that hasn't fired yet")

    def __init__(self):
        # cross-file state (collect runs over every module first):
        # identifier (variable/attribute the counter is bound to) ->
        # family name; families with a declare() anywhere; identifiers
        # declare()d anywhere (when the binding couldn't be resolved)
        self._families: Dict[str, str] = {}
        self._declared_families: Set[str] = set()
        self._declared_idents: Set[str] = set()

    @staticmethod
    def _ident(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def collect(self, module: Module) -> None:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                family = _counter_metric_name(node.value)
                if family is not None:
                    for t in node.targets:
                        ident = self._ident(t)
                        if ident is not None:
                            self._families[ident] = family
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "declare":
                ident = self._ident(node.func.value)
                if ident is not None:
                    self._declared_idents.add(ident)
                    if ident in self._families:
                        self._declared_families.add(self._families[ident])

    def check(self, module: Module) -> Iterator[Finding]:
        # resolve identifier->family declares recorded before the binding
        # was seen (collect order is file order, bindings cross files)
        for ident in self._declared_idents:
            if ident in self._families:
                self._declared_families.add(self._families[ident])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr != "inc":
                continue
            labels = [k.arg for k in node.keywords
                      if k.arg and k.arg not in _NON_LABEL_KWARGS]
            if not labels:
                continue
            ident = self._ident(node.func.value)
            if ident is None or ident not in self._families:
                continue     # not a counter we saw constructed
            family = self._families[ident]
            if family in self._declared_families \
                    or ident in self._declared_idents:
                continue
            yield self.finding(
                module, node,
                f"labeled increment of {family} "
                f"({', '.join(sorted(labels))}) but the family never "
                f"declare()s its label matrix")


class UnpairedSpan(Rule):
    id = "PL502"
    name = "unpaired-span"
    severity = SEVERITY_ERROR
    fix_hint = ("use `with telemetry.span(...)` so the exit stamp rides "
                "the exception path too; for cross-thread pairs record "
                "both stamps and call telemetry.record()")
    rationale = ("a span entered outside `with` leaks its begin stamp on "
                 "any error path — unbalanced spans corrupt busy/idle "
                 "attribution in trace_report")

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr != "span":
                continue
            parent = module.parent(node)
            if isinstance(parent, ast.withitem):
                continue
            # `return rec.span(...)` / `span(...)` as a factory return
            # value is the recorder's own API surface, not a probe site
            if isinstance(parent, ast.Return):
                continue
            yield self.finding(
                module, node,
                "telemetry span created outside a `with` block")


RULES = (UndeclaredMetricLabels, UnpairedSpan)
