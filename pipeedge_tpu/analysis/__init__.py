"""Repo-specific static analysis + runtime invariant witnesses (pipelint).

Two halves, one correctness plane (docs/STATIC_ANALYSIS.md):

- `lint` + the `rules_*` modules: an AST rule engine encoding the
  codebase's own laws — lock discipline, thread hygiene, JAX dispatch-path
  rules, DCN protocol-table rules, telemetry pre-declaration — run by
  `tools/pipelint.py` over every diff (CI gate: zero non-baselined
  findings). Rules support `# pipelint: disable=RULE` suppression and a
  checked-in justified baseline for grandfathered findings.
- `lockdep`: an opt-in (env PIPEEDGE_LOCKDEP=1) runtime lock-order
  witness behind `utils/threads.py`'s lock factories: per-thread
  acquisition stacks feed a global order graph, cycles and
  held-lock-across-blocking-call hazards are detected while the tier-1
  suite exercises the real interleavings, and a one-JSON-line report is
  dumped at exit.

This package is stdlib-only by design: `utils/threads.py` imports
`lockdep` at module load, so nothing here may pull jax/numpy (or any
other piece of the runtime it watches).
"""
