"""Thread-hygiene rule: every thread is daemon or has a join path.

A non-daemon thread with no `.join()` keeps the interpreter alive after
`main` returns — the hung-fleet-teardown class of bug (a rank that
"exited" but its process never died, holding its listen port and wedging
the next run's rendezvous). The law: every `threading.Thread`/`Timer`
either passes `daemon=True` at construction, sets `.daemon = True` before
start, or is joined somewhere (the close()/stop() path of its owner).
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from .lint import Finding, Module, Rule, SEVERITY_ERROR, dotted


def _thread_ctor(node: ast.Call) -> Optional[str]:
    """"Thread"/"Timer" when `node` constructs one (threading.Thread /
    threading.Timer / bare Thread from an import)."""
    name = dotted(node.func)
    if name in ("threading.Thread", "threading.Timer", "Thread", "Timer"):
        return name.split(".")[-1]
    return None


def _bound_name(module: Module, node: ast.Call) -> Optional[str]:
    """The name the constructed thread is bound to: `t = Thread(...)` ->
    "t", `self._hb_thread = Thread(...)` -> "_hb_thread", a list/dict
    element or comprehension -> the collection's name."""
    parent = module.parent(node)
    # unwrap containers: [Thread(...) for ...], [Thread(...), ...]
    hops = 0
    while parent is not None and hops < 6 and not isinstance(
            parent, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        if isinstance(parent, (ast.Expr, ast.Call)):
            return None     # Thread(...).start() / passed straight away
        parent = module.parent(parent)
        hops += 1
    if parent is None:
        return None
    target = parent.targets[0] if isinstance(parent, ast.Assign) \
        else parent.target
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Subscript):
        inner = target.value
        if isinstance(inner, ast.Attribute):
            return inner.attr
        if isinstance(inner, ast.Name):
            return inner.id
    return None


class ThreadWithoutJoinOrDaemon(Rule):
    id = "PL201"
    name = "thread-without-join-or-daemon"
    severity = SEVERITY_ERROR
    fix_hint = ("pass daemon=True at construction, or join the thread "
                "from the owner's close()/stop() path")
    rationale = ("a non-daemon thread with no join path outlives main and "
                 "wedges process teardown (the port-holding zombie-rank "
                 "failure class)")

    def check(self, module: Module) -> Iterator[Finding]:
        # module-wide sets: names ever joined, names ever set daemon=True
        joined: Set[str] = set()
        daemoned: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                recv = node.func.value
                if isinstance(recv, ast.Attribute):
                    joined.add(recv.attr)
                elif isinstance(recv, ast.Name):
                    joined.add(recv.id)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon":
                        recv = t.value
                        if isinstance(recv, ast.Attribute):
                            daemoned.add(recv.attr)
                        elif isinstance(recv, ast.Name):
                            daemoned.add(recv.id)
        # `for w in self._workers: w.join()` — joining the loop variable
        # counts for the iterated collection's name
        for node in ast.walk(module.tree):
            if isinstance(node, ast.For) and isinstance(node.target,
                                                        ast.Name):
                loop_var = node.target.id
                if loop_var in joined or loop_var in daemoned:
                    src = node.iter
                    if isinstance(src, ast.Attribute):
                        joined.add(src.attr)
                    elif isinstance(src, ast.Name):
                        joined.add(src.id)

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _thread_ctor(node)
            if kind is None:
                continue
            daemon_kw = next((k for k in node.keywords
                              if k.arg == "daemon"), None)
            if daemon_kw is not None and not (
                    isinstance(daemon_kw.value, ast.Constant)
                    and not daemon_kw.value.value):
                # daemon=True or a computed value: owned. An explicit
                # constant daemon=False/None says the author CHOSE a
                # non-daemon thread — it still needs a join path.
                continue
            bound = _bound_name(module, node)
            if bound is not None and (bound in joined or bound in daemoned):
                continue
            where = f" (bound to {bound!r})" if bound else ""
            yield self.finding(
                module, node,
                f"threading.{kind} is neither daemon nor joined "
                f"anywhere in this module{where}")


RULES = (ThreadWithoutJoinOrDaemon,)
