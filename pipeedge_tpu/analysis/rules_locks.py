"""Lock-discipline rules: guarded-field writes and blocking-under-lock.

The laws PRs 2-7 enforced by hand: a field that one method protects with
`self._lock` is protected EVERYWHERE (PL101), and a critical section never
executes a blocking call — socket traffic, queue waits, device syncs,
sleeps — because every other thread needing that lock stalls for the full
I/O latency, and a blocked-holder + reverse-order acquirer is half a
deadlock (PL102; `analysis/lockdep.py` witnesses the dynamic half).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .lint import (Finding, Module, Rule, SEVERITY_ERROR, SEVERITY_WARNING,
                   lock_name, walk_excluding_nested_functions,
                   with_lock_names)

# methods where unlocked writes to guarded fields are the idiom, not a
# race: construction happens before any other thread can see the object
_INIT_METHODS = frozenset(("__init__", "__new__", "__post_init__"))

# the `_locked` suffix is this codebase's contract that the CALLER holds
# the lock (admission._grant_locked, runtime._snapshot_locked): writes
# inside are dynamically locked even though no `with` is lexically visible
_LOCKED_SUFFIX = "_locked"


def _self_attr_target(node: ast.AST) -> Optional[str]:
    """`self.X` as an assignment target -> "X" (plain attributes only)."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class LockGuardedFieldWrite(Rule):
    id = "PL101"
    name = "lock-guarded-field-write"
    severity = SEVERITY_WARNING
    fix_hint = ("take the same lock this field is written under elsewhere "
                "(or move the write into the existing critical section)")
    rationale = ("a field written under `self._lock` in one method is "
                 "lock-protected shared state; writing it bare in another "
                 "method races every reader that trusts the lock")

    def check(self, module: Module) -> Iterator[Finding]:
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            yield from self._check_class(module, cls)

    def _check_class(self, module: Module, cls: ast.ClassDef) \
            -> Iterator[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        # pass 1: fields assigned inside a `with <lock>` in any method
        guarded: Dict[str, Tuple[str, str]] = {}   # field -> (lock, method)
        for m in methods:
            for node in ast.walk(m):
                if not isinstance(node, ast.With):
                    continue
                locks = with_lock_names(node)
                if not locks:
                    continue
                lk = locks[0][0]
                for inner in walk_excluding_nested_functions(node.body):
                    targets: List[ast.AST] = []
                    if isinstance(inner, ast.Assign):
                        targets = list(inner.targets)
                    elif isinstance(inner, (ast.AugAssign, ast.AnnAssign)):
                        targets = [inner.target]
                    for t in targets:
                        if isinstance(t, ast.Tuple):
                            elts: List[ast.AST] = list(t.elts)
                        else:
                            elts = [t]
                        for e in elts:
                            attr = _self_attr_target(e)
                            if attr is not None:
                                guarded.setdefault(attr, (lk, m.name))
        if not guarded:
            return
        # pass 2: writes to a guarded field outside every lock
        for m in methods:
            if m.name in _INIT_METHODS or m.name.endswith(_LOCKED_SUFFIX):
                continue
            yield from self._scan_method(module, m, guarded)

    def _scan_method(self, module: Module, method: ast.AST,
                     guarded: Dict[str, Tuple[str, str]]) \
            -> Iterator[Finding]:
        def visit(nodes, lock_depth: int):
            for node in nodes:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    continue
                depth = lock_depth
                if isinstance(node, ast.With) and with_lock_names(node):
                    depth += 1
                if depth == 0:
                    targets: List[ast.AST] = []
                    if isinstance(node, ast.Assign):
                        targets = list(node.targets)
                    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                        targets = [node.target]
                    for t in targets:
                        elts = list(t.elts) if isinstance(t, ast.Tuple) \
                            else [t]
                        for e in elts:
                            attr = _self_attr_target(e)
                            if attr in guarded:
                                lk, where = guarded[attr]
                                yield self.finding(
                                    module, node,
                                    f"self.{attr} is written under "
                                    f"{lk} in {where}() but written "
                                    f"without a lock here")
                yield from visit(ast.iter_child_nodes(node), depth)

        yield from visit(getattr(method, "body", []), 0)


# attribute calls that block (or synchronize with the device) — executing
# one inside a critical section stalls every thread contending the lock
_BLOCKING_ATTRS = frozenset((
    "sleep",             # time.sleep
    "recv", "recv_into", "sendall", "sendmsg", "send", "accept", "connect",
    "select",
    "block_until_ready", "result",
    "wait", "wait_for", "wait_gte",
))
# repo-specific blocking transport helpers called as bare names (comm/dcn.py
# framing layer: each performs full socket sends/reads)
_BLOCKING_FUNCS = frozenset((
    "_send_frame", "_recv_frame", "_recv_header", "_recv_body",
    "_read_exact",
))


def _is_blocking_call(node: ast.Call, held_exprs: List[str]) \
        -> Optional[str]:
    """Description of the blocking call, or None.

    `held_exprs` are source renderings of the held locks' context
    expressions: `cond.wait()` inside `with cond:` is the condition-wait
    idiom (the wait RELEASES the lock) and is exempt.
    """
    func = node.func
    if isinstance(func, ast.Name):
        if func.id in _BLOCKING_FUNCS:
            return f"{func.id}()"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    if attr not in _BLOCKING_ATTRS and attr not in ("get", "put", "join"):
        return None
    recv_src = ast.unparse(func.value) if hasattr(ast, "unparse") else ""
    if attr in ("wait", "wait_for"):
        # waiting on the very condition you hold releases it — the idiom
        if recv_src in held_exprs:
            return None
        return f"{recv_src}.{attr}()"
    if attr == "get":
        # queue.get() blocks; dict.get(key[, default]) doesn't. A bare
        # get() or a get with block=/timeout= kwargs is queue-style.
        kw = {k.arg for k in node.keywords}
        if node.args and not ({"block", "timeout"} & kw):
            return None
        return f"{recv_src}.get()"
    if attr == "put":
        kw = {k.arg for k in node.keywords}
        if ({"block", "timeout"} & kw) or len(node.args) == 1:
            return f"{recv_src}.put()"
        return None
    if attr == "join":
        # thread.join() / thread.join(5) block; ", ".join(seq) doesn't
        if isinstance(func.value, ast.Constant):
            return None
        if len(node.args) == 1 and not node.keywords:
            a = node.args[0]
            if not (isinstance(a, ast.Constant)
                    and isinstance(a.value, (int, float))):
                return None      # one non-numeric arg: string join
        return f"{recv_src}.join()"
    return f"{recv_src}.{attr}()"


class BlockingCallUnderLock(Rule):
    id = "PL102"
    name = "blocking-call-under-lock"
    severity = SEVERITY_ERROR
    fix_hint = ("move the blocking call outside the critical section: "
                "snapshot state under the lock, do the I/O after release "
                "(comm/dcn.py's _declare_dead/_admit_peer pattern)")
    rationale = ("a lock held across socket/queue/device/sleep blocking "
                 "stalls every contending thread for the I/O's latency "
                 "and is half of a lock-order deadlock")

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.With):
                continue
            locks = with_lock_names(node)
            if not locks:
                continue
            held_exprs = [ast.unparse(expr) if hasattr(ast, "unparse")
                          else "" for _, expr in locks]
            lock_desc = ", ".join(n for n, _ in locks)
            for inner in walk_excluding_nested_functions(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                desc = _is_blocking_call(inner, held_exprs)
                if desc is not None:
                    yield self.finding(
                        module, inner,
                        f"blocking call {desc} while holding {lock_desc}")


RULES = (LockGuardedFieldWrite, BlockingCallUnderLock)
