"""JAX dispatch-path rules: jit-in-loop, donated reuse, host syncs.

The steady-state laws behind BENCH_r05's 8.15 ms ubatch cadence: tracing
is for setup (a `jax.jit` inside a per-microbatch loop recompiles or at
best re-hashes every iteration, PL301); a donated buffer belongs to XLA
the moment the jitted call runs (touching it after is undefined, PL302);
and the dispatch path stays ASYNC — one `np.asarray`/`float()` on a
device array in the hot loop serializes host and device and the overlap
window (DCN_STAGE_DEPTH) collapses (PL303).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set

from .lint import (Finding, Module, Rule, SEVERITY_WARNING, dotted,
                   walk_excluding_nested_functions)


def _is_jit_call(node: ast.Call) -> bool:
    name = dotted(node.func)
    if name in ("jax.jit", "jit"):
        return True
    # functools.partial(jax.jit, ...) — the decorator-factory idiom
    if name.endswith("partial") and node.args:
        return dotted(node.args[0]) in ("jax.jit", "jit")
    return False


class JitInLoop(Rule):
    id = "PL301"
    name = "jit-in-loop"
    severity = SEVERITY_WARNING
    fix_hint = ("hoist the jax.jit out of the loop (module level, setup "
                "path, or a keyed cache like spmd_decode's _cache_init)")
    rationale = ("jax.jit inside a per-microbatch/per-round loop pays "
                 "wrapper construction and cache lookup every iteration — "
                 "and a capture-varying signature recompiles every time")

    def check(self, module: Module) -> Iterator[Finding]:
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in walk_excluding_nested_functions(loop.body):
                if isinstance(node, ast.Call) and _is_jit_call(node):
                    yield self.finding(
                        module, node,
                        "jax.jit constructed inside a loop body")


class DonatedArgReuse(Rule):
    id = "PL302"
    name = "donated-arg-reuse"
    severity = SEVERITY_WARNING
    fix_hint = ("a donated argument's buffer belongs to XLA after the "
                "call: use the call's RESULT, or stop donating "
                "(donate_argnums) if the input must stay live")
    rationale = ("reading a donated jax.Array after the jitted call is "
                 "undefined behavior — deleted-buffer errors on CPU, "
                 "silent garbage on TPU with buffer reuse")

    def __init__(self):
        # per-module donating callee names, filled by collect():
        # `fn = jax.jit(step, donate_argnums=(1,))` -> "fn";
        # `self._fn = jax.jit(...)` -> "_fn"
        self._donating: Dict[str, Set[str]] = {}

    @staticmethod
    def _donates(call: ast.Call) -> bool:
        if not _is_jit_call(call):
            return False
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                v = kw.value
                # an empty literal tuple/list donates nothing; anything
                # computed is conservatively treated as donating
                if isinstance(v, (ast.Tuple, ast.List)) and not v.elts:
                    return False
                return True
        return False

    def collect(self, module: Module) -> None:
        names: Set[str] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call) \
                    or not self._donates(node.value):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    names.add(t.attr)
        self._donating[module.path] = names

    def check(self, module: Module) -> Iterator[Finding]:
        donating = self._donating.get(module.path, set())
        if not donating:
            return
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_function(module, fn, donating)

    def _check_function(self, module: Module, fn: ast.AST,
                        donating: Set[str]) -> Iterator[Finding]:
        body = list(walk_excluding_nested_functions(fn.body))
        calls = []
        for node in body:
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name) and node.func.id in donating:
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in donating:
                callee = node.func.attr
            if callee is not None:
                args = [a.id for a in node.args if isinstance(a, ast.Name)]
                if args:
                    calls.append((node.lineno, callee, args))
        if not calls:
            return
        loads: List = [n for n in body if isinstance(n, ast.Name)
                       and isinstance(n.ctx, ast.Load)]
        stores = [n for n in body if isinstance(n, ast.Name)
                  and isinstance(n.ctx, ast.Store)]
        for call_line, callee, args in calls:
            for arg in args:
                for use in loads:
                    if use.id != arg or use.lineno <= call_line:
                        continue
                    # re-assignment between the call and the use makes the
                    # later load a DIFFERENT value (x = fn(x) idiom)
                    if any(s.id == arg and call_line <= s.lineno
                           <= use.lineno for s in stores):
                        continue
                    yield self.finding(
                        module, use,
                        f"{arg} may be donated to {callee}() on line "
                        f"{call_line} and is read again afterwards")
                    break    # one finding per (call, arg)


# the steady-state dispatch surface, by function name: the hot path the
# overlap design (DCN_STAGE_DEPTH, PendingWire) keeps asynchronous
_DISPATCH_NAME_RE = re.compile(r"dispatch|steady|(^|_)emit(_|$)")

# host-sync primitives: each forces a device->host round trip (or a
# blocking wait) when applied to a device array
_SYNC_DOTTED = frozenset((
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get",
))
_SYNC_ATTRS = frozenset(("block_until_ready", "tolist", "item"))
_SYNC_BUILTINS = frozenset(("float", "int", "bytes"))


class HostSyncInDispatchPath(Rule):
    id = "PL303"
    name = "host-sync-in-dispatch-path"
    severity = SEVERITY_WARNING
    fix_hint = ("keep the dispatch path async: move the sync to the "
                "readback/retire side (PendingWire.finalize idiom), or "
                "suppress with a comment naming why the sync is safe here")
    rationale = ("np.asarray/float()/block_until_ready on a device array "
                 "in the steady dispatch path serializes host and device "
                 "and collapses the pipelined overlap window")

    def check(self, module: Module) -> Iterator[Finding]:
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _DISPATCH_NAME_RE.search(fn.name):
                continue
            for node in walk_excluding_nested_functions(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                desc = self._sync_desc(node)
                if desc is not None:
                    yield self.finding(
                        module, node,
                        f"host-sync {desc} inside dispatch-path "
                        f"function {fn.name}()")

    @staticmethod
    def _sync_desc(node: ast.Call) -> Optional[str]:
        name = dotted(node.func)
        if name in _SYNC_DOTTED:
            return f"{name}()"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_ATTRS:
            return f".{node.func.attr}()"
        if isinstance(node.func, ast.Name) \
                and node.func.id in _SYNC_BUILTINS:
            # only when converting a variable (a potential device array);
            # float("1.5") / int(os.getenv(...)) conversions are host data
            if len(node.args) == 1 and isinstance(
                    node.args[0], (ast.Name, ast.Attribute)):
                return f"{node.func.id}()"
        return None


RULES = (JitInLoop, DonatedArgReuse, HostSyncInDispatchPath)
