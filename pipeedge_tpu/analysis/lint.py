"""pipelint engine: AST rule runner, suppressions, justified baseline.

The machinery under `tools/pipelint.py` (docs/STATIC_ANALYSIS.md). A rule
is a class with an id (PLxxx), severity, fix-hint, and a `check(module)`
generator over `Finding`s; cross-file rules (metric declarations live in a
different module than the increments they license) get a `collect(module)`
pre-pass over every linted file before any `check` runs.

Three escape hatches, in order of preference:

- fix the code (the rules encode laws PRs 1-7 enforced by hand-audit);
- `# pipelint: disable=PL102` trailing comment on the flagged line
  (`disable=all` silences every rule there) — for the rare line where
  the law genuinely doesn't apply and the reason fits in the comment;
- a baseline entry (tools/pipelint_baseline.json) carrying a non-empty
  `justification` — for grandfathered findings that survive triage.
  Entries are matched by FINGERPRINT (rule + file + symbol + message, no
  line numbers), so edits elsewhere in a file never invalidate them;
  repeats of one fingerprint are occurrence-indexed ('#2', '#3' in line
  order) so a justified entry covers exactly its one violation, not
  future identical copies. A baseline entry without a justification
  fails the whole run.

Stdlib-only, like everything in `analysis/`.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

_SUPPRESS_RE = re.compile(r"#\s*pipelint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*pipelint:\s*disable-file=([A-Za-z0-9_,\s]+)")

# attribute / variable names that denote a mutex in this codebase (the
# make_lock sites): `self._lock`, `self._dead_lock`, `dead_lock`,
# `self.key_locks[key]`, `self.cond`, `self.spec_lock`, ...
_LOCK_NAME_RE = re.compile(
    r"(^|_)(lock|locks|cond|conds|mutex|rwlock)$")


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    fix_hint: str = ""
    symbol: str = ""
    # 1-based index among findings sharing a raw fingerprint, assigned by
    # run_lint in line order: a SECOND identical violation in the same
    # function gets a distinct '#2' fingerprint, so one justified baseline
    # entry can never grandfather new copies of the same violation
    occurrence: int = 1

    @property
    def fingerprint(self) -> str:
        """Line-number-independent identity: stable across unrelated edits
        to the same file, which is what lets a baseline entry survive."""
        raw = f"{self.rule}|{self.path}|{self.symbol}|{self.message}"
        fp = hashlib.sha1(raw.encode()).hexdigest()[:12]
        return fp if self.occurrence <= 1 else f"{fp}#{self.occurrence}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        hint = f"\n    fix: {self.fix_hint}" if self.fix_hint else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"({self.severity}) {self.message}{sym}{hint}")


class LintError(Exception):
    """Engine-level failure (unparseable file, malformed baseline)."""


class Module:
    """One parsed file + the lookaside structures every rule needs."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        try:
            self.tree = ast.parse(source)
        except SyntaxError as exc:
            raise LintError(f"{path}: syntax error: {exc}") from exc
        # parent links + enclosing (class, function) symbol per node
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._symbols: Dict[ast.AST, str] = {}
        self._link(self.tree, None, ())
        # suppression maps
        self._line_suppress: Dict[int, set] = {}
        self._file_suppress: set = set()
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_FILE_RE.search(text)
            if m:
                self._file_suppress |= {
                    r.strip() for r in m.group(1).split(",") if r.strip()}
                continue
            m = _SUPPRESS_RE.search(text)
            if m:
                self._line_suppress[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}

    def _link(self, node: ast.AST, parent: Optional[ast.AST],
              scope: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            self._parents[child] = node
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_scope = scope + (child.name,)
            self._symbols[child] = ".".join(child_scope)
            self._link(child, node, child_scope)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def symbol(self, node: ast.AST) -> str:
        return self._symbols.get(node, "")

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.source, node) or ""

    def suppressed(self, rule_id: str, line: int) -> bool:
        if rule_id in self._file_suppress or "all" in self._file_suppress:
            return True
        rules = self._line_suppress.get(line)
        return bool(rules) and (rule_id in rules or "all" in rules)


class Rule:
    """Base rule: subclasses set the class attributes and implement
    `check`; `collect` is the optional cross-file pre-pass."""

    id = "PL000"
    name = "abstract"
    severity = SEVERITY_ERROR
    fix_hint = ""
    rationale = ""

    def collect(self, module: Module) -> None:
        pass

    def check(self, module: Module) -> Iterator[Finding]:
        return iter(())

    def finding(self, module: Module, node: ast.AST, message: str,
                fix_hint: Optional[str] = None) -> Finding:
        return Finding(rule=self.id, severity=self.severity,
                       path=module.path, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message,
                       fix_hint=self.fix_hint if fix_hint is None
                       else fix_hint,
                       symbol=module.symbol(node))


# -- shared AST helpers (the lock grammar of this codebase) --------------

def lock_name(node: ast.AST) -> Optional[str]:
    """Canonical name when `node` denotes a lock, else None.

    Recognized: `self._lock` / `self._dead_lock` (attribute whose name
    matches the lock grammar), bare `dead_lock` names, and indexed lock
    tables `self._conn_locks[dst]` / `self.key_locks[key]`.
    """
    if isinstance(node, ast.Attribute) and _LOCK_NAME_RE.search(node.attr):
        return node.attr
    if isinstance(node, ast.Name) and _LOCK_NAME_RE.search(node.id):
        return node.id
    if isinstance(node, ast.Subscript):
        inner = lock_name(node.value)
        if inner is not None:
            return inner + "[]"
    return None


def with_lock_names(node: ast.With) -> List[Tuple[str, ast.AST]]:
    """(lock name, context expr) for every lock-denoting item of a With —
    including the RWLock context managers `x.lock_read()`/`x.lock_write()`."""
    out = []
    for item in node.items:
        expr = item.context_expr
        name = lock_name(expr)
        if name is None and isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in ("lock_read", "lock_write"):
            name = expr.func.attr
        if name is not None:
            out.append((name, expr))
    return out


def walk_excluding_nested_functions(body: Sequence[ast.AST]) \
        -> Iterator[ast.AST]:
    """Every node in `body`, NOT descending into nested function/lambda
    definitions (their bodies execute later, outside the lexical lock)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue    # yielded as a statement, body deferred
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def dotted(node: ast.AST) -> str:
    """`jax.jit` -> "jax.jit", best-effort for Name/Attribute chains."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


# -- file walking --------------------------------------------------------

def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise LintError(f"not a python file or directory: {p}")
    return out


def load_modules(files: Sequence[str]) -> Tuple[List[Module], List[str]]:
    modules, errors = [], []
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                source = fh.read()
            modules.append(Module(os.path.normpath(f).replace(os.sep, "/"),
                                  source))
        except (OSError, LintError) as exc:
            errors.append(str(exc))
    return modules, errors


def default_rules() -> List[Rule]:
    # local import: rules modules import this one for the base class
    from . import (rules_jax, rules_locks, rules_protocol, rules_telemetry,
                   rules_threads)
    rules: List[Rule] = []
    for mod in (rules_locks, rules_threads, rules_jax, rules_protocol,
                rules_telemetry):
        rules.extend(cls() for cls in mod.RULES)
    return sorted(rules, key=lambda r: r.id)


def run_lint(paths: Sequence[str],
             rules: Optional[Sequence[Rule]] = None) \
        -> Tuple[List[Finding], List[str], int]:
    """Lint `paths`; returns (findings, engine errors, files seen).
    Suppressed findings are dropped here — the baseline is the caller's
    layer (tools/pipelint.py), so programmatic users see raw results."""
    if rules is None:
        rules = default_rules()
    modules, errors = load_modules(iter_py_files(paths))
    for rule in rules:
        for m in modules:
            rule.collect(m)
    findings: List[Finding] = []
    for rule in rules:
        for m in modules:
            for f in rule.check(m):
                if not m.suppressed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    dupes: Dict[str, int] = {}
    for f in findings:
        raw = f"{f.rule}|{f.path}|{f.symbol}|{f.message}"
        f.occurrence = dupes[raw] = dupes.get(raw, 0) + 1
    return findings, errors, len(modules)


# -- baseline ------------------------------------------------------------

class Baseline:
    """Checked-in grandfather list: every entry names a finding by
    fingerprint and MUST carry a justification (the 'empty-or-justified'
    acceptance law — an unexplained suppression is itself a finding)."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except OSError as exc:
            raise LintError(f"cannot read baseline {path}: {exc}") from exc
        except ValueError as exc:
            raise LintError(f"baseline {path} is not valid JSON: {exc}") \
                from exc
        entries = data.get("findings", [])
        for e in entries:
            if not e.get("fingerprint"):
                raise LintError(
                    f"baseline {path}: entry missing fingerprint: {e}")
            if not str(e.get("justification", "")).strip():
                raise LintError(
                    f"baseline {path}: entry {e.get('fingerprint')} "
                    f"({e.get('rule')} in {e.get('path')}) has no "
                    "justification — baselines must explain themselves")
        return cls(entries)

    def split(self, findings: Sequence[Finding]) \
            -> Tuple[List[Finding], List[Finding], List[dict]]:
        """(new, baselined, stale entries)."""
        by_fp = {e["fingerprint"]: e for e in self.entries}
        new, base = [], []
        seen = set()
        for f in findings:
            if f.fingerprint in by_fp:
                base.append(f)
                seen.add(f.fingerprint)
            else:
                new.append(f)
        stale = [e for e in self.entries if e["fingerprint"] not in seen]
        return new, base, stale

    @staticmethod
    def render(findings: Sequence[Finding],
               justifications: Optional[Dict[str, str]] = None) -> str:
        """A baseline document for `findings`; justification defaults to
        an empty string the author must fill in (the loader enforces it)."""
        justifications = justifications or {}
        entries = [{
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "symbol": f.symbol,
            "message": f.message,
            "justification": justifications.get(f.fingerprint, ""),
        } for f in findings]
        return json.dumps({"version": 1, "findings": entries}, indent=2,
                          sort_keys=False) + "\n"
