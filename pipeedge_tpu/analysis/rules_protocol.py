"""DCN protocol-table rules: unique `_MSG_*` ids, no orphan ids, 503s
carry Retry-After.

comm/dcn.py's wire protocol is a hand-maintained table of `_MSG_*`
constants dispatched by an if/elif chain in `_reader_loop`: a colliding
id silently routes one message type into another's handler (PL401), and a
constant nobody dispatches is a frame the reader logs as "unknown" and
drops (PL402 — `dcn._check_protocol_table()` enforces the same law at
import time). PL403 is PR 7's serving-plane audit as a machine check:
every 503 response names a Retry-After, because a bare 503 teaches
clients to hammer.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List

from .lint import Finding, Module, Rule, SEVERITY_ERROR


class MsgIdCollision(Rule):
    id = "PL401"
    name = "msg-id-collision"
    severity = SEVERITY_ERROR
    fix_hint = "pick the next unused integer for the new _MSG_ constant"
    rationale = ("two _MSG_ constants sharing an id silently route one "
                 "frame type into the other's dispatch arm")

    def check(self, module: Module) -> Iterator[Finding]:
        by_id: Dict[int, List[str]] = {}
        nodes: Dict[str, ast.AST] = {}
        for node in module.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Name) and t.id.startswith("_MSG_")):
                continue
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                by_id.setdefault(node.value.value, []).append(t.id)
                nodes[t.id] = node
        for msg_id, names in sorted(by_id.items()):
            for name in names[1:]:
                yield self.finding(
                    module, nodes[name],
                    f"{name} reuses protocol id {msg_id} "
                    f"(already taken by {names[0]})")


class MsgIdUnhandled(Rule):
    id = "PL402"
    name = "msg-id-unhandled"
    severity = SEVERITY_ERROR
    fix_hint = ("add the dispatch arm (and sender) for the new message "
                "type, or delete the dead constant")
    rationale = ("a _MSG_ constant referenced nowhere else is a frame "
                 "type the reader drops as 'unknown frame type'")

    def check(self, module: Module) -> Iterator[Finding]:
        defined: Dict[str, ast.AST] = {}
        uses: Dict[str, int] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Name) and node.id.startswith("_MSG_"):
                if isinstance(node.ctx, ast.Store):
                    defined[node.id] = node
                else:
                    uses[node.id] = uses.get(node.id, 0) + 1
        for name, node in sorted(defined.items()):
            if uses.get(name, 0) == 0:
                yield self.finding(
                    module, node,
                    f"protocol constant {name} is defined but never "
                    f"dispatched or sent")


class MissingRetryAfter(Rule):
    id = "PL403"
    name = "missing-retry-after"
    severity = SEVERITY_ERROR
    fix_hint = ("attach a Retry-After header (serve.py retry_after_hint() "
                "is the shared source) on every 503 path")
    rationale = ("a 503 without Retry-After turns graceful shedding into "
                 "a client retry storm (docs/SERVING.md audit, PR 7)")

    _SEND_NAMES = ("send", "_send", "send_response", "send_error",
                   "respond")

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            fname = func.attr if isinstance(func, ast.Attribute) \
                else (func.id if isinstance(func, ast.Name) else "")
            if fname not in self._SEND_NAMES:
                continue
            if not any(isinstance(a, ast.Constant) and a.value == 503
                       for a in node.args):
                continue
            call_src = module.segment(node)
            if "retry_after" in call_src.lower() \
                    or "retry-after" in call_src.lower():
                continue
            # the header may be attached right after (send_response(503)
            # ... send_header("Retry-After", ...)): accept a mention in
            # the few lines following the call — but NOT anywhere in the
            # enclosing function, where one compliant 503 path would
            # silently immunize every other 503 path beside it
            end = getattr(node, "end_lineno", node.lineno)
            window = "\n".join(module.lines[node.lineno - 1:end + 5])
            if "retry-after" in window.lower() \
                    or "retry_after" in window.lower():
                continue
            yield self.finding(
                module, node,
                "503 response without a Retry-After hint")


RULES = (MsgIdCollision, MsgIdUnhandled, MissingRetryAfter)
