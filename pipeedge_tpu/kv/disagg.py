"""Disaggregated serving: a prefill fleet feeding a decode fleet.

The O(S^2) prompt pass and the O(1)-per-token decode step have opposite
resource shapes: prefill is a compute burst that, colocated, steals
stage-time from every in-flight decode wave (one long prompt bumps
every other tenant's inter-token latency — the p99 coupling ROADMAP
item 2 names). The split:

- **PrefillFleet** owns a DEDICATED `DecodePipeline` (same weights,
  its own compiled programs and devices) and runs ONLY prompt passes —
  `prefill()` returns a ship handle: per-stage KV rows + final logits
  (kv/ship.py). Concurrency is bounded (each in-flight prefill holds
  dense prompt-sized buffers until shipped).
- The DECODE executors admit the handle through
  `PagedKvBackend.admit` (`shipped=`): pages are charged, the rows land
  by gather/scatter, the first token is picked decode-side from the
  shipped logits with the request's own rng — so disaggregated token
  streams are IDENTICAL to colocated ones (tests/test_kv_plane.py's
  loopback acceptance).

Ship paths mirror the PR 6 transport tiers: `local` hands device arrays
over in-process (the colocated-fleet loopback — zero serialization);
`wire` pushes real bytes through the v2 codec + a loopback socket
(int8 at `ship_bits=8`, CRC-verified) — the single-process stand-in for
a cross-host prefill fleet, exercising every byte of the wire path.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..telemetry import metrics as prom
from . import ship


class PrefillFleet:
    """Prompt passes on a dedicated pipeline, results shipped as KV.

    `max_concurrent` bounds in-flight prefills (each holds dense
    prompt-length KV until its handle is consumed); `path` picks the
    ship transport ("local" | "wire"); `ship_bits` quantizes KV wire
    bytes (0 exact — the parity setting; 8 = int8 block-scaled)."""

    def __init__(self, pipe, path: str = "local", ship_bits: int = 0,
                 max_concurrent: int = 2,
                 registry: Optional[prom.Registry] = None):
        if path not in ship.SHIP_PATHS:
            raise ValueError(f"unknown ship path {path!r} (expected one "
                             f"of {ship.SHIP_PATHS})")
        if ship_bits not in (0, 8):
            raise ValueError(f"ship_bits must be 0 or 8, got {ship_bits}")
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        if pipe.cache_bits:
            raise ValueError("the prefill fleet ships fp KV rows; int8 "
                             "CACHES don't ship (quantize the wire with "
                             "ship_bits=8 instead)")
        self.pipe = pipe
        self.path = path
        self.ship_bits = int(ship_bits)
        self._slots = threading.Semaphore(max_concurrent)
        reg = prom.REGISTRY if registry is None else registry
        self.m_prefills = reg.counter(
            "pipeedge_kv_prefills_total",
            "prompt passes run by the prefill fleet")
        self.m_prefills.declare()
        self.m_ship_bytes = reg.counter(
            "pipeedge_kv_ship_bytes_total",
            "KV bytes shipped prefill fleet -> decode fleet, by path "
            "(local = in-process array hand-off, estimated; wire = "
            "serialized v2 frame bytes through the loopback socket)")
        for p in ship.SHIP_PATHS:
            self.m_ship_bytes.declare(path=p)

    def prefill(self, ids, rid: Optional[str] = None) -> dict:
        """Run one prompt batch `[B, S]` through the prefill pipeline
        and ship the result; returns the decode-side install handle
        (`PagedKvBackend.admit`'s `shipped=`). Blocks while
        `max_concurrent` prefills are in flight."""
        ids = jnp.asarray(ids, jnp.int32)
        srid = None if rid is None else str(rid)
        with self._slots:
            with telemetry.span("kv", "prefill", rid=srid):
                out, caches = self.pipe._prefill(ids)
                logits = out[:, -1]
            self.m_prefills.inc()
            prompt_len = ids.shape[1]
            with telemetry.span("kv", f"ship:{self.path}", rid=srid):
                if self.path == "local":
                    # in-process hand-off: the arrays ARE the handle
                    handle = {
                        "stage_rows": [
                            {n: c[n][:, :, :prompt_len]
                             for n in ("k", "v")} for c in caches],
                        "logits": logits, "prompt_len": prompt_len,
                    }
                    self.m_ship_bytes.inc(
                        sum(int(np.prod(a.shape)) * a.dtype.itemsize
                            for row in handle["stage_rows"]
                            for a in row.values()), path="local")
                    return handle
                frames = ship.encode_kv_ship(caches, prompt_len, logits,
                                             bits=self.ship_bits)
                blob = ship.frames_to_bytes(frames)
                self.m_ship_bytes.inc(len(blob), path="wire")
                back = ship.frames_from_bytes(ship.ship_over_socket(blob))
                return ship.decode_kv_ship(back, self.pipe.dtype)
