"""KV-page shipping: finished prefill KV rows as wire frames.

The disaggregation split (kv/disagg.py) runs prompt passes on a PREFILL
fleet and decode waves on a DECODE fleet; what travels between them is
each request's per-stage KV rows `[n_blocks, B, prompt_len, H, Dh]`
plus the last stage's final-position logits `[B, V]` (the pick stays on
the decode side, with the request's own rng — disaggregated tokens are
identical to colocated ones).

The payload rides the SAME wire-v2 device-encoded frames activations
already use (comm/wire.py): one v2 frame per stage — int8 block-scaled
quads at `bits=8` (4x fewer KV bytes on the wire, the PR 6/9 codec
lineage, bit-identical packing across the XLA/native/fused encoders),
raw arrays at `bits=0` (exact; the parity-acceptance setting) — with
the optional CRC integrity trailer (PIPEEDGE_WIRE_CRC) verified on
decode like any other v2 frame. `frames_to_bytes`/`frames_from_bytes`
give the byte-stream form for the socket path; a colocated prefill
fleet hands the arrays over in-process instead (the transport-tier
split of docs/DCN_WIRE.md applied to KV).

Logits always ship exact (bit 0): quantizing the pick's input would
change tokens, not just bytes — KV rows are the bandwidth, logits are
one row.
"""
from __future__ import annotations

import io
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..comm import wire

# distinct from WIRE_V2_MAGIC (-2): a kv-ship bundle opens with its own
# sentinel so a misrouted frame fails loudly, not as a shape error
KV_SHIP_MAGIC = -7
KV_SHIP_VERSION = 1
_LEAVES = ("k", "v")     # fp cache leaves, in shipped order

SHIP_PATHS = ("local", "wire")


def encode_kv_ship(caches: Sequence[Dict], prompt_len: int, logits,
                   bits: int = 0, crc: Optional[bool] = None) \
        -> List[np.ndarray]:
    """Per-stage dense caches (+ final logits) -> one flat tensor list:
    `[kv_header, logits, stage0 v2 frame..., stage1 v2 frame..., ...]`.
    Only the first `prompt_len` cache positions ship. fp caches only —
    int8 caches' scale rows have no codec lane (and re-quantizing int8
    would compound error); quantize on the WIRE with `bits=8` instead."""
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    if bits not in (0, 8):
        raise ValueError(f"kv ship bits must be 0 (exact) or 8, "
                         f"got {bits}")
    logits = np.asarray(logits, np.float32)
    if logits.ndim != 2:
        raise ValueError(f"logits must be [B, V], got {logits.shape}")
    frames: List[np.ndarray] = []
    for cache in caches:
        if set(cache) != set(_LEAVES):
            raise ValueError(
                "kv ship covers fp caches (leaves k/v); this cache has "
                f"{sorted(cache)} — int8 CACHES don't ship (use "
                "bits=8 to quantize on the wire instead)")
        rows = tuple(cache[name][:, :, :prompt_len] for name in _LEAVES)
        frames.extend(wire.wire_encode_device(rows, bits,
                                              crc=crc).finalize())
    header = np.asarray([KV_SHIP_MAGIC, KV_SHIP_VERSION, bits,
                         len(caches), prompt_len, logits.shape[0]],
                        np.int64)
    return [header, logits] + frames


def _v2_span(tensors: Sequence[np.ndarray], start: int) -> int:
    """Tensor count of the v2 frame starting at `tensors[start]`."""
    header = np.asarray(tensors[start])
    if not (header.ndim == 1 and header.size >= 5
            and header.dtype.kind == "i"
            and int(header[0]) == wire.WIRE_V2_MAGIC):
        raise ValueError("malformed kv-ship bundle: expected a wire-v2 "
                         f"frame header at tensor {start}")
    bit, flags, n_payload = (int(header[2]), int(header[3]),
                             int(header[4]))
    span = 1 + (n_payload if bit == 0 else 4 * n_payload)
    if flags & wire.FLAG_CRC:
        span += 1
    return span


def decode_kv_ship(tensors: Sequence[np.ndarray], dtype) -> dict:
    """Inverse of `encode_kv_ship`: returns the install handle
    `{"stage_rows": [{k, v} per stage], "logits", "prompt_len"}`
    (kv/backend.py `_install_shipped`'s input). CRC-flagged frames are
    verified; corruption raises `wire.WireCorruptError`."""
    header = np.asarray(tensors[0])
    if not (header.ndim == 1 and header.size >= 6
            and int(header[0]) == KV_SHIP_MAGIC):
        raise ValueError("not a kv-ship bundle (bad magic header)")
    if int(header[1]) != KV_SHIP_VERSION:
        raise ValueError(f"kv-ship version {int(header[1])} "
                         f"(this decoder speaks {KV_SHIP_VERSION})")
    n_stages, prompt_len = int(header[3]), int(header[4])
    logits = np.asarray(tensors[1], np.float32)
    stage_rows: List[Dict] = []
    at = 2
    for _ in range(n_stages):
        span = _v2_span(tensors, at)
        payload = wire.wire_decode(list(tensors[at:at + span]), dtype)
        at += span
        if not isinstance(payload, tuple) or len(payload) != len(_LEAVES):
            raise ValueError("malformed kv-ship stage frame: expected "
                             f"{len(_LEAVES)} payload tensors")
        stage_rows.append(dict(zip(_LEAVES, payload)))
    if at != len(tensors):
        raise ValueError(f"kv-ship bundle has {len(tensors) - at} "
                         "trailing tensor(s)")
    return {"stage_rows": stage_rows, "logits": logits,
            "prompt_len": prompt_len}


# -- byte-stream form (the socket path) ----------------------------------

def frames_to_bytes(tensors: Sequence[np.ndarray]) -> bytes:
    """Tensor list -> one bytes blob (npz container, order-preserving)."""
    buf = io.BytesIO()
    np.savez(buf, **{f"t{i}": np.asarray(t)
                     for i, t in enumerate(tensors)})
    return buf.getvalue()


def frames_from_bytes(blob: bytes) -> List[np.ndarray]:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        return [z[f"t{i}"] for i in range(len(z.files))]


def ship_over_socket(blob: bytes) -> bytes:
    """Round one blob through a real loopback socket pair (length-
    prefixed), a writer thread feeding the far end — the wire-path
    exercise for tests/CI and the `--disaggregate wire` loopback: the
    bytes genuinely leave and re-enter the process boundary machinery,
    so framing/CRC bugs surface here, not on a multi-host fleet."""
    a, b = socket.socketpair()
    try:
        def feed():
            with a:
                a.sendall(struct.pack("!Q", len(blob)))
                a.sendall(blob)

        t = threading.Thread(target=feed, daemon=True,
                             name="kv-ship-feeder")
        t.start()
        with b:
            need = struct.unpack("!Q", _read_exact(b, 8))[0]
            out = _read_exact(b, need)
        t.join(timeout=60)
        return out
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass


def _read_exact(sock: socket.socket, n: int) -> bytes:
    # THE exact-read primitive is comm/dcn.py's (recv_into, no
    # flattening copy) — one implementation, reused lazily so importing
    # the ship codec never pulls the DCN runtime in
    from ..comm.dcn import _recv_exact
    return bytes(_recv_exact(sock, n))
