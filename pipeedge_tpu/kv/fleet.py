"""Cross-process prefill fleet: leases, fault-tolerant KV shipping.

PR 14's `PrefillFleet` (kv/disagg.py) proved the disaggregation math —
decode p99 under a prefill burst 1054 -> 171 ms — but ran both fleets in
ONE process over loopback, and its ship path had no retry, no death
handling, and no fallback. This module promotes the prefill fleet to
real separate processes (`tools/prefill_worker.py` ranks over DCN
sockets, the PR 6 transport plane) and makes the ship edge survive
every fault the chaos grammar can throw at it
(docs/FAULT_TOLERANCE.md, disaggregated serving lifecycle):

- **Lease/ack protocol**: every prompt pass is tracked per-request. The
  decode side (`RemotePrefillFleet.prefill`) registers a LEASE
  (lease id + attempt number + deadline), sends it to a live prefill
  rank, and waits; the worker (`PrefillWorkerLoop`) runs the prompt
  pass and acks with the ship bundle (kv/ship.py wire-v2 frames, CRC
  when armed). A lease that is not acked within its deadline is
  RE-DISPATCHED to a surviving rank.
- **Fault matrix**: ship timeout -> re-dispatch; CRC failure on decode
  (`wire.WireCorruptError`) -> bounded re-ship (the prompt pass is
  deterministic, so a re-run IS a resend); prefill-peer death (stream
  error or missed heartbeats, the PR 2/12 liveness plane) -> in-flight
  leases on that rank resolve immediately as failed and re-dispatch;
  every path exhausted -> `PrefillUnavailable`, which the serving layer
  converts to COLOCATED prefill (the decode executor runs the prompt
  pass itself — token parity either way, tests/test_kv_fleet.py).
- **Zombie fencing**: acks carry (lease id, attempt). A late ack for a
  lease that was re-dispatched, completed, or abandoned — e.g. from a
  slow or restarted worker incarnation — is dropped and counted, never
  installed. Below this sits the DCN epoch fence (PR 5): frames from a
  dead incarnation never reach the reply queue at all.
- **Readmission**: a restarted worker re-execs with DCN_EPOCH+1 and
  JOINs (announce_join); the fleet's rejoin handler puts the rank back
  in rotation — the serve supervisor (tools/serve.py `--disaggregate
  process`) respawns dead workers to close the loop.

The wire protocol rides `send_tensors`/`recv_tensors` data frames on
two dedicated channels (no new `_MSG_` types — the transport's own
protocol table is untouched):

    decode -> worker  CH_LEASE  [lease_hdr, ids]
    worker -> decode  CH_SHIP   [ack_hdr, *encode_kv_ship(...) frames]

`lease_hdr` = int64 [LEASE_MAGIC, lease_id, attempt, ship_bits, crc,
deadline_ms]; `ack_hdr` = int64 [ACK_MAGIC, lease_id, attempt, status].
CRC verification happens where the bytes are consumed
(`decode_kv_ship` verifies each stage frame's trailer), so a corrupt
ship surfaces as a typed error on the decode side, not silent garbage.
"""
from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..comm import wire
from ..telemetry import metrics as prom
from ..utils.threads import make_lock
from . import ship

logger = logging.getLogger(__name__)

# ship-plane data channels (comm/dcn.py CHANNEL_*: 0 data, 1 results,
# 2 feed, 3 bids are taken; base_channel folds mod 8, so these must
# stay below CHANNEL_ROUND_PARITY). Cancels ride their OWN channel:
# a cancel queued behind pending leases on CH_LEASE would arrive only
# after the stale lease it exists to stop had already run
CH_LEASE = 4
CH_SHIP = 5
CH_CANCEL = 6

LEASE_MAGIC = -11
ACK_MAGIC = -12
CANCEL_MAGIC = -13
ACK_OK = 0
ACK_ERROR = 1

# lease outcomes the per-fleet counter tracks (pre-declared, PL501)
LEASE_OUTCOMES = ("shipped", "redispatched", "corrupt_retry", "fallback")


class PrefillUnavailable(RuntimeError):
    """No prefill rank could complete this prompt pass (every live rank
    timed out, died, or shipped corrupt frames past the retry budget —
    or none is live at all). The serving layer degrades the request to
    COLOCATED prefill: the decode executor runs the prompt pass itself,
    so the request survives with identical tokens, paying only the p99
    isolation the split existed to buy."""


def lease_header(lease_id: int, attempt: int, ship_bits: int,
                 crc: bool, deadline_ms: float) -> np.ndarray:
    return np.asarray([LEASE_MAGIC, int(lease_id), int(attempt),
                       int(ship_bits), int(bool(crc)),
                       int(max(0, deadline_ms))], np.int64)


def parse_lease_header(t) -> dict:
    hdr = np.asarray(t)
    if not (hdr.ndim == 1 and hdr.size >= 6 and hdr.dtype.kind == "i"
            and int(hdr[0]) == LEASE_MAGIC):
        raise ValueError("not a prefill lease frame (bad magic header)")
    return {"lease_id": int(hdr[1]), "attempt": int(hdr[2]),
            "ship_bits": int(hdr[3]), "crc": bool(hdr[4]),
            "deadline_ms": int(hdr[5])}


def cancel_header(lease_id: int) -> np.ndarray:
    return np.asarray([CANCEL_MAGIC, int(lease_id)], np.int64)


def parse_cancel_header(t) -> int:
    hdr = np.asarray(t)
    if not (hdr.ndim == 1 and hdr.size >= 2 and hdr.dtype.kind == "i"
            and int(hdr[0]) == CANCEL_MAGIC):
        raise ValueError("not a prefill lease cancel (bad magic header)")
    return int(hdr[1])


def ack_header(lease_id: int, attempt: int, status: int) -> np.ndarray:
    return np.asarray([ACK_MAGIC, int(lease_id), int(attempt),
                       int(status)], np.int64)


def parse_ack_header(t) -> dict:
    hdr = np.asarray(t)
    if not (hdr.ndim == 1 and hdr.size >= 4 and hdr.dtype.kind == "i"
            and int(hdr[0]) == ACK_MAGIC):
        raise ValueError("not a prefill ship ack (bad magic header)")
    return {"lease_id": int(hdr[1]), "attempt": int(hdr[2]),
            "status": int(hdr[3])}


class _Lease:
    """One tracked prompt pass: the decode-side record an ack resolves.
    `attempt` is the fence — an ack carrying any other attempt number is
    a zombie (the lease was since re-dispatched or abandoned)."""

    __slots__ = ("lease_id", "attempt", "rank", "rid", "event",
                 "tensors", "error")

    def __init__(self, lease_id: int, attempt: int, rank: int, rid):
        self.lease_id = lease_id
        self.attempt = attempt
        self.rank = rank
        self.rid = rid
        self.event = threading.Event()
        self.tensors: Optional[List[np.ndarray]] = None
        self.error: Optional[str] = None


class RemotePrefillFleet:
    """Decode-side client of a cross-process prefill fleet.

    Owns the ship edge over an externally-constructed `DistDcnContext`
    (this process is the decode rank; `ranks` are the prefill workers).
    Interface-compatible with the in-process `PrefillFleet`:
    `prefill(ids, rid) -> ship handle` — but every call is a LEASE that
    survives worker death, ship timeout, and wire corruption, degrading
    to `PrefillUnavailable` (colocated fallback) only when every rank
    and retry is exhausted.

    `lease_timeout_s` is the per-dispatch ack deadline; `max_attempts`
    bounds total dispatches per prompt (re-dispatches + corrupt
    re-ships). `flight_note(event, **fields)` is the serving layer's
    flight-recorder hook (kept as a callable so kv/ never imports the
    recorder)."""

    def __init__(self, ctx, ranks: Sequence[int], dtype,
                 ship_bits: int = 0, crc: Optional[bool] = None,
                 lease_timeout_s: float = 30.0, max_attempts: int = 3,
                 max_concurrent: Optional[int] = None,
                 heartbeat_interval: float = 0.0,
                 heartbeat_miss: int = 5,
                 registry: Optional[prom.Registry] = None,
                 flight_note: Optional[Callable] = None):
        if ship_bits not in (0, 8):
            raise ValueError(f"ship_bits must be 0 or 8, got {ship_bits}")
        if not ranks:
            raise ValueError("a prefill fleet needs at least one rank")
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.ctx = ctx
        self.ranks = tuple(int(r) for r in ranks)
        self.dtype = dtype
        self.ship_bits = int(ship_bits)
        self.crc = wire.crc_enabled() if crc is None else bool(crc)
        self.lease_timeout_s = float(lease_timeout_s)
        self.max_attempts = int(max_attempts)
        # a lease dispatch must never out-dial its own deadline: a
        # worker killed before it EVER connected has no fast-refused
        # path in _ensure_conn, and the default 60s connect budget
        # would wedge the dispatching thread far past the lease — the
        # workers' listeners come up before their model build, so a
        # healthy fleet always dials in milliseconds anyway
        ctx.CONNECT_TIMEOUT = min(ctx.CONNECT_TIMEOUT,
                                  max(5.0, self.lease_timeout_s))
        self.flight_note = flight_note
        self._lock = make_lock("kv.fleet")
        self._live = set(self.ranks)
        self._leases: Dict[int, _Lease] = {}
        self._next_lease = 0
        self._rr = 0
        self._stop = threading.Event()
        # in-flight bound: workers process leases serially, so anything
        # past ~2 per rank only queues in socket buffers
        self._slots = threading.Semaphore(
            max_concurrent if max_concurrent is not None
            else 2 * len(self.ranks))
        reg = prom.REGISTRY if registry is None else registry
        self.m_leases = reg.counter(
            "pipeedge_kv_prefill_leases_total",
            "prefill lease dispatches by outcome (shipped = acked + "
            "installed; redispatched = timeout/death moved it to "
            "another rank; corrupt_retry = CRC failure triggered a "
            "re-ship; fallback = exhausted, degraded to colocated "
            "prefill — docs/FAULT_TOLERANCE.md disaggregated serving)")
        for outcome in LEASE_OUTCOMES:
            self.m_leases.declare(outcome=outcome)
        self.m_corrupt = reg.counter(
            "pipeedge_kv_ship_corrupt_total",
            "shipped KV bundles that failed CRC verification on decode")
        self.m_corrupt.declare()
        self.m_zombie = reg.counter(
            "pipeedge_kv_zombie_ships_dropped_total",
            "ship acks dropped by the lease fence (unknown lease or "
            "stale attempt: the lease was re-dispatched, completed, or "
            "abandoned before this ack arrived)")
        self.m_zombie.declare()
        self.m_live = reg.gauge(
            "pipeedge_kv_prefill_ranks_live",
            "prefill ranks currently in dispatch rotation")
        self.m_live.set(len(self._live))
        ctx.register_peer_death_handler(self._on_peer_death)
        ctx.register_peer_rejoin_handler(self._on_peer_rejoin)
        # PR 6 transport-path negotiation on the LEASE edge (decode ->
        # worker): colocated test fleets get the in-process hand-off,
        # real worker processes land on zerocopy/socket_v2 — best
        # effort, the socket truth stands when a worker is slow to
        # answer (exactly runtime.py's per-round stance)
        for r in self.ranks:
            try:
                self.ctx.negotiate_edge_path(r, timeout=5.0)
            except Exception as exc:   # noqa: BLE001 — queue.Empty /
                # OSError / a worker mid-build: keep the socket path
                logger.info("lease edge ->r%d: path handshake skipped "
                            "(%s)", r, exc)
        if heartbeat_interval > 0:
            ctx.start_heartbeat(self.ranks, interval=heartbeat_interval,
                                miss_threshold=heartbeat_miss)
        # one ack reader per worker rank: a dead rank's reader idles
        # (ConnectionError -> backoff) and resumes after a rejoin
        self._readers = [
            threading.Thread(target=self._ack_loop, args=(r,),
                             daemon=True, name=f"kv-ship-ack-r{r}")
            for r in self.ranks]
        for t in self._readers:
            t.start()

    # -- membership -------------------------------------------------------

    def _note(self, event: str, **fields) -> None:
        if self.flight_note is not None:
            try:
                self.flight_note(event, **fields)
            except Exception:   # noqa: BLE001 — observability must never
                pass            # fail the data path

    def _on_peer_death(self, rank: int) -> None:
        if rank not in self.ranks:
            return
        stranded: List[_Lease] = []
        with self._lock:
            self._live.discard(rank)
            self.m_live.set(len(self._live))
            stranded = [ls for ls in self._leases.values()
                        if ls.rank == rank and not ls.event.is_set()]
        logger.warning("prefill rank %d died; %d in-flight lease(s) "
                       "re-dispatching", rank, len(stranded))
        self._note("prefill_rank_dead", rank=rank,
                   stranded=len(stranded))
        # resolve stranded leases as failed NOW: their waiters re-dispatch
        # immediately instead of burning the full lease timeout
        for ls in stranded:
            ls.error = f"prefill rank {rank} died"
            ls.event.set()

    def _on_peer_rejoin(self, rank: int, epoch: int) -> None:
        if rank not in self.ranks:
            return
        with self._lock:
            self._live.add(rank)
            self.m_live.set(len(self._live))
        logger.info("prefill rank %d readmitted (epoch %d)", rank, epoch)
        self._note("prefill_rank_readmitted", rank=rank, epoch=epoch)

    def live_ranks(self) -> frozenset:
        with self._lock:
            return frozenset(self._live)

    def _pick_rank(self, avoid: Optional[int] = None) -> int:
        """Round-robin over live ranks, skipping `avoid` (the rank that
        just failed this lease) when any alternative exists."""
        with self._lock:
            live = sorted(self._live)
            if not live:
                raise PrefillUnavailable(
                    "no live prefill rank (all "
                    f"{len(self.ranks)} worker(s) dead)")
            pool = [r for r in live if r != avoid] or live
            self._rr += 1
            return pool[self._rr % len(pool)]

    # -- the ack plane ----------------------------------------------------

    def _ack_loop(self, rank: int) -> None:
        while not self._stop.is_set():
            try:
                tensors = self.ctx.recv_tensors(rank, timeout=0.5,
                                                channel=CH_SHIP)
            except queue_mod.Empty:
                continue
            except (ConnectionError, OSError):
                # rank dead: idle until a rejoin revives the queue
                if self._stop.wait(0.5):
                    return
                continue
            try:
                ack = parse_ack_header(tensors[0])
            except (ValueError, IndexError):
                logger.error("malformed ship ack from rank %d dropped",
                             rank)
                continue
            self._resolve(ack, tensors[1:])

    def _resolve(self, ack: dict, tensors: List[np.ndarray]) -> None:
        """Deliver an ack to its lease — or fence it: an unknown lease
        id or a stale attempt number means the lease moved on (re-
        dispatched, completed, abandoned) and this ack is a ZOMBIE that
        must never install."""
        with self._lock:
            ls = self._leases.get(ack["lease_id"])
            stale = ls is None or ls.attempt != ack["attempt"] \
                or ls.event.is_set()
        if stale:
            self.m_zombie.inc()
            logger.warning(
                "zombie ship ack dropped (lease %d attempt %d)",
                ack["lease_id"], ack["attempt"])
            self._note("zombie_ship_dropped", lease=ack["lease_id"],
                       attempt=ack["attempt"])
            return
        if ack["status"] != ACK_OK:
            ls.error = f"prefill rank {ls.rank} errored the lease"
        else:
            ls.tensors = tensors
        ls.event.set()

    # -- the lease path ---------------------------------------------------

    def prefill(self, ids, rid: Optional[str] = None) -> dict:
        """One tracked prompt pass: returns the decode-side install
        handle (`PagedKvBackend.admit`'s `shipped=`), or raises
        `PrefillUnavailable` after every rank/retry is exhausted — the
        caller's cue to run the prompt pass colocated."""
        ids_t = np.asarray(ids, np.int64)
        srid = None if rid is None else str(rid)
        with self._lock:
            self._next_lease += 1
            lease_id = self._next_lease
        last_rank: Optional[int] = None
        with self._slots:
            for attempt in range(1, self.max_attempts + 1):
                try:
                    rank = self._pick_rank(avoid=last_rank)
                except PrefillUnavailable:
                    # the whole fleet is dead: degrade immediately —
                    # burning the remaining attempts against nothing
                    # would just stretch the request's first-token time
                    self.m_leases.inc(outcome="fallback")
                    self._note("prefill_fallback", rid=srid,
                               lease=lease_id, reason="no_live_rank")
                    raise
                ls = _Lease(lease_id, attempt, rank, srid)
                with self._lock:
                    self._leases[lease_id] = ls
                try:
                    status, handle = self._dispatch_once(ls, ids_t)
                finally:
                    with self._lock:
                        self._leases.pop(lease_id, None)
                if status == "ok":
                    self.m_leases.inc(outcome="shipped")
                    return handle
                last_rank = rank
                if attempt < self.max_attempts:
                    # outcome counted HERE, where the retry actually
                    # happens — the FINAL failed attempt re-dispatches
                    # nothing and must not inflate the counter
                    self.m_leases.inc(
                        outcome="corrupt_retry" if status == "corrupt"
                        else "redispatched")
        self.m_leases.inc(outcome="fallback")
        self._note("prefill_fallback", rid=srid, lease=lease_id,
                   attempts=self.max_attempts)
        raise PrefillUnavailable(
            f"prefill lease {lease_id} exhausted {self.max_attempts} "
            f"attempt(s) (last rank {last_rank})")

    def _dispatch_once(self, ls: _Lease, ids_t: np.ndarray) \
            -> tuple:
        """One lease dispatch: send, await ack, decode. Returns
        `("ok", handle)`, `("corrupt", None)` (CRC failure — a re-ship
        can recover it), or `("failed", None)` (timeout / death /
        worker error / malformed); the caller decides whether another
        attempt follows and counts the outcome accordingly."""
        hdr = lease_header(ls.lease_id, ls.attempt, self.ship_bits,
                           self.crc, self.lease_timeout_s * 1e3)
        with telemetry.span("kv", f"lease:r{ls.rank}", rid=ls.rid):
            try:
                self.ctx.send_tensors(ls.rank, [hdr, ids_t],
                                      channel=CH_LEASE)
            except (ConnectionError, OSError) as exc:
                logger.warning("lease %d send to rank %d failed: %s",
                               ls.lease_id, ls.rank, exc)
                return "failed", None
            if not ls.event.wait(self.lease_timeout_s):
                logger.warning(
                    "lease %d timed out on rank %d after %.1fs",
                    ls.lease_id, ls.rank, self.lease_timeout_s)
                self._note("prefill_lease_timeout", rank=ls.rank,
                           lease=ls.lease_id, rid=ls.rid)
                # best-effort cancel: if the stale lease is still
                # queued at the worker (a fault window backs leases
                # up), it must be SKIPPED there, not fully executed
                # into a zombie ack — capacity is scarcest exactly then
                try:
                    self.ctx.send_tensors(
                        ls.rank, [cancel_header(ls.lease_id)],
                        channel=CH_CANCEL)
                except (ConnectionError, OSError):
                    pass       # rank gone: nothing left to cancel
                return "failed", None
        if ls.error is not None:
            return "failed", None
        try:
            return "ok", ship.decode_kv_ship(ls.tensors, self.dtype)
        except wire.WireCorruptError as exc:
            # wire corruption made it past the transport (or CRC is the
            # only integrity layer on this edge): bounded re-ship — the
            # prompt pass is deterministic, so a re-run IS a resend
            self.m_corrupt.inc()
            logger.warning("lease %d ship from rank %d corrupt (%s); "
                           "re-shipping", ls.lease_id, ls.rank, exc)
            self._note("ship_corrupt", rank=ls.rank, lease=ls.lease_id)
            return "corrupt", None
        except (ValueError, IndexError) as exc:
            logger.error("lease %d ship from rank %d malformed: %s",
                         ls.lease_id, ls.rank, exc)
            return "failed", None

    def snapshot(self) -> dict:
        with self._lock:
            live = sorted(self._live)
            in_flight = sum(1 for ls in self._leases.values()
                            if not ls.event.is_set())
        return {
            "ranks": list(self.ranks),
            "live": live,
            "dead": sorted(set(self.ranks) - set(live)),
            "in_flight": in_flight,
            "leases": {o: int(self.m_leases.value(outcome=o))
                       for o in LEASE_OUTCOMES},
            "ship_corrupt_total": int(self.m_corrupt.value()),
            "zombies_dropped_total": int(self.m_zombie.value()),
        }

    def close(self) -> None:
        self._stop.set()
        # fail pending waiters fast (their retries will see no live rank)
        with self._lock:
            pending = list(self._leases.values())
            self._live.clear()
            self.m_live.set(0)
        for ls in pending:
            ls.error = "prefill fleet closed"
            ls.event.set()
        self.ctx.stop_heartbeat()
        for t in self._readers:
            t.join(timeout=5)


class PrefillWorkerLoop:
    """The worker side of the lease protocol: recv lease -> prompt pass
    -> ship ack. One loop, serial prompt passes (concurrency is the
    number of worker RANKS; a process-wide pool would just contend for
    the same host dispatch thread). `tools/prefill_worker.py` drives it
    as a standalone process; tests drive it in-process on its own
    context (the same frames either way)."""

    def __init__(self, pipe, ctx, decode_rank: int = 0):
        if pipe.cache_bits:
            raise ValueError("the prefill fleet ships fp KV rows; int8 "
                             "CACHES don't ship (quantize the wire with "
                             "ship bits instead)")
        self.pipe = pipe
        self.ctx = ctx
        self.decode_rank = int(decode_rank)
        self._stop = threading.Event()
        self._ship_path: Optional[str] = None
        self.leases_served = 0
        self.leases_cancelled = 0
        # cancelled lease ids, bounded: a cancel can arrive BEFORE its
        # lease (separate channels have no cross-ordering), so the set
        # must persist — and must not grow without bound
        self._cancelled: set = set()
        self._cancel_order: deque = deque(maxlen=256)

    def stop(self) -> None:
        self._stop.set()

    def _drain_cancels(self) -> None:
        """Pull every pending cancel off CH_CANCEL (non-blocking): the
        decode side cancels a lease it re-dispatched elsewhere, and a
        stale lease still queued here must be SKIPPED — running it
        would burn a full prompt pass into a zombie ack exactly when a
        fault window has made prefill capacity scarce."""
        while True:
            try:
                tensors = self.ctx.recv_tensors(self.decode_rank,
                                                timeout=0.0,
                                                channel=CH_CANCEL)
            except (queue_mod.Empty, ConnectionError, OSError):
                return
            try:
                lease_id = parse_cancel_header(tensors[0])
            except (ValueError, IndexError):
                continue
            if len(self._cancel_order) == self._cancel_order.maxlen:
                self._cancelled.discard(self._cancel_order[0])
            self._cancel_order.append(lease_id)
            self._cancelled.add(lease_id)

    def run(self) -> None:
        """Serve leases until stopped or the decode rank dies."""
        import jax.numpy as jnp
        while not self._stop.is_set():
            try:
                tensors = self.ctx.recv_tensors(self.decode_rank,
                                                timeout=0.5,
                                                channel=CH_LEASE)
            except queue_mod.Empty:
                continue
            except (ConnectionError, OSError):
                logger.info("prefill worker: decode rank %d gone; "
                            "exiting", self.decode_rank)
                return
            try:
                lease = parse_lease_header(tensors[0])
                ids = jnp.asarray(np.asarray(tensors[1]), jnp.int32)
            except (ValueError, IndexError) as exc:
                logger.error("malformed lease frame dropped: %s", exc)
                continue
            self._drain_cancels()
            if lease["lease_id"] in self._cancelled:
                self.leases_cancelled += 1
                logger.info("prefill lease %d cancelled before "
                            "execution; skipped", lease["lease_id"])
                continue
            t0 = time.monotonic()
            try:
                with telemetry.span("kv", f"prefill:l{lease['lease_id']}"):
                    out, caches = self.pipe._prefill(ids)
                    logits = out[:, -1]
                frames = ship.encode_kv_ship(
                    caches, ids.shape[1], np.asarray(logits, np.float32),
                    bits=lease["ship_bits"], crc=lease["crc"])
                reply = [ack_header(lease["lease_id"], lease["attempt"],
                                    ACK_OK)] + frames
            except Exception as exc:   # noqa: BLE001 — a poisoned prompt
                # must ack as an ERROR, not silence: silence costs the
                # decode side a full lease timeout per attempt
                logger.error("prefill lease %d failed: %s",
                             lease["lease_id"], exc)
                reply = [ack_header(lease["lease_id"], lease["attempt"],
                                    ACK_ERROR)]
            if self._ship_path is None:
                # PR 6 path negotiation on the SHIP edge (worker ->
                # decode), once, before the first ack: the decode rank
                # is provably up by now (it sent this lease)
                try:
                    self._ship_path = self.ctx.negotiate_edge_path(
                        self.decode_rank, timeout=5.0)
                except Exception as exc:   # noqa: BLE001 — keep socket
                    self._ship_path = "socket_v2"
                    logger.info("ship edge ->r%d: path handshake "
                                "skipped (%s)", self.decode_rank, exc)
            try:
                self.ctx.send_tensors(self.decode_rank, reply,
                                      channel=CH_SHIP)
            except (ConnectionError, OSError):
                logger.warning("ship ack for lease %d undeliverable "
                               "(decode rank gone?)", lease["lease_id"])
                continue
            self.leases_served += 1
            logger.info("prefill lease %d served in %.3fs",
                        lease["lease_id"], time.monotonic() - t0)
