"""Paged-KV execution backend for the serving executors.

`ContinuousBatcher` / `StageWorkerExecutor` (parallel/batcher.py) drive
per-request stage-steps; this backend replaces their dense per-request
cache slots with page-table indirection over the shared pool:

- **admit**: charge `ceil((prompt + new_tokens) / page_size)` pages per
  batch row (power-of-two bucketed to bound compiled cache shapes),
  walk the prefix trie for whole-page prompt reuse (B==1 requests), or
  install a prefill fleet's SHIPPED KV rows (kv/ship.py) so the decode
  fleet never runs a prompt pass at all.
- **run_stage**: gather the request's cache view from the page arena,
  dispatch the UNCHANGED compiled stage program (prefill / span / step —
  exactly `_run_stage`'s semantics, same `stage`/`exec{i}` spans), then
  scatter back only the pages the step actually wrote AND that the
  request privately owns — shared prefix pages are physically
  immutable.
- **release**: drop the request's page references; completed prompts'
  full pages were published to the trie at the end of their prompt
  pass, so the NEXT request with that prefix reuses them.

Numerics: the gathered view is `[n_blocks, B, pages * page_size, ...]`
instead of the dense `[.., max_len, ..]` — positions past the window
were fully masked in the dense path (exact softmax zeros), so the paged
path is TOKEN-IDENTICAL to the dense executors and to solo
`DecodePipeline.generate` runs for fp caches (tests/test_kv_plane.py
pins this); int8 caches carry the same quantization caveat as
`precompute_prefix` reuse.

Thread model: page/trie accounting locks live in pool/prefix; the
arena's read-modify-write (gather -> program dispatch -> scatter) is
serialized under one "kv.arena" lock — dispatch is async, so the hold
is host-side only.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..telemetry import metrics as prom
from ..utils.threads import make_lock
from .pool import KvPagePool, pages_for
from .prefix import PrefixTrie


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class PagedKvBackend:
    """The executors' cache provider: page tables instead of dense slots.

    `share_prefixes` arms the trie (single-row requests only — lockstep
    multi-row prompts have per-row token content); `bucket_pages` rounds
    each request's page span up to a power of two so the per-stage
    decode programs compile per page-count BUCKET, not per exact prompt
    length (the attend-window `attend_bucket` idea applied to the cache
    shape)."""

    def __init__(self, pipe, n_pages: int, page_size: int = 16,
                 pool: Optional[KvPagePool] = None,
                 trie: Optional[PrefixTrie] = None,
                 share_prefixes: bool = True,
                 bucket_pages: bool = True,
                 registry: Optional[prom.Registry] = None):
        self.pipe = pipe
        self.pool = pool if pool is not None else KvPagePool(
            pipe, n_pages, page_size, registry=registry)
        self.page_size = self.pool.page_size
        self.trie = trie if trie is not None else (
            PrefixTrie(self.pool, registry=registry)
            if share_prefixes else None)
        if self.trie is not None:
            self.pool.set_evict_hook(self.trie.evict_cold)
        self.bucket_pages = bool(bucket_pages)
        self._arena_lock = make_lock("kv.arena")
        self._n_stages = len(pipe.stages)

    # -- sizing -----------------------------------------------------------

    def pages_needed(self, prompt_len: int, new_tokens: int,
                     batch: int = 1) -> int:
        per_row = pages_for(prompt_len + new_tokens, self.page_size)
        if self.bucket_pages:
            per_row = min(_next_pow2(per_row),
                          pages_for(self.pipe.max_len, self.page_size))
        return per_row * batch

    def tokens_needed(self, prompt_len: int, new_tokens: int,
                      batch: int = 1) -> int:
        """The admission token charge (pages x page_size: what the
        request actually reserves, bucketing included)."""
        return self.pages_needed(prompt_len, new_tokens,
                                 batch) * self.page_size

    def can_admit(self, req) -> bool:
        """Whether `admit` would succeed right now (free + evictable
        cold pages cover the request) — the wave batcher's pending-queue
        gate, so a too-big head request pends instead of raising."""
        need = self.pages_needed(req.prompt_len, req.new_tokens,
                                 req.ids.shape[0])
        free = self.pool.free_pages
        if free >= need:
            return True
        cold = self.trie.cold_pages() if self.trie is not None else 0
        return free + cold >= need

    def check_admittable(self, req) -> None:
        """Reject at SUBMIT time what admission could never take: a
        hand-passed prefix handle (the trie replaces them), or a page
        reservation exceeding the whole pool — the paged analogue of
        `validate_capacity`'s up-front max_len check. Without this the
        wave batcher's pending queue would wedge behind a head whose
        `can_admit` can never become true (or its serve loop would die
        on the deferred ValueError instead of the submitter)."""
        if getattr(req, "prefix", None) is not None:
            raise ValueError(
                "paged KV replaces hand-passed prefix handles (the "
                "prefix trie shares prompts automatically); submit the "
                "full prompt instead")
        need = self.pages_needed(req.prompt_len, req.new_tokens,
                                 req.ids.shape[0])
        if need > self.pool.n_pages:
            raise ValueError(
                f"request needs {need} KV page(s) "
                f"({req.ids.shape[0]} row(s) x prompt {req.prompt_len} "
                f"+ {req.new_tokens} new tokens at page_size "
                f"{self.page_size}); the pool holds {self.pool.n_pages}")

    # -- admission --------------------------------------------------------

    def admit(self, req, block: bool = False) -> Tuple[str, object]:
        """Seed the request's page tables; returns `(kind, data)` for
        its first stage-0 dispatch: ("prefill", ids) for a fresh prompt,
        ("span", suffix_ids) when the trie matched a prefix, ("step",
        token) when shipped KV was installed (the prompt pass already
        happened on the prefill fleet), or ("done", None) when the
        shipped first token already completes the request."""
        if getattr(req, "prefix", None) is not None:
            raise ValueError(
                "paged KV replaces hand-passed prefix handles (the "
                "prefix trie shares prompts automatically); submit the "
                "full prompt instead")
        batch, prompt_len = req.ids.shape[0], req.prompt_len
        per_row = self.pages_needed(prompt_len, req.new_tokens)
        shipped = getattr(req, "shipped", None)
        tokens = (np.asarray(req.ids)[0].tolist() if batch == 1
                  and self.trie is not None else None)
        shared_pids: List[int] = []
        if shipped is None and tokens is not None:
            shared_pids = self.trie.lookup(tokens,
                                           max_tokens=prompt_len - 1)
        shared = len(shared_pids)
        private: List[List[int]] = []
        try:
            for _ in range(batch):
                private.append(self.pool.alloc(per_row - shared,
                                               block=block))
        except BaseException:
            for row in private:
                self.pool.release(row)
            if shared_pids:
                self.pool.release(shared_pids)
            raise
        table = np.asarray(
            [shared_pids + row for row in private], np.int32)
        req.kvstate = {
            "table": table, "shared": shared,
            "shared_len": shared * self.page_size,
            "owned": shared_pids + [p for row in private for p in row],
            "tokens": tokens, "published": False,
        }
        # leak audit: the pool's owner ledger mirrors this request's
        # page references from the instant they exist, so a submitter
        # that dies anywhere past this point (install failure path
        # included) is reclaimable by the orphan sweep
        self.pool.adopt(req.rid, req.kvstate["owned"])
        if shipped is not None:
            try:
                return self._install_shipped(req, shipped)
            except BaseException:
                # a malformed handle must not leak the pages just
                # charged (the executor rolls back its slot, not ours)
                self.release(req)
                raise
        if shared:
            return "span", req.ids[:, shared * self.page_size:]
        return "prefill", req.ids

    def _install_shipped(self, req, handle) -> Tuple[str, object]:
        """Write a prefill fleet's shipped KV rows into this request's
        pages and pick the first token from the shipped last-stage
        logits — the decode-fleet side of disaggregation (kv/ship.py
        moved the bytes; this lands them)."""
        ks0 = req.kvstate
        if ks0.get("install_result") is not None:
            # idempotence fence: a second install (retried/zombie ship
            # delivered twice above the lease fence) must neither
            # re-scatter pages nor re-append the first token — return
            # the first install's decision unchanged
            return ks0["install_result"]
        plen = int(handle["prompt_len"])
        rows = handle["stage_rows"]
        if plen != req.prompt_len:
            raise ValueError(f"shipped KV covers {plen} prompt tokens; "
                             f"request prompt is {req.prompt_len}")
        if len(rows) != self._n_stages:
            raise ValueError(f"shipped KV has {len(rows)} stages; this "
                             f"pipeline has {self._n_stages}")
        ks = req.kvstate
        touched = list(range(pages_for(plen, self.page_size)))
        batch = req.ids.shape[0]
        with telemetry.span("kv", "install", rid=str(req.rid)):
            with self._arena_lock:
                for i in range(self._n_stages):
                    view = self.pool.gather(i, ks["table"])
                    if set(rows[i]) != set(view):
                        raise ValueError(
                            f"shipped KV leaves {sorted(rows[i])} do not "
                            f"match this pipeline's cache leaves "
                            f"{sorted(view)} (cache_bits mismatch?)")
                    for name, arr in rows[i].items():
                        arr = jnp.asarray(arr).astype(view[name].dtype)
                        if arr.shape[1] != batch:
                            raise ValueError(
                                f"shipped KV batch {arr.shape[1]} != "
                                f"request batch {batch}")
                        view[name] = view[name].at[:, :, :plen].set(arr)
                    self.pool.scatter(
                        i, ks["table"], view,
                        [(b, j) for b in range(batch) for j in touched])
        if self.trie is not None and tokens_publishable(req):
            self._publish(req)
        # the prefill fleet ships LOGITS, not a token: the pick stays on
        # the decode side with the request's own rng discipline, so
        # disaggregated tokens are identical to colocated ones
        logits = jnp.asarray(handle["logits"])
        req.rng, sub = jax.random.split(req.rng)
        token = req.pick(logits.astype(jnp.float32), sub)
        req.tokens.append(token)
        if req.on_token is not None:
            req.on_token(0, token)
        done = len(req.tokens) >= req.new_tokens
        if not done and req.eos_token is not None:
            hit = np.asarray(token) == req.eos_token
            req.rows_done = hit
            done = bool(hit.all())
        result = ("done", None) if done else ("step", token[:, None])
        ks["install_result"] = result
        return result

    # -- the stage-step indirection --------------------------------------

    def _touched_pages(self, kind: str, req, span: int) -> range:
        ks = req.kvstate
        if kind == "prefill":
            lo, hi = 0, req.prompt_len
        elif kind == "span":
            lo, hi = ks["shared_len"], req.prompt_len
        elif kind == "chunk":
            # chunked prefill: only this chunk's slice of the prompt
            # was written (earlier chunks already scattered theirs)
            lo, hi = req.chunk_off, req.chunk_off + span
        else:
            lo, hi = req.pos, req.pos + 1
        return range(lo // self.page_size,
                     pages_for(hi, self.page_size))

    def run_stage(self, i: int, req, data, kind: str):
        """One stage-step through page-table indirection — the paged
        analogue of `batcher._run_stage` (same spans, same program
        dispatch, device placement included)."""
        st = self.pipe.stages[i]
        ks = req.kvstate
        batch = req.ids.shape[0]
        span = data.shape[1] if kind in ("prefill", "span", "chunk") else 1
        writes = [(b, j) for b in range(batch)
                  for j in self._touched_pages(kind, req, span)
                  if j >= ks["shared"]]
        with telemetry.span("stage", f"exec{i}", stage=i,
                            rid=str(req.rid)):
            if st["device"] is not None:
                data = jax.device_put(data, st["device"])
            with self._arena_lock:
                cache = self.pool.gather(i, ks["table"])
                if kind == "prefill":
                    out, cache = st["prefill"](st["params"], data, cache)
                elif kind == "span":
                    out, cache = self.pipe._decode_step(
                        st, data, cache, ks["shared_len"], span=span)
                elif kind == "chunk":
                    # one slice of a chunked prompt pass: a span at the
                    # chunk's absolute offset (batcher._run_stage's rule)
                    out, cache = self.pipe._decode_step(
                        st, data, cache, req.chunk_off, span=span)
                else:
                    out, cache = self.pipe._decode_step(st, data, cache,
                                                        req.pos)
                self.pool.scatter(i, ks["table"], cache, writes)
        # trie publish waits for the prompt pass to COMPLETE: a single
        # prefill/span, or the FINAL chunk of a chunked pass (publishing
        # a half-written prompt would serve garbage pages to sharers)
        if i == self._n_stages - 1 and self.trie is not None \
                and (kind in ("prefill", "span")
                     or (kind == "chunk" and req.chunk_final)) \
                and tokens_publishable(req):
            self._publish(req)
        return out

    def _publish(self, req) -> None:
        """Prompt pass complete on every stage: hand the prompt's FULL
        pages to the trie for cross-request reuse (partial tail pages
        stay private — their owner's decode steps keep writing them)."""
        ks = req.kvstate
        ks["published"] = True
        full = req.prompt_len // self.page_size
        if full <= ks["shared"]:
            return          # nothing new beyond the already-shared pages
        self.trie.insert(ks["tokens"][:full * self.page_size],
                         ks["table"][0][:full].tolist())

    # -- prefix migration (router drain — docs/FAULT_TOLERANCE.md) -------

    def export_prefix(self, tokens, bits: int = 0):
        """Snapshot the trie's cached pages for this prompt prefix as
        wire-v2 ship frames (kv/ship.py) — the router's drain path ships
        these to a survivor replica instead of re-prefilling there.
        Returns `(frames, tokens_covered, n_pages)` or `None` when the
        trie holds nothing for the prefix (or is unarmed / the cache is
        int8 — quantized caches don't ship exactly)."""
        if self.trie is None:
            return None
        toks = [int(t) for t in tokens]
        pids = self.trie.lookup(toks, max_tokens=len(toks))
        if not pids:
            return None
        try:
            plen = len(pids) * self.page_size
            table = np.asarray([pids], np.int32)
            caches = []
            with telemetry.span("kv", "export", mb=None):
                with self._arena_lock:
                    for i in range(self._n_stages):
                        view = self.pool.gather(i, table)
                        if set(view) != {"k", "v"}:
                            return None       # int8 cache: not shippable
                        caches.append(view)
                from . import ship
                # prefix export carries no sampling decision — the
                # logits slot is a placeholder the importer ignores
                frames = ship.encode_kv_ship(
                    caches, plen, np.zeros((1, 1), np.float32), bits=bits)
            return frames, plen, len(pids)
        finally:
            # lookup took one reference per matched page for us; the
            # trie's own retention references keep the pages cached
            self.pool.release(pids)

    def install_prefix(self, tokens, handle) -> int:
        """Land a peer replica's exported prefix into this pool + trie
        (the receive side of `export_prefix`): alloc pages, scatter the
        shipped rows, publish to the trie. Idempotent — a prefix the
        trie already covers installs zero pages. Returns pages
        installed."""
        if self.trie is None:
            raise ValueError("prefix install needs the prefix trie "
                             "(share_prefixes)")
        toks = [int(t) for t in tokens]
        plen = int(handle["prompt_len"])
        rows = handle["stage_rows"]
        if plen % self.page_size or plen > len(toks) or plen <= 0:
            raise ValueError(
                f"shipped prefix covers {plen} tokens; expected a "
                f"positive multiple of page_size {self.page_size} "
                f"within the {len(toks)}-token prefix")
        if len(rows) != self._n_stages:
            raise ValueError(f"shipped prefix has {len(rows)} stages; "
                             f"this pipeline has {self._n_stages}")
        toks = toks[:plen]
        if self.trie.peek(toks, max_tokens=plen) >= plen:
            return 0        # already cached here: nothing to install
        n = plen // self.page_size
        pids = self.pool.alloc(n)
        try:
            table = np.asarray([pids], np.int32)
            writes = [(0, j) for j in range(n)]
            with telemetry.span("kv", "import"):
                with self._arena_lock:
                    for i in range(self._n_stages):
                        view = self.pool.gather(i, table)
                        if set(rows[i]) != set(view):
                            raise ValueError(
                                f"shipped prefix leaves "
                                f"{sorted(rows[i])} do not match this "
                                f"pipeline's cache leaves "
                                f"{sorted(view)}")
                        for name, arr in rows[i].items():
                            arr = jnp.asarray(arr).astype(
                                view[name].dtype)
                            view[name] = view[name].at[
                                :, :, :plen].set(arr)
                        self.pool.scatter(i, table, view, writes)
            # insert adds the trie's retention refs for NEW nodes; pages
            # duplicating an existing node stay ours alone and die with
            # the release below
            self.trie.insert(toks, pids)
        except BaseException:
            self.pool.release(pids)
            raise
        self.pool.release(pids)     # drop the alloc ref; trie refs live on
        return n

    # -- completion / pressure -------------------------------------------

    def release(self, req) -> None:
        ks = getattr(req, "kvstate", None)
        if not ks:
            return
        req.kvstate = None
        # claim-then-release through the owner ledger: if the orphan
        # sweep already reclaimed this request (we ARE the death it
        # raced), disown returns None and there is nothing left to drop
        pids = self.pool.disown(req.rid)
        if pids is not None:
            self.pool.release(pids)

    def shared_prompt_tokens(self, tokens) -> int:
        """How many leading prompt tokens the trie could serve from
        shared pages right now (no references taken — a routing probe;
        the binding lookup happens at admission)."""
        if self.trie is None or tokens is None:
            return 0
        return self.trie.peek(tokens, max_tokens=len(tokens) - 1)

    def sweep_orphans(self, live_rids) -> int:
        """Reclaim pages whose owning request is no longer live (the
        periodic leak audit — a shipper/submitter death mid-transfer
        must strand zero pages). `live_rids` is the executor's live
        request-id set; returns pages reclaimed."""
        return self.pool.sweep_leaked(live_rids)

    def evict_cold_all(self) -> int:
        """Drop EVERY cold cached prefix page (the brownout
        `evict_cold_pages` rung's sweep). 0 when no trie is armed."""
        if self.trie is None:
            return 0
        return self.trie.evict_cold(None)

    def snapshot(self) -> dict:
        s = {"pool": self.pool.stats()}
        if self.trie is not None:
            s["prefix"] = self.trie.stats()
        return s


def tokens_publishable(req) -> bool:
    """Whether this request's prompt can feed the trie: sharing armed,
    single-row, host tokens captured, not already published."""
    ks = getattr(req, "kvstate", None)
    return (ks is not None and not ks["published"]
            and ks["tokens"] is not None)
