"""Paged KV pool: fixed-size token pages behind per-request page tables.

The serving executors historically gave every request PRIVATE dense
per-stage cache slots sized for `max_len` tokens (`DecodePipeline.
_fresh_caches`), so concurrency was bounded by SLOTS — a 6-token
interactive request held the same KV memory as a 1024-token one, and a
prompt prefix shared by a thousand requests was prefixed a thousand
times unless the caller hand-passed a `precompute_prefix` handle. This
module is the memory half of ROADMAP item 2's paged KV plane:

- **One page arena per stage**, preallocated: page `p` of stage `i` is
  a `[n_blocks, page_size, ...]` slice of each cache leaf (K, V, and —
  for int8 caches — their scale/shift rows), so a page always means the
  same `page_size` token positions on EVERY stage and one page-id list
  describes a request fleet-wide.
- **Page tables, not slots**: a request holds `ceil((prompt + new_tokens)
  / page_size)` pages per batch row; admission charges tokens, not
  slots, so short requests pack densely and concurrency is bounded by
  the pool's TOKEN capacity (serving/admission.py's token budget).
- **Refcounted sharing**: pages are refcounted, so the prefix trie
  (kv/prefix.py) can retain a finished prompt's pages for cross-request
  reuse — a later request with the same prompt prefix references the
  SAME arena pages instead of re-prefilling them.
- **Static shapes preserved**: the executors materialize a request's
  cache view by a gather over the page axis and write back touched
  pages with a scatter (kv/backend.py); the compiled stage programs are
  exactly `DecodePipeline`'s, shaped `[n_blocks, B, pages * page_size,
  ...]` — one program per page-count bucket, no dynamic shapes.

Eviction: when the free list runs dry, `alloc` calls the registered
evict hook (the trie's cold-page eviction) before failing — and the
brownout ladder's `evict_cold_pages` rung (serving/brownout.py) calls
it proactively, reclaiming cached-but-idle prefix pages before any
request is shed.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import metrics as prom
from ..utils.threads import make_condition


class PoolExhausted(RuntimeError):
    """The pool cannot supply the requested pages — even after cold-page
    eviction. The serving layer's token-budget admission exists to make
    this unreachable; hitting it from a raw executor is backpressure."""

    def __init__(self, need: int, free: int, capacity: int):
        super().__init__(
            f"KV page pool exhausted: need {need} page(s), {free} free "
            f"of {capacity}")
        self.need = need
        self.free = free
        self.capacity = capacity


def pages_for(tokens: int, page_size: int) -> int:
    """Pages covering `tokens` cache positions (ceil division)."""
    if tokens <= 0:
        return 0
    return -(-int(tokens) // int(page_size))


class KvPagePool:
    """Preallocated per-stage page arenas + one global page-id space.

    `pipe` supplies the per-stage cache geometry (block counts, KV head
    layout, dtype, cache_bits) — arena leaves mirror `init_cache`'s
    leaves with the batch axis replaced by the page axis. Sharded
    pipelines (tp/sp/ep meshes) are refused: their caches are
    device-sharded pytrees whose page gather/scatter would silently
    gather across shards (the paged plane covers the host-driven
    serving pipeline, like the executors it backs).

    Thread model: page accounting (free list, refcounts) lives under one
    condition ("kv.pool"); `release` notifies so a blocking `alloc` can
    wait for completions. Arena LEAVES are swapped functionally
    (`arr.at[...].set`) by `scatter` — the caller (kv/backend.py)
    serializes same-stage mutations under its arena lock.
    """

    def __init__(self, pipe, n_pages: int, page_size: int = 16,
                 registry: Optional[prom.Registry] = None):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if getattr(pipe, "mesh", None) is not None \
                or getattr(pipe, "ep_mesh", None) is not None \
                or getattr(pipe, "tp_ep_mesh", None) is not None \
                or getattr(pipe, "sp_degree", 1) != 1:
            raise ValueError(
                "paged KV covers the host-driven pipeline; tp/ep/sp mesh "
                "pipelines keep their sharded dense caches")
        from ..parallel.decode import init_cache
        self.pipe = pipe
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # arena leaves per stage: template leaf [L, 1, page, ...] ->
        # arena [P, L, page, ...] (batch axis dropped; page axis leads)
        self._arena: List[Dict[str, jax.Array]] = []
        for st in pipe.stages:
            tmpl = init_cache(pipe.cfg, st["n_blocks"], 1, page_size,
                              pipe.dtype, cache_bits=pipe.cache_bits)
            leaves = {}
            for name, leaf in tmpl.items():
                shape = (self.n_pages, leaf.shape[0]) + leaf.shape[2:]
                arr = jnp.zeros(shape, leaf.dtype)
                if st["device"] is not None:
                    arr = jax.device_put(arr, st["device"])
                leaves[name] = arr
            self._arena.append(leaves)
        self._cond = make_condition("kv.pool")
        self._free: List[int] = list(range(self.n_pages - 1, -1, -1))
        self._refs: Dict[int, int] = {}
        # owner ledger (leak audit, docs/FAULT_TOLERANCE.md): the page
        # references a live REQUEST holds, keyed by its id — what the
        # periodic sweep reconciles against executor liveness so a
        # submitter that died mid-ship can never strand its pages
        self._owners: Dict[str, List[int]] = {}
        self._evict_hook: Optional[Callable[[int], int]] = None
        self._closed = False
        reg = prom.REGISTRY if registry is None else registry
        self.m_pages = reg.gauge(
            "pipeedge_kv_pages",
            "KV page pool accounting by state (total / free); occupancy "
            "= 1 - free/total (docs/SERVING.md paged KV plane)")
        self.m_pages.set(self.n_pages, state="total")
        self.m_pages.set(self.n_pages, state="free")
        self.m_evicted = reg.counter(
            "pipeedge_kv_pages_evicted_total",
            "cold prefix pages reclaimed from the trie (allocation "
            "pressure or the brownout evict_cold_pages rung)")
        self.m_evicted.declare()
        self.m_leaked = reg.counter(
            "pipeedge_kv_pages_leaked_total",
            "page references reclaimed by the orphan sweep: their "
            "owning request was no longer live (submitter/shipper died "
            "between page charge and release — "
            "docs/FAULT_TOLERANCE.md disaggregated serving)")
        self.m_leaked.declare()

    # -- accounting -------------------------------------------------------

    @property
    def tokens_capacity(self) -> int:
        """Total cache positions the pool can hold (the admission token
        budget's natural value)."""
        return self.n_pages * self.page_size

    @property
    def free_pages(self) -> int:
        with self._cond:
            return len(self._free)

    def set_evict_hook(self, hook: Optional[Callable[[int], int]]) -> None:
        """`hook(need) -> freed` reclaims cold pages (the prefix trie's
        eviction); called OUTSIDE the pool lock on allocation pressure."""
        self._evict_hook = hook

    def refcount(self, pid: int) -> int:
        with self._cond:
            return self._refs.get(pid, 0)

    def refcounts(self) -> Dict[int, int]:
        """One locked snapshot of every page's refcount — the trie's
        cold-page walks take this ONCE instead of a pool-lock round
        trip per node (kv/prefix.py)."""
        with self._cond:
            return dict(self._refs)

    def close(self) -> None:
        """Fail every current and future BLOCKING allocation: the
        executor's death/stop path must wake submitters parked on page
        availability, exactly like its semaphore over-release wakes
        slot-blocked ones (parallel/batcher.py's wake-on-death
        contract). Releases still work — in-flight completions drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def alloc(self, n: int, block: bool = False,
              timeout: Optional[float] = None) -> List[int]:
        """Take `n` fresh pages (refcount 1 each). On a dry free list the
        evict hook runs first; `block=True` then waits for releases (the
        stage-worker submit path's backpressure) up to `timeout`."""
        if n <= 0:
            return []
        if n > self.n_pages:
            raise PoolExhausted(n, self.free_pages, self.n_pages)
        while True:
            with self._cond:
                if self._closed:
                    raise RuntimeError(
                        "KV page pool closed (executor shut down)")
                if len(self._free) >= n:
                    pids = [self._free.pop() for _ in range(n)]
                    for p in pids:
                        self._refs[p] = 1
                    self.m_pages.set(len(self._free), state="free")
                    return pids
                short = n - len(self._free)
            hook = self._evict_hook
            if hook is not None and hook(short) > 0:
                continue            # eviction freed something: retry
            with self._cond:
                if self._closed:
                    raise RuntimeError(
                        "KV page pool closed (executor shut down)")
                if len(self._free) >= n:
                    continue        # a release raced us: retry the take
                if not block:
                    raise PoolExhausted(n, len(self._free), self.n_pages)
                if not self._cond.wait(timeout):
                    raise PoolExhausted(n, len(self._free), self.n_pages)

    def share(self, pids: Sequence[int]) -> None:
        """Add one reference to each page (prefix reuse / trie retention)."""
        with self._cond:
            for p in pids:
                if self._refs.get(p, 0) <= 0:
                    raise ValueError(f"share of unallocated page {p}")
                self._refs[p] += 1

    def release(self, pids: Sequence[int], evicted: bool = False) -> None:
        """Drop one reference per page; refcount 0 returns the page to
        the free list and wakes blocked allocators."""
        freed = 0
        with self._cond:
            for p in pids:
                r = self._refs.get(p, 0)
                if r <= 0:
                    raise ValueError(f"release of unallocated page {p}")
                if r == 1:
                    del self._refs[p]
                    self._free.append(p)
                    freed += 1
                else:
                    self._refs[p] = r - 1
            if freed:
                self.m_pages.set(len(self._free), state="free")
                self._cond.notify_all()
        if evicted and freed:
            self.m_evicted.inc(freed)

    # -- owner ledger + orphan sweep (leak audit) -------------------------

    def adopt(self, owner, pids: Sequence[int]) -> None:
        """Record `owner` (a request id) as holding one reference to
        each page in `pids` — the set `release`/`sweep_leaked` will
        drop. Exactly ONE of the two ever drops it: `disown` is the
        atomic claim."""
        with self._cond:
            self._owners[str(owner)] = list(pids)

    def disown(self, owner) -> Optional[List[int]]:
        """Claim `owner`'s page references for release. None = already
        claimed (the request's own release path and the orphan sweep
        race benignly: whoever pops the ledger entry does the release,
        the other sees None and does nothing)."""
        with self._cond:
            return self._owners.pop(str(owner), None)

    def sweep_leaked(self, live_owners) -> int:
        """Reconcile the owner ledger against executor liveness: drop
        the page references of every owner no longer live (a submitter
        or shipper that died between page charge and release). Safe
        against completion races — executors list a request as live
        BEFORE charging pages and release pages BEFORE delisting it, so
        a ledger entry whose owner is not live is genuinely orphaned —
        but ONLY if the ledger is observed FIRST and liveness SECOND:
        pass `live_owners` as a CALLABLE for live systems (invoked
        after the ledger snapshot; returning None aborts the sweep) so
        a request admitted between the two reads can never be taken
        for dead. A plain set is accepted for offline callers with no
        concurrent admissions. Returns pages reference-dropped
        (pipeedge_kv_pages_leaked_total counts them; /healthz surfaces
        the running total)."""
        with self._cond:
            owners = list(self._owners)
        if callable(live_owners):
            live_owners = live_owners()
            if live_owners is None:     # liveness snapshot raced; skip
                return 0
        live = {str(o) for o in live_owners}
        dead = [o for o in owners if o not in live]
        leaked = 0
        for owner in dead:
            pids = self.disown(owner)
            if pids:
                self.release(pids)
                leaked += len(pids)
        if leaked:
            self.m_leaked.inc(leaked)
        return leaked

    def stats(self) -> dict:
        with self._cond:
            free = len(self._free)
            shared = sum(1 for r in self._refs.values() if r > 1)
            owners = len(self._owners)
        return {"pages_total": self.n_pages, "pages_free": free,
                "page_size": self.page_size,
                "pages_shared": shared,
                "occupancy": round(1.0 - free / self.n_pages, 4),
                "pages_evicted_total": int(self.m_evicted.value()),
                "owners": owners,
                "leaked": int(self.m_leaked.value())}

    # -- the gather/scatter indirection ----------------------------------

    def gather(self, stage: int, table: np.ndarray) -> Dict[str, jax.Array]:
        """Materialize a request's stage-`stage` cache view from its page
        table `[B, n]` -> cache leaves `[L, B, n * page_size, ...]` (the
        exact layout `DecodePipeline`'s stage programs consume)."""
        ids = jnp.asarray(np.asarray(table, np.int32))
        out = {}
        for name, arr in self._arena[stage].items():
            g = arr[ids]                       # [B, n, L, page, ...]
            g = jnp.moveaxis(g, 2, 0)          # [L, B, n, page, ...]
            out[name] = g.reshape(g.shape[0], g.shape[1], -1,
                                  *g.shape[4:])
        return out

    def scatter(self, stage: int, table: np.ndarray,
                cache: Dict[str, jax.Array],
                writes: Sequence[Tuple[int, int]]) -> None:
        """Write the view pages named by `writes` — `(row, page_col)`
        pairs into `table` — back into the stage arena. Only a request's
        PRIVATE, TOUCHED pages are written (kv/backend.py computes the
        set), so shared prefix pages are physically immutable."""
        if not writes:
            return
        table = np.asarray(table)
        b_idx = np.asarray([b for b, _ in writes], np.int32)
        j_idx = np.asarray([j for _, j in writes], np.int32)
        pids = jnp.asarray(table[b_idx, j_idx].astype(np.int32))
        n = table.shape[1]
        arena = self._arena[stage]
        for name, arr in arena.items():
            v = cache[name]                    # [L, B, n*page, ...]
            v = v.reshape(v.shape[0], v.shape[1], n, self.page_size,
                          *v.shape[3:])
            v = jnp.moveaxis(v, 0, 2)          # [B, n, L, page, ...]
            pieces = v[jnp.asarray(b_idx), jnp.asarray(j_idx)]
            arena[name] = arr.at[pids].set(pieces)
