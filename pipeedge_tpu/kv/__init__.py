"""The paged KV plane (ROADMAP item 2): shared paged KV pool, prompt-
prefix trie, page-table execution backend, and prefill/decode
disaggregation.

Dense per-request cache slots bound serving concurrency by SLOTS; this
package bounds it by TOKENS instead, shares prompt prefixes across
requests automatically, and lets a prefill fleet feed a decode fleet
over the existing tiered transport:

- `pool`:    `KvPagePool` — per-stage page arenas, refcounts, eviction
- `prefix`:  `PrefixTrie` — whole-page prompt matching + cold eviction
- `backend`: `PagedKvBackend` — the executors' gather/scatter cache
             provider (token-identical to the dense path for fp caches)
- `ship`:    KV rows as wire-v2 frames (int8 option, CRC, socket path)
- `disagg`:  `PrefillFleet` — prompt passes on a dedicated IN-PROCESS
             pipeline, results shipped into the decode fleet's pages
- `fleet`:   `RemotePrefillFleet`/`PrefillWorkerLoop` — the CROSS-
             PROCESS fleet (tools/prefill_worker.py ranks over DCN)
             with the fault-tolerant lease/ack ship protocol
             (docs/FAULT_TOLERANCE.md disaggregated serving)

Grounded in the Gemma-on-TPU serving comparison and production paged-
attention practice (PAPERS.md); docs/SERVING.md has the operator story
(token-budget math, brownout evict rung, knob table).
"""
from .backend import PagedKvBackend
from .disagg import PrefillFleet
from .fleet import (PrefillUnavailable, PrefillWorkerLoop,
                    RemotePrefillFleet)
from .pool import KvPagePool, PoolExhausted, pages_for
from .prefix import PrefixTrie

__all__ = [
    "KvPagePool", "PagedKvBackend", "PoolExhausted", "PrefillFleet",
    "PrefillUnavailable", "PrefillWorkerLoop", "PrefixTrie",
    "RemotePrefillFleet", "pages_for",
]
