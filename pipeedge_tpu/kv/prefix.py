"""Cross-request prefix sharing: a prompt-token hash trie over KV pages.

`precompute_prefix` (PR 4) made prompt caching possible, but only
through a HAND-PASSED handle: the caller had to know which requests
share a prefix. The trie makes sharing automatic and cross-request: a
finished prompt's full pages are published here keyed by their token
content, and a later request walks the trie at admission — every
matched page is referenced (refcount, kv/pool.py) instead of
re-prefilled, and only the unmatched suffix runs as a span step.

Structure: one node per PAGE of prompt tokens (`page_size` tokens), so
the key at each level is a fixed-size token chunk and a match is always
a whole number of pages — shared pages are physically immutable (the
borrower never writes positions below its shared length; kv/backend.py
restricts scatter to private pages). Partial-page matches are
deliberately NOT shared: the tail page of a prompt is still being
written by its owner's decode steps.

Lifecycle: `insert` retains each published page with one trie
reference; a page is COLD when the trie holds its only reference
(`pool.refcount == 1`) — no live request is reading it. `evict_cold`
reclaims cold leaf nodes in LRU order (leaf-first keeps every surviving
node's prefix chain intact); it is the pool's allocation-pressure hook
and the brownout ladder's `evict_cold_pages` rung.

Lock order: the trie lock ("kv.prefix") is taken before any pool call;
the pool's condition is a leaf lock (verified by the lockdep witness,
docs/STATIC_ANALYSIS.md).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..telemetry import metrics as prom
from ..utils.threads import make_lock
from .pool import KvPagePool

LOOKUP_RESULTS = ("hit", "partial", "miss")


class _Node:
    __slots__ = ("key", "pid", "parent", "children", "stamp")

    def __init__(self, key: Tuple[int, ...], pid: int,
                 parent: Optional["_Node"], stamp: int):
        self.key = key
        self.pid = pid
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.stamp = stamp


class PrefixTrie:
    """Page-granular prompt-prefix cache over a `KvPagePool`."""

    def __init__(self, pool: KvPagePool,
                 registry: Optional[prom.Registry] = None):
        self.pool = pool
        self.page_size = pool.page_size
        self._lock = make_lock("kv.prefix")
        self._root: Dict[Tuple[int, ...], _Node] = {}
        self._nodes = 0
        self._clock = 0      # logical LRU clock (bumped per lookup/insert)
        reg = prom.REGISTRY if registry is None else registry
        self.m_lookups = reg.counter(
            "pipeedge_kv_prefix_lookups_total",
            "prefix-trie lookups by result: hit (>= 1 full page matched "
            "and reused), partial (some pages matched, shorter than the "
            "longest published prefix path), miss (nothing matched). "
            "hit+partial both reuse pages; the split tells how often the "
            "workload's prefixes align with published ones")
        for result in LOOKUP_RESULTS:
            self.m_lookups.declare(result=result)
        self.m_pages_reused = reg.counter(
            "pipeedge_kv_prefix_pages_reused_total",
            "KV pages referenced from the trie instead of re-prefilled")
        self.m_pages_reused.declare()
        self.m_cached = reg.gauge(
            "pipeedge_kv_prefix_pages_cached",
            "prompt pages currently retained by the prefix trie")
        self.m_cached.set(0)

    def __len__(self) -> int:
        with self._lock:
            return self._nodes

    def _chunks(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        n = len(tokens) // self.page_size
        return [tuple(int(t) for t in
                      tokens[i * self.page_size:(i + 1) * self.page_size])
                for i in range(n)]

    # -- lookup / insert --------------------------------------------------

    def lookup(self, tokens: Sequence[int],
               max_tokens: Optional[int] = None) -> List[int]:
        """Longest whole-page prefix match for `tokens`; returns the
        matched page ids, each with one reference taken for the caller
        (release them with `pool.release` when the request completes).
        `max_tokens` caps the match (the borrower must keep at least one
        prompt token out of the shared prefix — the span step needs a
        non-empty suffix, `DecodePipeline.generate`'s prefix rule)."""
        chunks = self._chunks(tokens)
        if max_tokens is not None:
            chunks = chunks[:max(0, max_tokens // self.page_size)]
        pids: List[int] = []
        with self._lock:
            self._clock += 1
            level = self._root
            for key in chunks:
                node = level.get(key)
                if node is None:
                    break
                node.stamp = self._clock
                pids.append(node.pid)
                level = node.children
            if pids:
                self.pool.share(pids)
                self.m_pages_reused.inc(len(pids))
                self.m_lookups.inc(result="hit" if len(pids) == len(chunks)
                                   else "partial")
            else:
                self.m_lookups.inc(result="miss")
        return pids

    def peek(self, tokens: Sequence[int],
             max_tokens: Optional[int] = None) -> int:
        """Matched-token count of the longest whole-page prefix WITHOUT
        taking references or counting a lookup — a routing probe (the
        disaggregation split uses it to decide whether a prompt even
        needs the prefill fleet, tools/serve.py)."""
        chunks = self._chunks(tokens)
        if max_tokens is not None:
            chunks = chunks[:max(0, max_tokens // self.page_size)]
        matched = 0
        with self._lock:
            level = self._root
            for key in chunks:
                node = level.get(key)
                if node is None:
                    break
                matched += 1
                level = node.children
        return matched * self.page_size

    def insert(self, tokens: Sequence[int], pids: Sequence[int]) -> int:
        """Publish a prefilled prompt's full pages: `pids[i]` holds the
        KV rows of token chunk `i` on every stage. Existing nodes win
        (the first publisher of a chunk keeps it — a concurrent
        duplicate's pages simply stay private and die with its request);
        new nodes take one retention reference. Returns nodes added."""
        chunks = self._chunks(tokens)
        if len(pids) < len(chunks):
            chunks = chunks[:len(pids)]
        added = 0
        with self._lock:
            self._clock += 1
            level, parent = self._root, None
            for key, pid in zip(chunks, pids):
                node = level.get(key)
                if node is None:
                    node = _Node(key, int(pid), parent, self._clock)
                    level[key] = node
                    self.pool.share([int(pid)])
                    self._nodes += 1
                    added += 1
                else:
                    node.stamp = self._clock
                    if node.pid != pid:
                        # a different physical page holds the same
                        # tokens: keep the published one; the duplicate
                        # stays private to its request
                        level = node.children
                        parent = node
                        continue
                level = node.children
                parent = node
            self.m_cached.set(self._nodes)
        return added

    # -- eviction ---------------------------------------------------------

    def _cold_leaves(self) -> List[_Node]:
        """Leaf nodes whose page the trie alone references, oldest
        first. Leaf-first keeps surviving prefix chains contiguous.
        One refcount SNAPSHOT per walk, not a pool-lock round trip per
        node (can_admit probes this on the wave batcher's tick path)."""
        refs = self.pool.refcounts()
        out = []
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif refs.get(node.pid, 0) == 1:
                out.append(node)
        out.sort(key=lambda n: n.stamp)
        return out

    def cold_pages(self) -> int:
        """How many pages eviction could reclaim right now (free +
        cold is the backend's `can_admit` headroom). Counts the whole
        cold SUBTREES, not just current leaves: evicting a cold leaf
        exposes its parent, so a fully-cold chain reclaims end to end."""
        refs = self.pool.refcounts()    # one snapshot, not per-node locks

        def count(node: _Node) -> Tuple[int, bool]:
            total, all_cold = 0, True
            for child in node.children.values():
                t, cold = count(child)
                total += t
                all_cold = all_cold and cold
            if all_cold and refs.get(node.pid, 0) == 1:
                return total + 1, True
            return total, False

        with self._lock:
            return sum(count(n)[0] for n in self._root.values())

    def evict_cold(self, need: Optional[int] = None) -> int:
        """Reclaim cold pages: at most `need` (None = ALL cold pages —
        the brownout rung's proactive sweep). Returns pages freed."""
        freed = 0
        with self._lock:
            while need is None or freed < need:
                leaves = self._cold_leaves()
                if not leaves:
                    break
                for node in leaves:
                    if need is not None and freed >= need:
                        break
                    siblings = (self._root if node.parent is None
                                else node.parent.children)
                    siblings.pop(node.key, None)
                    self._nodes -= 1
                    self.pool.release([node.pid], evicted=True)
                    freed += 1
            self.m_cached.set(self._nodes)
        return freed

    def stats(self) -> dict:
        with self._lock:
            nodes = self._nodes
        hits = self.m_lookups.value(result="hit") \
            + self.m_lookups.value(result="partial")
        misses = self.m_lookups.value(result="miss")
        total = hits + misses
        return {"pages_cached": nodes,
                "lookups": int(total),
                # hits/misses exposed raw so consumers can difference
                # two snapshots into a WINDOW rate (benchkit serve_kv)
                # instead of the lifetime-cumulative hit_rate below
                "hits": int(hits), "misses": int(misses),
                "hit_rate": (None if total == 0
                             else round(hits / total, 4)),
                "pages_reused_total": int(self.m_pages_reused.value())}
