"""EWMA peer-health scoring with brownout-style hysteresis.

The detector behind `--on-peer-degraded` (runtime.py): one `observe()`
per rank per measured round window folds the window's signals into a
smoothed degradation score and advances the gray rank lifecycle

    healthy --score >= suspect_threshold--> suspect
    suspect --confirmed `confirm` consecutive windows AND the min-fleet
             floor holds (caller's `can_quarantine`)--> quarantined
    quarantined --score <= readmit_threshold for `readmit` consecutive
             windows--> probation (the caller restores the rank's stage
             at the next round boundary, the existing heal machinery)
    probation --`probation` clean windows--> healthy
    probation --score >= suspect_threshold (single window: a relapse
             needs no re-confirmation)--> quarantined

Signals are RELATIVE, not absolute — a fleet where everything is slow is
balanced, not gray — so the caller normalizes against the fleet median
before calling: `service_ratio` (stage service time / fleet median,
telemetry/feedback.py `stage_estimates`), `rtt_ratio` (heartbeat RTT p99
/ fleet median, comm/dcn.py `heartbeat_rtt_stats`), and the raw
`send_retries` the transport observed toward the rank this window. The
instant degradation is the MAX over the per-signal degradations (a gray
failure usually shows in one signal; averaging would dilute it), and the
score is its EWMA — so a single noisy window moves the score by at most
`alpha`, and confirmation windows filter the rest (the same
hysteresis-plus-confirmation discipline as `sched/rebalance.py`'s
RebalancePolicy and `serving/brownout.py`'s ladder).

A window with NO signal (an empty `HealthSample` — e.g. a quarantined
rank whose heartbeats are disabled) holds the score: absence of evidence
never readmits a rank, and never convicts one either.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Dict, Iterable, List, Optional

from ..telemetry import metrics as prom

logger = logging.getLogger(__name__)

STATE_HEALTHY = "healthy"
STATE_SUSPECT = "suspect"
STATE_QUARANTINED = "quarantined"
STATE_PROBATION = "probation"

# /metrics plane: the live per-rank score (0 = healthy, 1 = fully
# degraded) and quarantine transitions. Label matrices are pre-declared
# at scorer construction, when the fleet membership is known (PL501).
_HEALTH_SCORE = prom.REGISTRY.gauge(
    "pipeedge_peer_health_score",
    "EWMA gray-failure degradation score per rank "
    "(0 = healthy, 1 = fully degraded)")
_QUARANTINES = prom.REGISTRY.counter(
    "pipeedge_quarantines_total",
    "gray-failure quarantine transitions (suspect -> quarantined and "
    "probation relapses), by rank")


@dataclasses.dataclass(frozen=True)
class HealthSample:
    """One rank's signals for one measured window. All optional — the
    scorer uses whatever the window could measure."""
    service_ratio: Optional[float] = None  # stage service_s / fleet median
    rtt_ratio: Optional[float] = None      # heartbeat RTT p99 / fleet median
    send_retries: int = 0                  # transport redials toward the rank

    def empty(self) -> bool:
        return (self.service_ratio is None and self.rtt_ratio is None
                and self.send_retries <= 0)


@dataclasses.dataclass(frozen=True)
class Transition:
    """One state change, with the evidence that drove it."""
    rank: int
    frm: str
    to: str
    score: float
    window: int      # the observe() call index that fired it
    reason: str


class HealthPolicy:
    """The scorer's knobs (defaults sized for round windows of seconds).

    `suspect_threshold` > `readmit_threshold` is the hysteresis band: a
    score oscillating between them changes nothing. `ratio_bad` /
    `rtt_bad` / `retries_bad` are the per-signal "fully degraded"
    anchors: a service ratio of `ratio_bad` (stage costs 1.5x the fleet
    median) contributes degradation 1.0, ratio 1.0 contributes 0."""

    def __init__(self, alpha: float = 0.5,
                 suspect_threshold: float = 0.4,
                 readmit_threshold: float = 0.2,
                 confirm: int = 2,
                 readmit: int = 2,
                 probation: int = 2,
                 ratio_bad: float = 1.5,
                 rtt_bad: float = 3.0,
                 retries_bad: int = 3):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if not 0.0 < readmit_threshold < suspect_threshold <= 1.0:
            raise ValueError(
                "need 0 < readmit_threshold < suspect_threshold <= 1, got "
                f"{readmit_threshold} / {suspect_threshold}")
        if min(confirm, readmit, probation) < 1:
            raise ValueError("confirm/readmit/probation windows must be "
                             ">= 1")
        if ratio_bad <= 1.0 or rtt_bad <= 1.0 or retries_bad < 1:
            raise ValueError("ratio_bad/rtt_bad must exceed 1.0 and "
                             "retries_bad must be >= 1")
        self.alpha = float(alpha)
        self.suspect_threshold = float(suspect_threshold)
        self.readmit_threshold = float(readmit_threshold)
        self.confirm = int(confirm)
        self.readmit = int(readmit)
        self.probation = int(probation)
        self.ratio_bad = float(ratio_bad)
        self.rtt_bad = float(rtt_bad)
        self.retries_bad = int(retries_bad)

    def degradation(self, sample: HealthSample) -> Optional[float]:
        """Instant degradation in [0, 1] for one window's signals; None
        when the sample carries no signal at all (hold the score)."""
        if sample.empty():
            return None
        parts: List[float] = []
        if sample.service_ratio is not None:
            parts.append(_unit(sample.service_ratio, 1.0, self.ratio_bad))
        if sample.rtt_ratio is not None:
            parts.append(_unit(sample.rtt_ratio, 1.0, self.rtt_bad))
        if sample.send_retries > 0:
            parts.append(_unit(float(sample.send_retries), 0.0,
                               float(self.retries_bad)))
        return max(parts) if parts else 0.0


def _unit(value: float, lo: float, hi: float) -> float:
    """Clamp `value` onto [0, 1] linearly between `lo` (nominal) and
    `hi` (fully degraded)."""
    if hi <= lo:
        return 1.0 if value >= hi else 0.0
    return min(1.0, max(0.0, (value - lo) / (hi - lo)))


class _RankHealth:
    """Per-rank scorer state (internal)."""

    __slots__ = ("state", "score", "streak", "windows")

    def __init__(self):
        self.state = STATE_HEALTHY
        self.score = 0.0
        self.streak = 0     # consecutive windows toward the next transition
        self.windows = 0    # observe() calls that carried a signal


class PeerHealthScorer:
    """Fleet-wide gray-failure detector: one `_RankHealth` per peer.

    Single-threaded by design — the data rank's round loop is the only
    caller (`observe` per rank per boundary), and `snapshot()` reads are
    GIL-atomic dict copies, so no lock is needed (the same discipline as
    `RebalancePolicy`)."""

    def __init__(self, ranks: Iterable[int],
                 policy: Optional[HealthPolicy] = None):
        self.policy = policy or HealthPolicy()
        self._ranks: Dict[int, _RankHealth] = {
            int(r): _RankHealth() for r in ranks}
        self.transitions: List[Transition] = []
        # PL501: the fleet membership fixes the label matrices here
        for r in self._ranks:
            _HEALTH_SCORE.set(0.0, rank=str(r))
            _QUARANTINES.declare(rank=str(r))

    # -- queries --------------------------------------------------------

    def state_of(self, rank: int) -> str:
        return self._ranks[int(rank)].state

    def score_of(self, rank: int) -> float:
        return self._ranks[int(rank)].score

    def quarantined(self) -> List[int]:
        return sorted(r for r, h in self._ranks.items()
                      if h.state == STATE_QUARANTINED)

    def snapshot(self) -> Dict[str, dict]:
        """Per-rank state for /healthz (`{rank: {state, score, windows}}`;
        string keys — the block is JSON)."""
        return {str(r): {"state": h.state,
                         "score": round(h.score, 4),
                         "windows": h.windows}
                for r, h in sorted(self._ranks.items())}

    # -- the decision loop ---------------------------------------------

    def observe(self, rank: int, sample: HealthSample,
                can_quarantine: bool = True) -> Optional[Transition]:
        """Fold one window's signals for `rank`; returns the transition
        this window fired, if any. `can_quarantine=False` is the caller's
        min-fleet floor (and the `--on-peer-degraded ignore` policy): a
        confirmed suspect is HELD at suspect rather than benched below a
        runnable partition — and fires a (suspect -> suspect) transition
        with reason `held` exactly once per hold streak, so the refusal
        is observable without flooding."""
        h = self._ranks[int(rank)]
        pol = self.policy
        d = pol.degradation(sample)
        if d is None:
            return None     # no signal: hold everything
        h.windows += 1
        h.score = (1.0 - pol.alpha) * h.score + pol.alpha * d
        _HEALTH_SCORE.set(h.score, rank=str(rank))
        bad = h.score >= pol.suspect_threshold
        good = h.score <= pol.readmit_threshold

        if h.state == STATE_HEALTHY:
            if bad:
                return self._move(rank, h, STATE_SUSPECT,
                                  f"score {h.score:.3f} >= "
                                  f"{pol.suspect_threshold}")
            return None
        if h.state == STATE_SUSPECT:
            if good:
                # exit through the READMIT threshold, not the suspect
                # one: a score oscillating inside the hysteresis band
                # (readmit < score < suspect) holds the state AND the
                # confirmation streak — a threshold-straddling straggler
                # must not flip-flop its way out of ever confirming
                return self._move(rank, h, STATE_HEALTHY,
                                  f"score recovered to {h.score:.3f}")
            if not bad:
                return None     # in the band: hold
            h.streak += 1
            # `confirm` consecutive bad windows AFTER the suspect entry
            # (so the minimum path to quarantine is confirm + 1 bad
            # windows total — the entry window can never convict alone)
            if h.streak < pol.confirm:
                return None
            if not can_quarantine:
                if h.streak == pol.confirm:     # fire the hold once
                    return self._note(rank, h, "held",
                                      "min-fleet floor (or policy) "
                                      "refuses the bench")
                return None
            return self._move(rank, h, STATE_QUARANTINED,
                              f"confirmed over {h.streak + 1} windows")
        if h.state == STATE_QUARANTINED:
            if not good:
                h.streak = 0
                return None
            h.streak += 1
            if h.streak < pol.readmit:
                return None
            return self._move(rank, h, STATE_PROBATION,
                              f"score {h.score:.3f} <= "
                              f"{pol.readmit_threshold} for "
                              f"{h.streak} windows")
        # probation: one bad window relapses (no re-confirmation — the
        # rank already proved it can degrade), `probation` clean windows
        # graduate back to healthy. The relapse is still a QUARANTINE
        # decision, so the caller's floor applies: with no runnable plan
        # left (the spare died meanwhile) the rank is HELD on probation —
        # running degraded beats aborting the fleet.
        if bad:
            if not can_quarantine:
                h.streak = 0    # a bad window breaks the clean streak
                return self._note(rank, h, "held",
                                  "min-fleet floor (or policy) refuses "
                                  "the relapse bench")
            return self._move(rank, h, STATE_QUARANTINED,
                              f"probation relapse (score {h.score:.3f})")
        h.streak += 1
        if h.streak < pol.probation:
            return None
        return self._move(rank, h, STATE_HEALTHY,
                          f"{h.streak} clean probation windows")

    def _move(self, rank: int, h: _RankHealth, to: str, reason: str) \
            -> Transition:
        t = Transition(rank=int(rank), frm=h.state, to=to,
                       score=h.score, window=h.windows, reason=reason)
        h.state = to
        h.streak = 0
        if to == STATE_QUARANTINED:
            _QUARANTINES.inc(rank=str(rank))
        self.transitions.append(t)
        logger.warning("peer health: rank %d %s -> %s (%s)", rank, t.frm,
                       to, reason)
        return t

    def _note(self, rank: int, h: _RankHealth, kind: str, reason: str) \
            -> Transition:
        """A no-move event (the floor hold): recorded and returned like a
        transition so callers can surface it, state untouched."""
        t = Transition(rank=int(rank), frm=h.state, to=h.state,
                       score=h.score, window=h.windows,
                       reason=f"{kind}: {reason}")
        self.transitions.append(t)
        logger.warning("peer health: rank %d stays %s (%s)", rank,
                       h.state, t.reason)
        return t
