"""Opt-in NaN/Inf activation guard at stage boundaries.

A poisoned microbatch — a NaN or Inf produced by a numerics bug, a
corrupted frame that slipped past integrity checks, or a degrading
accelerator — propagates silently: every downstream stage happily
multiplies garbage, and the failure surfaces as wrong answers, not an
error. With `PIPEEDGE_NAN_GUARD=1` the runtime checks activations at
stage boundaries and converts the first poisoned payload into a NAMED
error (`PoisonedActivationError`), a flight-recorder postmortem bundle
(trigger `poison`), and a `pipeedge_poisoned_microbatches_total` bump —
the microbatch dies loudly at the boundary where the poison appeared.

Opt-in because the check is a host sync (`jnp.isfinite(...).all()`
forces the value): the steady-state overlap the DCN stage split buys
(docs/DCN_WIRE.md) is exactly what a per-microbatch sync spends. Turn it
on when chasing a numerics incident, leave it off on the hot path.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

from ..telemetry import flight
from ..telemetry import metrics as prom

logger = logging.getLogger(__name__)

ENV_NAN_GUARD = "PIPEEDGE_NAN_GUARD"

_POISONED = prom.REGISTRY.counter(
    "pipeedge_poisoned_microbatches_total",
    "microbatches whose activations failed the NaN/Inf guard at a stage "
    "boundary (PIPEEDGE_NAN_GUARD=1)")


class PoisonedActivationError(RuntimeError):
    """A stage-boundary activation contained NaN/Inf (the named error the
    guard raises instead of letting garbage propagate downstream)."""

    def __init__(self, where: str, mb: Optional[int] = None,
                 rid: Optional[str] = None):
        self.where = where
        self.mb = mb
        self.rid = rid
        at = f" (mb={mb}" + (f", rid={rid})" if rid else ")") \
            if mb is not None or rid else ""
        super().__init__(
            f"poisoned activations (NaN/Inf) at {where}{at}; postmortem "
            "bundle written — see pipeedge_poisoned_microbatches_total")


def nan_guard_enabled() -> bool:
    return os.getenv(ENV_NAN_GUARD, "0") == "1"


def check_finite(payload, where: str, mb: Optional[int] = None,
                 rid: Optional[str] = None):
    """Pass `payload` (tensor or tuple; numpy or jax arrays) through the
    guard: returns it unchanged when finite or when the guard is off,
    raises `PoisonedActivationError` otherwise — after bumping the
    counter, noting the event on the flight ring, and writing a
    postmortem bundle (never cooldown-starved into silence: the raise
    itself still happens when the dump is suppressed)."""
    if not nan_guard_enabled():
        return payload
    import jax.numpy as jnp

    tensors = payload if isinstance(payload, tuple) else (payload,)
    for t in tensors:
        if getattr(t, "dtype", None) is None \
                or jnp.asarray(t).dtype.kind not in "fc":
            continue    # integer/bool payloads (token ids) cannot poison
        if bool(jnp.isfinite(jnp.asarray(t)).all()):
            continue
        _POISONED.inc()
        flight.note("poisoned", rid=rid, where=where, mb=mb)
        flight.maybe_dump("poison", rid=rid,
                          context={"where": where, "mb": mb})
        logger.error("NaN guard: poisoned activations at %s (mb=%s)",
                     where, mb)
        raise PoisonedActivationError(where, mb=mb, rid=rid)
    return payload
