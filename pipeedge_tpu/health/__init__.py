"""Peer-health plane: gray-failure detection for the DCN fleet.

Fail-stop faults trip the liveness plane (missed heartbeats, dropped
connections — docs/FAULT_TOLERANCE.md); a *gray* failure does not: a
throttled TPU, a degrading NIC, or a noisy neighbor keeps a rank alive
and beating while every microbatch drags through the pipeline's new
bottleneck stage. This package closes the telemetry loop the repo
already has — per-round span digests measure per-stage cost
(telemetry/feedback.py), heartbeats prove liveness (comm/dcn.py), the
membership plane can bench and re-expand ranks (sched/failover.py) —
into a detector:

- `scorer.PeerHealthScorer` folds per-window signals (relative stage
  service time, heartbeat RTT, transport send retries) into an EWMA
  health score per rank and walks the gray rank lifecycle
  `healthy -> suspect -> quarantined -> probation -> healthy` with
  brownout-style hysteresis (suspect and readmit thresholds differ,
  N-consecutive-windows confirmation both directions).
- `guard.check_finite` is the opt-in NaN/Inf activation guard at stage
  boundaries (`PIPEEDGE_NAN_GUARD=1`): a poisoned microbatch raises
  `guard.PoisonedActivationError` and writes a flight-recorder
  postmortem instead of propagating garbage downstream.

The scorer registers itself as a process singleton so observability
surfaces (`tools/serve.py` /healthz, tests) can read the fleet's
per-peer scores without plumbing: `snapshot()` returns `{}` until a
runtime installs a scorer.
"""
from __future__ import annotations

from typing import Dict, Optional

from ..utils.threads import make_lock
from .guard import PoisonedActivationError, check_finite, nan_guard_enabled
from .scorer import (HealthPolicy, HealthSample, PeerHealthScorer,
                     Transition, STATE_HEALTHY, STATE_PROBATION,
                     STATE_QUARANTINED, STATE_SUSPECT)

__all__ = [
    "HealthPolicy", "HealthSample", "PeerHealthScorer", "Transition",
    "STATE_HEALTHY", "STATE_SUSPECT", "STATE_QUARANTINED",
    "STATE_PROBATION", "PoisonedActivationError", "check_finite",
    "nan_guard_enabled", "set_scorer", "scorer", "snapshot",
]

_scorer: Optional[PeerHealthScorer] = None
_scorer_lock = make_lock("health.singleton")


def set_scorer(scorer_obj: Optional[PeerHealthScorer]) -> None:
    """Install (or clear, with None) the process's peer-health scorer —
    what the DCN data rank does at fleet bring-up so /healthz and tests
    can read the same state the quarantine decisions run on."""
    global _scorer  # pylint: disable=global-statement
    with _scorer_lock:
        _scorer = scorer_obj


def scorer() -> Optional[PeerHealthScorer]:
    with _scorer_lock:
        return _scorer


def snapshot() -> Dict[str, dict]:
    """Per-peer health state for observability surfaces (the /healthz
    `peer_health` block): `{rank: {state, score, windows}}`; empty when
    no scorer is installed in this process."""
    with _scorer_lock:
        sc = _scorer
    return sc.snapshot() if sc is not None else {}
