"""pipeedge_tpu: a TPU-native pipeline-parallel transformer inference framework.

A ground-up JAX/XLA rebuild of the capabilities of usc-isi/PipeEdge
(reference: /root/reference): pipeline-parallel inference over layer-range
model shards (ViT / DeiT / BERT), microbatch streaming between stages,
profile-driven heterogeneous scheduling (native C++ DP scheduler +
reverse-auction schedulers), QuantPipe-style quantized inter-stage
activations with adaptive bitwidth policies, and heartbeat monitoring.

Architecture (TPU-first, not a port):
- Model shards are *pure functions* over parameter pytrees with static
  shapes, jit-compiled per (model, layer-range, microbatch) signature.
- Stage-to-stage transport inside a slice is XLA collective-permute
  (`jax.lax.ppermute`) under `shard_map` over a device mesh; a host-driven
  driver with `jax.device_put` edges is the simple/debug path.
- The quantized activation wire format is a fixed-shape packed uint32
  buffer + scalar metadata (vs the reference's pickled dynamic tensors).
"""

__version__ = "0.1.0"
