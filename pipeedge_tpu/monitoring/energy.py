"""Concrete energy sources for the monitoring subsystem.

The reference meters energy through the `energymon` native library with a
graceful fallback when it's missing or unpermitted (reference
monitoring.py:104-121, monitoring/__init__.py:110-114). The TPU-host
equivalent: Linux powercap/RAPL sysfs counters, which cover the host CPU
package(s) — TPU chip power is not exposed through JAX, so host-side RAPL is
what an edge-style deployment can actually meter. `default_energy_source()`
preserves the reference's fallback contract: returns None (all energy/power
metrics read 0) when no readable counter exists.
"""
from __future__ import annotations

import glob
import logging
import os
from typing import List, Optional

from . import EnergySource

logger = logging.getLogger(__name__)

_POWERCAP_ROOT = "/sys/class/powercap"


class RaplEnergySource(EnergySource):
    """Cumulative microjoules from powercap RAPL package domains.

    Sums every readable top-level `intel-rapl:<n>/energy_uj` counter and
    handles counter wraparound via `max_energy_range_uj` (the counters are
    typically 32-bit-ish and wrap within hours under load).
    """

    def __init__(self, root: str = _POWERCAP_ROOT):
        self._root = root
        self._domains: List[str] = []
        self._ranges: List[int] = []
        self._last: List[int] = []
        self._wrap_uj: List[int] = []

    def init(self) -> None:
        pattern = os.path.join(self._root, "intel-rapl:[0-9]*")
        for d in sorted(glob.glob(pattern)):
            if not os.path.basename(d).count(":") == 1:
                continue  # skip subdomains like intel-rapl:0:0
            path = os.path.join(d, "energy_uj")
            try:
                with open(path, encoding="ascii") as f:
                    first = int(f.read().strip())
            except (OSError, ValueError):
                continue  # unreadable (permissions) or malformed
            try:
                with open(os.path.join(d, "max_energy_range_uj"),
                          encoding="ascii") as f:
                    rng = int(f.read().strip())
            except (OSError, ValueError):
                rng = 0
            self._domains.append(path)
            self._ranges.append(rng)
            self._last.append(first)
            self._wrap_uj.append(0)
        if not self._domains:
            raise RuntimeError(f"no readable RAPL domains under {self._root}")

    def finish(self) -> None:
        self._domains = []

    def get_uj(self) -> int:
        total = 0
        for i, path in enumerate(self._domains):
            try:
                with open(path, encoding="ascii") as f:
                    now = int(f.read().strip())
            except (OSError, ValueError):
                # Transient read failure: report the last known value so the
                # cumulative total never goes backwards (a dropped domain
                # would make this iteration's delta hugely negative).
                total += self._last[i] + self._wrap_uj[i]
                continue
            if now < self._last[i]:
                # Counter wrapped. When the range is unreadable (rng==0),
                # the best wrap estimate is the last observed value.
                self._wrap_uj[i] += self._ranges[i] if self._ranges[i] > 0 \
                    else self._last[i]
            self._last[i] = now
            total += now + self._wrap_uj[i]
        return total

    def get_source(self) -> str:
        return f"RAPL({len(self._domains)} domains)" if self._domains \
            else "RAPL(uninitialized)"


def default_energy_source(root: str = _POWERCAP_ROOT) \
        -> Optional[EnergySource]:
    """A working `RaplEnergySource`, or None when the host exposes no
    readable counters (the reference's graceful fallback)."""
    src = RaplEnergySource(root)
    try:
        src.init()
    except RuntimeError as exc:
        logger.info("energy metering unavailable: %s", exc)
        return None
    src.finish()
    return RaplEnergySource(root)
