"""Heartbeat-based monitoring: keyed work/energy/accuracy windows + CSV logs.

Capability parity with /root/reference/src/pipeedge/monitoring/__init__.py
(MonitorContext, 61-364), with the two native dependencies replaced:

- `apphb.Heartbeat` -> an in-module ring-buffer heartbeat (per-beat
  duration/work/energy/accuracy; instant = last beat, window = last
  `window_size` beats, global = everything).
- `energymon` -> a pluggable `EnergySource`. TPU power telemetry is not
  exposed through JAX, so the default source is None and all energy/power
  metrics read 0 — the same graceful fallback the reference applies when the
  energymon library is missing (monitoring.py:104-121). A custom source (for
  hosts with RAPL sysfs, for instance) can be passed in.

Semantics preserved: the (instant | window | global) x (time | heartrate |
work | perf | energy | power | accuracy | accuracy-rate) getter matrix
(monitoring/__init__.py:228-330), per-beat CSV rows with rates normalized to
/s and W (216-224), reusable-context-manager behavior, and a pickling block.

CSV logs are held-open file handles (one per key), with every row flushed
and an explicit `flush()` hook, so a rank that dies or fails over
mid-run leaves complete post-mortem records (docs/FAULT_TOLERANCE.md).
"""
from __future__ import annotations

import csv
import dataclasses
import time
import warnings
from collections import deque
from typing import Any, Optional, Union

_NS_PER_S = 1_000_000_000


class EnergySource:
    """Interface for an energy meter; `get_uj()` returns cumulative microjoules."""

    def init(self) -> None:  # pragma: no cover - interface
        pass

    def finish(self) -> None:  # pragma: no cover - interface
        pass

    def get_uj(self) -> int:  # pragma: no cover - interface
        return 0

    def get_source(self) -> str:  # pragma: no cover - interface
        return "None"


@dataclasses.dataclass
class MonitorIterationContext:
    """In-flight iteration state — clients should not modify."""
    t_ns_last: Optional[int] = None
    e_uj_last: Optional[int] = None


@dataclasses.dataclass
class _Beat:
    duration_ns: int
    work: Union[int, float]
    energy_uj: int
    accuracy: Union[int, float]


class _Heartbeat:
    """Ring-buffer heartbeat with instant/window/global aggregation."""

    def __init__(self, window_size: int):
        assert window_size > 0
        self.window_size = window_size
        self._window = deque(maxlen=window_size)
        self._totals = _Beat(0, 0, 0, 0)
        self.count = 0

    def beat(self, duration_ns, work, energy_uj, accuracy):
        b = _Beat(duration_ns, work, energy_uj, accuracy)
        self._window.append(b)
        self._totals.duration_ns += duration_ns
        self._totals.work += work
        self._totals.energy_uj += energy_uj
        self._totals.accuracy += accuracy
        self.count += 1

    def _scope(self, scope: str):
        if scope == "instant":
            if not self._window:
                return _Beat(0, 0, 0, 0), 0
            return self._window[-1], 1
        if scope == "window":
            agg = _Beat(0, 0, 0, 0)
            for b in self._window:
                agg.duration_ns += b.duration_ns
                agg.work += b.work
                agg.energy_uj += b.energy_uj
                agg.accuracy += b.accuracy
            return agg, len(self._window)
        return self._totals, self.count

    def time_ns(self, scope): return self._scope(scope)[0].duration_ns

    def heartrate(self, scope):
        agg, n = self._scope(scope)
        return n * _NS_PER_S / agg.duration_ns if agg.duration_ns else 0.0

    def work(self, scope): return self._scope(scope)[0].work

    def perf(self, scope):
        agg, _ = self._scope(scope)
        return agg.work * _NS_PER_S / agg.duration_ns if agg.duration_ns else 0.0

    def energy_uj(self, scope): return self._scope(scope)[0].energy_uj

    def power_w(self, scope):
        agg, _ = self._scope(scope)
        # uJ/ns == 1000 W
        return agg.energy_uj * 1000 / agg.duration_ns if agg.duration_ns else 0.0

    def accuracy(self, scope): return self._scope(scope)[0].accuracy

    def accuracy_rate(self, scope):
        agg, _ = self._scope(scope)
        return agg.accuracy * _NS_PER_S / agg.duration_ns if agg.duration_ns else 0.0


_CSV_HEADER = ["Tag", "Time (ns)", "Heart Rate (/s)", "Work",
               "Performance (/s)", "Energy (uJ)", "Power (W)", "Accuracy",
               "Accuracy Rate (/s)"]


def _format_record(record):
    """High-precision floats, never exponential (reference monitoring/__init__.py:39-41)."""
    return [f"{r:.15f}" if isinstance(r, float) else r for r in record]


@dataclasses.dataclass
class _KeyedState:
    hbt: _Heartbeat
    log_name: Optional[str] = None
    log_mode: str = "x"
    iter_ctx: MonitorIterationContext = dataclasses.field(
        default_factory=MonitorIterationContext)
    tag: int = 0
    # held-open CSV handle (opened by MonitorContext.open/add_heartbeat):
    # rows append to it without a reopen per beat, and every row is flushed
    # so a crashed process's post-mortem log never loses its tail
    log_file: Optional[Any] = None


class MonitorContext:
    """Top-level monitoring interface (reusable context manager, not reentrant).

    Parameters mirror the reference (monitoring/__init__.py:98-114), with
    `energy_source` (an `EnergySource` or None) replacing the energymon
    library name/getter pair.
    """

    def __init__(self, key: Any = None, window_size: int = 1,
                 log_name: Optional[str] = None, log_mode: str = "x",
                 energy_source: Optional[EnergySource] = None):
        self._initialized = False
        self._key = key
        self._states = {key: _KeyedState(_Heartbeat(window_size), log_name, log_mode)}
        self._em = energy_source

    def keys(self) -> tuple:
        return tuple(self._states.keys())

    def add_heartbeat(self, key: Any = None, window_size: Optional[int] = None,
                      log_name: Optional[str] = None,
                      log_mode: Optional[str] = None) -> None:
        """Add a heartbeat for a new key (monitoring/__init__.py:120-148)."""
        if key in self._states:
            raise ValueError(f"key already in use: {key}")
        if window_size is None:
            window_size = self.get_window_size(key=self._key)
        if log_mode is None:
            log_mode = self._states[self._key].log_mode
        self._states[key] = _KeyedState(_Heartbeat(window_size), log_name, log_mode)
        if self._initialized:
            self._log_header(self._states[key])

    def _log_header(self, state: _KeyedState) -> None:
        if state.log_name is not None:
            state.log_file = open(state.log_name, mode=state.log_mode,
                                  encoding="utf8")
            csv.writer(state.log_file, delimiter=",",
                       quoting=csv.QUOTE_MINIMAL).writerow(_CSV_HEADER)
            state.log_file.flush()

    def open(self) -> None:
        if self._initialized:
            raise RuntimeError("Monitor is already open")
        if self._em is not None:
            self._em.init()
        self._initialized = True
        for state in self._states.values():
            self._log_header(state)

    def flush(self) -> None:
        """Push buffered CSV rows to the OS — the fleet-abort / failover
        hook that makes post-mortem records survive whatever comes next."""
        for state in self._states.values():
            if state.log_file is not None and not state.log_file.closed:
                state.log_file.flush()

    def close(self) -> None:
        self._initialized = False
        for state in self._states.values():
            if state.log_file is not None and not state.log_file.closed:
                state.log_file.close()
            state.log_file = None
        if self._em is not None:
            self._em.finish()

    def _check_init(self):
        if not self._initialized:
            raise RuntimeError("Monitor is not open")

    def iteration_start(self, key: Any = None,
                        iter_ctx: Optional[MonitorIterationContext] = None) -> None:
        """Begin a measurement (monitoring/__init__.py:170-187)."""
        self._check_init()
        if iter_ctx is None:
            iter_ctx = self._states[key].iter_ctx
        iter_ctx.t_ns_last = time.monotonic_ns()
        iter_ctx.e_uj_last = 0 if self._em is None else self._em.get_uj()

    def iteration_reset(self, key: Any = None) -> None:
        """Forget the key's shared last-beat baseline: the next
        start-less `iteration` becomes a fresh first beat instead of
        recording the idle gap since the previous beat as one giant
        iteration (beat-to-beat consumers crossing an idle boundary,
        e.g. a DCN re-schedule round)."""
        self._check_init()
        iter_ctx = self._states[key].iter_ctx
        iter_ctx.t_ns_last = None
        iter_ctx.e_uj_last = None

    def iteration(self, key: Any = None, work: int = 1,
                  accuracy: Union[int, float] = 1,
                  iter_ctx: Optional[MonitorIterationContext] = None) -> None:
        """Complete a measurement and emit a heartbeat + CSV row
        (monitoring/__init__.py:189-226)."""
        self._check_init()
        t_ns = time.monotonic_ns()
        e_uj = 0 if self._em is None else self._em.get_uj()
        state = self._states[key]
        if iter_ctx is None:
            iter_ctx = state.iter_ctx
        # calling without a prior start makes this call the start
        if iter_ctx.t_ns_last is not None:
            state.hbt.beat(t_ns - iter_ctx.t_ns_last, work,
                           e_uj - iter_ctx.e_uj_last, accuracy)
            state.tag += 1
            if state.log_file is not None and not state.log_file.closed:
                hbt = state.hbt
                rec = [state.tag - 1, hbt.time_ns("instant"),
                       hbt.heartrate("instant"), hbt.work("instant"),
                       hbt.perf("instant"), hbt.energy_uj("instant"),
                       hbt.power_w("instant"), hbt.accuracy("instant"),
                       hbt.accuracy_rate("instant")]
                csv.writer(state.log_file, delimiter=",",
                           quoting=csv.QUOTE_MINIMAL
                           ).writerow(_format_record(rec))
                state.log_file.flush()
        iter_ctx.t_ns_last = t_ns
        iter_ctx.e_uj_last = e_uj

    # getter matrix: (instant | window | global) x 8 metrics
    def get_instant_time_s(self, key=None): return self._states[key].hbt.time_ns("instant") / _NS_PER_S
    def get_instant_heartrate(self, key=None): return self._states[key].hbt.heartrate("instant")
    def get_instant_work(self, key=None): return self._states[key].hbt.work("instant")
    def get_instant_perf(self, key=None): return self._states[key].hbt.perf("instant")
    def get_instant_energy_j(self, key=None): return self._states[key].hbt.energy_uj("instant") / 1e6
    def get_instant_power_w(self, key=None): return self._states[key].hbt.power_w("instant")
    def get_instant_accuracy(self, key=None): return self._states[key].hbt.accuracy("instant")
    def get_instant_accuracy_rate(self, key=None): return self._states[key].hbt.accuracy_rate("instant")

    def get_window_time_s(self, key=None): return self._states[key].hbt.time_ns("window") / _NS_PER_S
    def get_window_heartrate(self, key=None): return self._states[key].hbt.heartrate("window")
    def get_window_work(self, key=None): return self._states[key].hbt.work("window")
    def get_window_perf(self, key=None): return self._states[key].hbt.perf("window")
    def get_window_energy_j(self, key=None): return self._states[key].hbt.energy_uj("window") / 1e6
    def get_window_power_w(self, key=None): return self._states[key].hbt.power_w("window")
    def get_window_accuracy(self, key=None): return self._states[key].hbt.accuracy("window")
    def get_window_accuracy_rate(self, key=None): return self._states[key].hbt.accuracy_rate("window")

    def get_global_time_s(self, key=None): return self._states[key].hbt.time_ns("global") / _NS_PER_S
    def get_global_heartrate(self, key=None): return self._states[key].hbt.heartrate("global")
    def get_global_work(self, key=None): return self._states[key].hbt.work("global")
    def get_global_perf(self, key=None): return self._states[key].hbt.perf("global")
    def get_global_energy_j(self, key=None): return self._states[key].hbt.energy_uj("global") / 1e6
    def get_global_power_w(self, key=None): return self._states[key].hbt.power_w("global")
    def get_global_accuracy(self, key=None): return self._states[key].hbt.accuracy("global")
    def get_global_accuracy_rate(self, key=None): return self._states[key].hbt.accuracy_rate("global")

    # the 8 metrics of the getter matrix, as (name, per-scope accessor)
    _SNAPSHOT_METRICS = (
        ("time_s", lambda h, s: h.time_ns(s) / _NS_PER_S),
        ("heartrate", lambda h, s: h.heartrate(s)),
        ("work", lambda h, s: h.work(s)),
        ("perf", lambda h, s: h.perf(s)),
        ("energy_j", lambda h, s: h.energy_uj(s) / 1e6),
        ("power_w", lambda h, s: h.power_w(s)),
        ("accuracy", lambda h, s: h.accuracy(s)),
        ("accuracy_rate", lambda h, s: h.accuracy_rate(s)),
    )

    def snapshot(self) -> dict:
        """The whole (instant | window | global) x metric getter matrix for
        every key as ONE dict — `{key: {scope: {metric: value}, "tag": n,
        "window_size": n}}` — so telemetry/metrics exporters read the
        monitoring state in one call instead of reaching into the per-key
        getters one at a time."""
        out = {}
        for key, state in self._states.items():
            hbt = state.hbt
            entry: dict = {
                scope: {name: fn(hbt, scope)
                        for name, fn in self._SNAPSHOT_METRICS}
                for scope in ("instant", "window", "global")}
            entry["tag"] = state.tag
            entry["window_size"] = hbt.window_size
            out[key] = entry
        return out

    def get_tag(self, key: Any = None) -> int:
        """The next tag (== completed heartbeat count)."""
        return self._states[key].tag

    def get_window_size(self, key: Any = None) -> int:
        return self._states[key].hbt.window_size

    @property
    def initialized(self) -> bool:
        return self._initialized

    @property
    def energy_source(self) -> str:
        return "None" if self._em is None else self._em.get_source()

    def __enter__(self):
        self.open()
        return self

    def __exit__(self, *args):
        self.close()

    def __del__(self):
        if self._initialized:
            warnings.warn("unclosed monitor", category=ResourceWarning, source=self)
            self.close()

    def __getstate__(self):
        raise TypeError(f"Cannot pickle {self.__class__.__name__!r} object")
