"""Schemas for the scheduler's YAML data interchange.

The emitted dict shapes are the interop contract shared with the native
`sched-pipeline` binary and the reverse-auction scheduler (same formats as
the reference framework's models.yml / device_types.yml /
device_neighbors*.yml — documented schemas in
/root/reference/README_Scheduler.md:44-264). Each `yaml_*` constructor
validates its inputs (raising TypeError on schema violations) and returns a
plain dict ready for `yaml.safe_dump`.
"""
from typing import List, Optional, Union

Scalar = Union[int, float]


def _require(cond: bool, what: str) -> None:
    if not cond:
        raise TypeError(f"yaml schema: {what}")


def _number_series(xs, what: str) -> List[Scalar]:
    _require(isinstance(xs, list), f"{what} must be a list")
    _require(all(isinstance(x, (int, float)) for x in xs),
             f"{what} entries must be numbers")
    return list(xs)


def yaml_model(num_layers: int, parameters_in: int, parameters_out: List[int],
               mem_MB: List[Scalar]) -> dict:
    """A models.yml record: layer count, boundary element counts, per-layer
    weight memory. `parameters_out[i]` (elements flowing out of layer i) is
    the scheduler's comm-bytes source."""
    _require(isinstance(num_layers, int), "layers must be int")
    _require(isinstance(parameters_in, int), "parameters_in must be int")
    _require(isinstance(parameters_out, list)
             and all(isinstance(p, int) for p in parameters_out),
             "parameters_out must be a list of int")
    return {
        'layers': num_layers,
        'parameters_in': parameters_in,
        'parameters_out': list(parameters_out),
        'mem_MB': _number_series(mem_MB, "mem_MB"),
    }


def yaml_model_profile(dtype: str, batch_size: int,
                       time_s: List[Scalar]) -> dict:
    """A device type's timing profile for one model; (dtype, batch_size) is
    the unique key within a model's profile list."""
    _require(isinstance(dtype, str), "dtype must be str")
    _require(isinstance(batch_size, int), "batch_size must be int")
    return {
        'dtype': dtype,
        'batch_size': batch_size,
        'time_s': _number_series(time_s, "time_s"),
    }


def yaml_device_type(mem_MB: Scalar, bw_Mbps: Scalar,
                     model_profiles: Optional[dict]) -> dict:
    """A device_types.yml record: capacity plus per-model timing profiles."""
    _require(isinstance(mem_MB, (int, float)), "mem_MB must be a number")
    _require(isinstance(bw_Mbps, (int, float)), "bw_Mbps must be a number")
    _require(model_profiles is None or isinstance(model_profiles, dict),
             "model_profiles must be a dict")
    return {
        'mem_MB': mem_MB,
        'bw_Mbps': bw_Mbps,
        'model_profiles': dict(model_profiles or {}),
    }


def yaml_device_neighbors_type(bw_Mbps: Scalar) -> dict:
    """A neighbor-link record (extensible: today just bandwidth)."""
    _require(isinstance(bw_Mbps, (int, float)), "bw_Mbps must be a number")
    return {'bw_Mbps': bw_Mbps}


def yaml_device_neighbors(neighbors: List[str],
                          bws_Mbps: List[Scalar]) -> dict:
    """A host's neighbor map: neighbor name -> link record."""
    _require(isinstance(neighbors, list)
             and all(isinstance(n, str) for n in neighbors),
             "neighbors must be a list of str")
    _number_series(bws_Mbps, "bws_Mbps")
    return {name: yaml_device_neighbors_type(bw)
            for name, bw in zip(neighbors, bws_Mbps)}
