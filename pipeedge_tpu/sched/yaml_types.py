"""Constructors/validators for the scheduler's YAML data interchange types.

Parity with /root/reference/src/pipeedge/sched/yaml_types.py:11-82; the same
dict shapes flow between the profiler, the converters, the native
sched-pipeline binary, and the reverse-auction scheduler.
"""
from typing import List, Optional, Union


def _assert_list_type(lst, dtype):
    assert isinstance(lst, list)
    for var in lst:
        assert isinstance(var, dtype)


def yaml_model(num_layers: int, parameters_in: int, parameters_out: List[int],
               mem_MB: Union[List[int], List[float]]) -> dict:
    """A models.yml entry (yaml_types.py:11-24)."""
    assert isinstance(num_layers, int)
    assert isinstance(parameters_in, int)
    _assert_list_type(parameters_out, int)
    _assert_list_type(mem_MB, (int, float))
    return {
        'layers': num_layers,
        'parameters_in': parameters_in,
        'parameters_out': parameters_out,
        'mem_MB': mem_MB,
    }


def yaml_model_profile(dtype: str, batch_size: int,
                       time_s: Union[List[int], List[float]]) -> dict:
    """A device type's per-model profile entry (yaml_types.py:27-38)."""
    assert isinstance(dtype, str)
    assert isinstance(batch_size, int)
    _assert_list_type(time_s, (int, float))
    return {
        'dtype': dtype,
        'batch_size': batch_size,
        'time_s': time_s,
    }


def yaml_device_type(mem_MB: Union[int, float], bw_Mbps: Union[int, float],
                     model_profiles: Optional[dict]) -> dict:
    """A device_types.yml entry (yaml_types.py:55-69)."""
    assert isinstance(mem_MB, (int, float))
    assert isinstance(bw_Mbps, (int, float))
    if model_profiles is None:
        model_profiles = {}
    assert isinstance(model_profiles, dict)
    return {
        'mem_MB': mem_MB,
        'bw_Mbps': bw_Mbps,
        'model_profiles': model_profiles,
    }


def yaml_device_neighbors_type(bw_Mbps: Union[int, float]) -> dict:
    """A neighbor-link entry; extensible (yaml_types.py:71-77)."""
    assert isinstance(bw_Mbps, (int, float))
    return {'bw_Mbps': bw_Mbps}


def yaml_device_neighbors(neighbors: List[str],
                          bws_Mbps: Union[List[int], List[float]]) -> dict:
    """Map of neighbor host -> link properties (yaml_types.py:79-82)."""
    _assert_list_type(neighbors, str)
    _assert_list_type(bws_Mbps, (int, float))
    return {neighbor: yaml_device_neighbors_type(bw)
            for neighbor, bw in zip(neighbors, bws_Mbps)}
