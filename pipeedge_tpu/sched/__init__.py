"""Scheduling: cost model mirroring the native scheduler.

Parity with /root/reference/src/pipeedge/sched/__init__.py:17-69. Layers are
0-based here, 1-based in the native scheduler and runtime CLIs (the same
legacy convention the reference documents in its module docstring).

TPU extension: bfloat16/float16 dtypes (the reference only knows
torch.float32) — inter-stage payloads and buffers on TPU default to bf16.
"""
from typing import Union

_DTYPE_BYTES = {
    'torch.float32': 4,
    'float32': 4,
    'torch.bfloat16': 2,
    'bfloat16': 2,
    'torch.float16': 2,
    'float16': 2,
}


def normalize_dtype(dtype: str) -> str:
    """'torch.float32' and 'float32' name the same dtype: reference-format
    YAML uses torch-style names, the TPU profiler writes bare jnp names.
    Mirrors normalize_dtype in native/sched_pipeline_main.cpp."""
    return dtype[len('torch.'):] if dtype.startswith('torch.') else dtype


def _dtype_bytes(dtype: str) -> int:
    """Bytes for a single value of `dtype`."""
    return _DTYPE_BYTES[dtype]


def ubatch_bytes(n_params: int, ubatch_size: int, dtype: str = 'torch.float32') -> int:
    """Bytes required for a microbatch buffer (reference sched/__init__.py:17-19)."""
    return n_params * ubatch_size * _dtype_bytes(dtype)


def mem_bytes(yml_model: dict, layer_l: int, layer_r: int, dtype: str,
              ubatch_size: int, data_buffers_in: int = 2,
              data_buffers_out: int = 2) -> int:
    """Estimated memory for a complete stage: weights + in/out data buffers +
    processing buffers (reference sched/__init__.py:22-48). Layers 0-based."""
    assert len(yml_model['mem_MB']) == len(yml_model['parameters_out'])
    assert 0 <= layer_l <= layer_r < len(yml_model['mem_MB'])
    weights = sum(yml_model['mem_MB'][layer_l:layer_r + 1]) * 1024 * 1024
    params_in = yml_model['parameters_in'] if layer_l == 0 else \
        yml_model['parameters_out'][layer_l - 1]
    bytes_in = ubatch_bytes(params_in, ubatch_size, dtype=dtype)
    bytes_out = ubatch_bytes(yml_model['parameters_out'][layer_r], ubatch_size,
                             dtype=dtype)
    buffers = 0
    if layer_l > 0:
        buffers += bytes_in * data_buffers_in   # recv buffer (+ queue)
    buffers += bytes_out * data_buffers_out     # send buffer (+ queue)
    buffers += bytes_in + bytes_out             # processing buffers
    return weights + buffers


def computation_time(yml_model_profile: dict, layer_l: int, layer_r: int) -> float:
    """Seconds to process a layer range (reference sched/__init__.py:51-57)."""
    time_s = yml_model_profile['time_s']
    assert 0 <= layer_l <= layer_r < len(time_s)
    return sum(time_s[layer_l:layer_r + 1])


def communication_time(yml_device_type: dict, data_bytes: int) -> float:
    """Seconds to transfer `data_bytes` at the device's bandwidth."""
    return communication_time_bw(yml_device_type['bw_Mbps'], data_bytes)


def communication_time_bw(bw_mbits_sec: Union[int, float], data_bytes: int) -> float:
    """Seconds to transfer `data_bytes` at `bw_mbits_sec` Mbit/s
    (reference sched/__init__.py:60-69: Mb = 1024*1024 bits)."""
    bytes_sec = bw_mbits_sec * 1024 * 1024 / 8
    return data_bytes / bytes_sec
