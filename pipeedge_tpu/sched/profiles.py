"""Bridge from profiler results to the scheduler's YAML input files.

The offline profiler emits one `profiler_results.yml` per (model, dtype,
batch) run; the scheduler consumes two different projections of it:

- `models.yml` — per-model structure: layer count, boundary element counts
  (`parameters_in`/`parameters_out`, the comm-bytes source for the DP
  scheduler's edge costs), per-layer weight memory.
- `device_types.yml` — per-device-type capacity plus (dtype, batch)-keyed
  timing profiles for each model measured on that device type.

This module owns the validation + merge ("upsert") semantics both root CLI
converters share; the scripts are thin argparse shims over it. Role parity
with the reference's converter pair (profiler_results_to_models.py /
profiler_results_to_device_types.py), redesigned as a library.
"""
import dataclasses
import math
from typing import List, Optional, Sequence

import yaml

from . import normalize_dtype, yaml_files, yaml_types


class ProfileError(Exception):
    """A profiler-results file is inconsistent or a merge would clobber."""


@dataclasses.dataclass(frozen=True)
class ProfilerResults:
    """A parsed, validated profiler_results.yml."""
    model_name: str
    dtype: str
    batch_size: int
    layers: int
    profile_data: List[dict]

    @classmethod
    def load(cls, path: str, known_layer_counts=None) -> "ProfilerResults":
        """Read + validate a results file.

        `known_layer_counts`: optional callable name -> expected layer count
        (the model registry); a mismatch or unknown model only warns, since
        profiles for models outside the registry are legitimate.
        """
        with open(path, "r", encoding="utf-8") as f:
            raw = yaml.safe_load(f)
        res = cls(model_name=raw["model_name"], dtype=raw["dtype"],
                  batch_size=raw["batch_size"], layers=raw["layers"],
                  profile_data=list(raw["profile_data"]))
        if not res.profile_data:
            raise ProfileError(f"{path}: empty profile data")
        if res.layers != len(res.profile_data):
            raise ProfileError(
                f"{path}: declared layer count {res.layers} != "
                f"{len(res.profile_data)} profile records")
        if known_layer_counts is not None:
            try:
                expected = known_layer_counts(res.model_name)
            except (KeyError, ValueError):
                print(f"Warning: layer count unverifiable for model outside "
                      f"the registry: {res.model_name}: {res.layers}")
            else:
                if expected != res.layers:
                    print(f"Warning: registry expects {expected} layers for "
                          f"{res.model_name}, profile has {res.layers}")
        return res

    # -- projections -------------------------------------------------------

    def model_entry(self) -> dict:
        """models.yml record: boundary element counts from recorded shapes."""
        def elements(shapes: Sequence[Sequence[int]]) -> int:
            return sum(math.prod(s) for s in shapes)

        return yaml_types.yaml_model(
            self.layers,
            elements(self.profile_data[0]["shape_in"]),
            [elements(rec["shape_out"]) for rec in self.profile_data],
            [rec["memory"] for rec in self.profile_data])

    def timing_profile(self) -> dict:
        """device_types.yml model-profile record (dtype+batch keyed)."""
        return yaml_types.yaml_model_profile(
            self.dtype, self.batch_size,
            [rec["time"] for rec in self.profile_data])

    def matches_profile(self, profile: dict) -> bool:
        """Whether `profile` carries this run's unique (dtype, batch) key.
        dtype compares normalized, so 'float32' == 'torch.float32'."""
        return (normalize_dtype(profile["dtype"]) == normalize_dtype(self.dtype)
                and profile["batch_size"] == self.batch_size)


# ---------------------------------------------------------------------------
# Live-measurement ingestion: runtime span timings -> profiler_results.yml

def results_from_measured(model_name: str, dtype: str, batch_size: int,
                          total_layers: int,
                          partition: Sequence[Sequence[int]],
                          stage_times_s: Sequence[float]) -> dict:
    """A profiler_results.yml-shaped record built from MEASURED per-stage
    runtime timings (tools/trace_report.py --emit-profiles) instead of the
    offline profiler: stage i's per-microbatch seconds spread uniformly
    over its `[l, r]` layer range — the per-layer resolution a per-stage
    measurement supports.

    Only the `time` series carries live data; `shape_in`/`shape_out`/
    `memory` are zeroed placeholders, so the record feeds
    `upsert_device_type` (timing profiles, what offline re-scheduling
    needs) but NOT `upsert_model` (structure comes from the static
    profiler's models.yml). `ProfilerResults.load` reads the file back.
    """
    from . import rebalance

    partition = [tuple(map(int, lr)) for lr in partition]
    try:
        # one owner for the partition contract + uniform spreading: the
        # runtime rebalancer and this offline path must always agree on
        # what a valid partition is
        per_layer = rebalance.spread_layer_costs(partition, stage_times_s)
    except ValueError as exc:
        raise ProfileError(str(exc)) from exc
    if len(per_layer) != total_layers:
        raise ProfileError(f"partition {partition} covers {len(per_layer)} "
                           f"layers, model has {total_layers}")
    profile_data = [{"time": t, "shape_in": [[0]], "shape_out": [[0]],
                     "memory": 0.0} for t in per_layer]
    return {"model_name": model_name, "dtype": dtype,
            "batch_size": int(batch_size), "layers": int(total_layers),
            "profile_data": profile_data}


def save_measured_profiles(path: str, record: dict) -> None:
    """Write a `results_from_measured` record as profiler_results.yml."""
    with open(path, "w", encoding="utf-8") as f:
        yaml.safe_dump(record, f, default_flow_style=None)


# ---------------------------------------------------------------------------
# Merge operations (each loads, upserts one record, saves)

def upsert_model(path: str, results: ProfilerResults,
                 overwrite: bool = False) -> None:
    """Merge the results' model entry into a models.yml file."""
    models = yaml_files.yaml_models_load(path)
    if results.model_name in models and not overwrite:
        raise ProfileError(f"model already exists: {path}: "
                           f"{results.model_name} (use overwrite)")
    models[results.model_name] = results.model_entry()
    yaml_files.yaml_save(models, path)


def upsert_device_type(path: str, dev_type: str, results: ProfilerResults,
                       mem_MB: Optional[float] = None,
                       bw_Mbps: Optional[float] = None,
                       overwrite: bool = False) -> None:
    """Merge the results' timing profile into a device_types.yml file.

    Creating a new device type requires mem_MB + bw_Mbps; an existing type's
    capacity values must not silently change (pass them identical or None).
    """
    device_types = yaml_files.yaml_device_types_load(path)
    entry = device_types.get(dev_type)
    if entry is None:
        if mem_MB is None or bw_Mbps is None:
            raise ProfileError(
                f"new device type {dev_type}: memory and bandwidth required")
        entry = yaml_types.yaml_device_type(mem_MB, bw_Mbps, {})
        device_types[dev_type] = entry
    else:
        for key, given in (("mem_MB", mem_MB), ("bw_Mbps", bw_Mbps)):
            if given is not None and entry[key] != given:
                raise ProfileError(
                    f"device type {dev_type} {key} mismatch: "
                    f"{entry[key]} != {given}")
        if entry.get("model_profiles") is None:
            entry["model_profiles"] = {}

    profiles = entry["model_profiles"].setdefault(results.model_name, [])
    fresh = results.timing_profile()
    slot = next((i for i, p in enumerate(profiles)
                 if results.matches_profile(p)), None)
    if slot is None:
        profiles.append(fresh)
    elif overwrite:
        print(f"Overwriting model profile: {path}: {dev_type}: "
              f"{results.model_name}: {profiles[slot]}")
        profiles[slot] = fresh
    else:
        raise ProfileError(
            f"model profile already exists: {path}: {dev_type}: "
            f"{results.model_name}: {profiles[slot]} (use overwrite)")
    yaml_files.yaml_save(device_types, path)
