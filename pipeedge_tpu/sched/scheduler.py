"""Subprocess wrapper for the native `sched-pipeline` scheduler binary.

Parity with /root/reference/src/pipeedge/sched/scheduler.py:24-73: builds the
CLI arguments, searches `app_paths` then the in-repo build dir then PATH, and
parses the YAML schedule from stdout into [{host: [layer_l, layer_r]}, ...].
"""
import logging
import os
import subprocess
from typing import Dict, List, Optional

import yaml

logger = logging.getLogger(__name__)

# in-repo build location (native/CMakeLists.txt)
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), 'native')
_REPO_BUILD_PATHS = [
    os.path.join(_NATIVE_DIR, 'build', 'sched-pipeline'),
]


_BUILD_FAILED = False


def build_native(force: bool = False,
                 artifact: Optional[str] = None) -> Optional[str]:
    """Build the in-repo native tree if `artifact` is absent; returns its
    path. `artifact` defaults to the `sched-pipeline` binary; other targets
    (e.g. libquantpack.so) pass their own path so a build tree that predates
    them still gets rebuilt.

    The reference ships its binary inside the wheel via py-build-cmake
    (pyproject.toml:36-52); for a source checkout we compile on first use so
    the build tree never needs to be committed. Returns None if no native
    toolchain is available; a failed build is cached so repeated calls don't
    re-run cmake.
    """
    global _BUILD_FAILED
    binary = artifact or _REPO_BUILD_PATHS[0]
    if os.path.exists(binary) and not force:
        return binary
    if _BUILD_FAILED and not force:
        return None
    build_dir = os.path.join(_NATIVE_DIR, 'build')
    os.makedirs(build_dir, exist_ok=True)
    try:
        # serialize concurrent builders (e.g. parallel test workers) on an
        # advisory file lock; the loser re-checks for the winner's binary
        import fcntl
        lock_f = open(os.path.join(build_dir, '.build-lock'), 'w')
    except (OSError, ImportError):
        lock_f = None
    try:
        if lock_f is not None:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            if os.path.exists(binary) and not force:
                return binary
        subprocess.run(['cmake', '-B', build_dir, '-G', 'Ninja', _NATIVE_DIR],
                       capture_output=True, check=True)
        subprocess.run(['ninja', '-C', build_dir], capture_output=True,
                       check=True)
    except FileNotFoundError as exc:
        logger.warning("native toolchain unavailable (%s); cannot build "
                       "sched-pipeline", exc)
        _BUILD_FAILED = True
        return None
    except subprocess.CalledProcessError as exc:
        _log_cpe(exc)
        _BUILD_FAILED = True
        return None
    finally:
        if lock_f is not None:
            lock_f.close()
    if os.path.exists(binary):
        _BUILD_FAILED = False
        return binary
    _BUILD_FAILED = True
    return None


def _log_cpe(exc: subprocess.CalledProcessError) -> None:
    logger.error("Scheduler subprocess failed, return code: %d", exc.returncode)
    stdout = exc.stdout.decode().strip()
    if stdout:
        logger.info("stdout:\n%s", stdout)
    stderr = exc.stderr.decode().strip()
    if stderr:
        logger.error("stderr:\n%s", stderr)


def sched_pipeline(model_name: str, buffers_in: int, buffers_out: int,
                   batch_size: int, dtype: str = 'torch.float32',
                   models_file: Optional[str] = None,
                   dev_types_file: Optional[str] = None,
                   dev_file: Optional[str] = None,
                   app_paths: Optional[List[str]] = None) \
        -> List[Dict[str, List[int]]]:
    """Run the native scheduler; returns the stage list in layer order."""
    if app_paths is None:
        app_paths = []
    args = ['-i', str(buffers_in), '-o', str(buffers_out),
            '-b', str(batch_size), '-d', dtype, '-m', model_name]
    if models_file:
        args += ['-M', models_file]
    if dev_types_file:
        args += ['-T', dev_types_file]
    if dev_file:
        args += ['-D', dev_file]

    candidates = list(app_paths) + _REPO_BUILD_PATHS + ['sched-pipeline']
    proc = None
    last_missing = None

    def _try(app_path):
        nonlocal proc, last_missing
        try:
            proc = subprocess.run([app_path] + args, capture_output=True,
                                  check=True)
            return True
        except FileNotFoundError:
            last_missing = app_path
            return False
        except subprocess.CalledProcessError as exc:
            _log_cpe(exc)
            raise

    for app_path in candidates:
        if _try(app_path):
            break
    else:
        # every candidate missing: compile the in-repo binary on demand
        # (only now, so explicit app_paths / PATH installs take precedence
        # and we never run cmake when a binary already exists)
        built = build_native()
        if built is None or not _try(built):
            if _BUILD_FAILED:
                logger.error("Could not locate sched-pipeline and the "
                             "auto-build failed (see log above) - fix the "
                             "native toolchain or install a prebuilt "
                             "sched-pipeline on PATH")
            else:
                logger.error("Could not locate sched-pipeline (last tried "
                             "%r) - build it with: cmake -B native/build "
                             "native && ninja -C native/build", last_missing)
            raise FileNotFoundError('sched-pipeline')

    stderr = proc.stderr.decode().strip()
    if stderr:
        logger.warning(stderr)
    sched = yaml.safe_load(proc.stdout.decode())
    if sched is None:
        sched = []
    assert isinstance(sched, list)
    return sched
