"""Subprocess wrapper for the native `sched-pipeline` scheduler binary.

Parity with /root/reference/src/pipeedge/sched/scheduler.py:24-73: builds the
CLI arguments, searches `app_paths` then the in-repo build dir then PATH, and
parses the YAML schedule from stdout into [{host: [layer_l, layer_r]}, ...].
"""
import logging
import os
import subprocess
from typing import Dict, List, Optional

import yaml

logger = logging.getLogger(__name__)

# in-repo build location (native/CMakeLists.txt)
_REPO_BUILD_PATHS = [
    os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), 'native', 'build', 'sched-pipeline'),
]


def _log_cpe(exc: subprocess.CalledProcessError) -> None:
    logger.error("Scheduler subprocess failed, return code: %d", exc.returncode)
    stdout = exc.stdout.decode().strip()
    if stdout:
        logger.info("stdout:\n%s", stdout)
    stderr = exc.stderr.decode().strip()
    if stderr:
        logger.error("stderr:\n%s", stderr)


def sched_pipeline(model_name: str, buffers_in: int, buffers_out: int,
                   batch_size: int, dtype: str = 'torch.float32',
                   models_file: Optional[str] = None,
                   dev_types_file: Optional[str] = None,
                   dev_file: Optional[str] = None,
                   app_paths: Optional[List[str]] = None) \
        -> List[Dict[str, List[int]]]:
    """Run the native scheduler; returns the stage list in layer order."""
    if app_paths is None:
        app_paths = []
    args = ['-i', str(buffers_in), '-o', str(buffers_out),
            '-b', str(batch_size), '-d', dtype, '-m', model_name]
    if models_file:
        args += ['-M', models_file]
    if dev_types_file:
        args += ['-T', dev_types_file]
    if dev_file:
        args += ['-D', dev_file]

    candidates = list(app_paths) + _REPO_BUILD_PATHS + ['sched-pipeline']
    proc = None
    last_missing = None
    for app_path in candidates:
        try:
            proc = subprocess.run([app_path] + args, capture_output=True,
                                  check=True)
            break
        except FileNotFoundError:
            last_missing = app_path
        except subprocess.CalledProcessError as exc:
            _log_cpe(exc)
            raise
    if proc is None:
        logger.error("Could not locate sched-pipeline (last tried %r) - "
                     "build it with: cmake -B native/build native && "
                     "ninja -C native/build", last_missing)
        raise FileNotFoundError('sched-pipeline')

    stderr = proc.stderr.decode().strip()
    if stderr:
        logger.warning(stderr)
    sched = yaml.safe_load(proc.stdout.decode())
    if sched is None:
        sched = []
    assert isinstance(sched, list)
    return sched
