"""Load/save helpers for the scheduler's YAML files.

Parity with /root/reference/src/pipeedge/sched/yaml_files.py:15-49. Missing
files load as empty maps.
"""
import os

import yaml


def _yaml_load_map(file) -> dict:
    if os.path.exists(file):
        with open(file, 'r', encoding='utf-8') as yfile:
            return yaml.safe_load(yfile) or {}
    return {}


def yaml_models_load(file) -> dict:
    """models.yml: model name -> yaml_model."""
    return _yaml_load_map(file)


def yaml_device_types_load(file) -> dict:
    """device_types.yml: device type name -> yaml_device_type."""
    return _yaml_load_map(file)


def yaml_devices_load(file) -> dict:
    """devices.yml: device type name -> list of hosts."""
    return _yaml_load_map(file)


def yaml_device_neighbors_load(file) -> dict:
    """device_neighbors.yml: neighbor host -> yaml_device_neighbors_type."""
    return _yaml_load_map(file)


def yaml_device_neighbors_world_load(file) -> dict:
    """device_neighbors_world.yml: host -> {neighbor host -> link props}."""
    return _yaml_load_map(file)


def yaml_save(yml, file) -> None:
    """Save with PyYAML's compact flow style for leaf lists (matches the
    reference's emitted formats)."""
    with open(file, 'w', encoding='utf-8') as yfile:
        yaml.safe_dump(yml, yfile, default_flow_style=None, encoding='utf-8')
