"""Failover re-scheduling: rebuild a pipeline schedule over the survivors
of a mid-run stage death.

The runtime's failover path (runtime.py, `--on-peer-death failover`) calls
`plan_failover` when a rank carrying a stage dies. The planner cascades
through three strategies, most-informed first:

1. **Native scheduler** (`sched/scheduler.py` `sched_pipeline`), when the
   caller passes profile files: re-solve the partition over the surviving
   ranks' device profiles. Produces the best schedule but may CHANGE the
   cut points, so the recovered run is numerically equivalent, not
   necessarily bit-identical, to the original partition.
2. **Reverse-auction bids** (`sched/revauct.py`), when the caller passes a
   `bid_fn` that can collect fresh bids from the survivors (the runtime's
   CMD_BID round over the DCN BIDS channel). Same caveat as (1).
3. **Spare substitution**: keep the stage_layers/stage_quant exactly as
   scheduled and move each dead rank's stage onto an idle survivor (a rank
   in the fleet but not in the schedule). Because the partition is
   unchanged and every stage runs the same jitted program, replayed
   microbatches are bit-identical to a no-fault run — the property the
   chaos acceptance test asserts.

Returns None when no strategy yields a schedule the survivors can run —
the caller then aborts, naming the dead rank (the pre-failover semantics).
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence, Set, Tuple

logger = logging.getLogger(__name__)

Schedule = Tuple[List[Tuple[int, int]], List[int], List[int]]


def plan_failover(stage_layers: Sequence[Tuple[int, int]],
                  stage_quant: Sequence[int],
                  stage_ranks: Sequence[int],
                  world_size: int,
                  dead_ranks: Set[int],
                  scheduler_fn: Optional[Callable[[int], Schedule]] = None,
                  bid_fn: Optional[Callable[[List[int]], Schedule]] = None,
                  benched: Optional[Set[int]] = None) \
        -> Optional[Schedule]:
    """Plan a schedule for the surviving ranks after `dead_ranks` died.

    `scheduler_fn(n_survivors)` re-runs the native scheduler for a fleet of
    that size and returns (stage_layers, stage_quant, stage_ranks) with
    ranks as indices 0..n-1 INTO the survivor list (remapped here);
    `bid_fn(survivors)` does the same from fresh reverse-auction bids.
    Either may raise or return None to fall through to spare substitution.

    `benched` ranks are ALIVE but must not keep a stage the schedule
    assigns them (a rejoined rank under `--on-peer-rejoin spare`: live
    idle capacity, but its old stage stays where the failover moved it).
    They remain eligible as last-resort spares — running on a benched
    rank beats running degraded.
    """
    dead_ranks = set(dead_ranks)
    benched = set(benched or ()) - dead_ranks
    survivors = [r for r in range(world_size) if r not in dead_ranks]
    lost = [i for i, r in enumerate(stage_ranks)
            if r in dead_ranks or r in benched]
    if not lost:
        # the dead rank carried no stage (an idle spare died): the running
        # schedule is untouched
        return list(stage_layers), list(stage_quant), list(stage_ranks)
    if not survivors:
        return None

    for name, attempt in (("scheduler", scheduler_fn), ("revauct", bid_fn)):
        if attempt is None:
            continue
        try:
            arg = len(survivors) if name == "scheduler" else survivors
            planned = attempt(arg)
        except Exception as exc:  # noqa: BLE001 — every strategy may fail;
            logger.warning("failover: %s re-schedule failed (%s); falling "
                           "through", name, exc)   # the cascade continues
            continue
        if planned is None:
            continue
        layers, quant, ranks = planned
        if len(layers) > len(survivors):
            logger.warning("failover: %s produced %d stages for %d "
                           "survivors; falling through", name, len(layers),
                           len(survivors))
            continue
        # scheduler ranks are indices into the survivor list; remap them
        # onto the fleet's real rank ids
        remapped = [survivors[r] for r in ranks]
        logger.info("failover: %s re-schedule: layers=%s ranks=%s",
                    name, layers, remapped)
        return list(layers), list(quant), remapped

    return substitute_spares(stage_layers, stage_quant, stage_ranks,
                             survivors, benched=benched)


def plan_rejoin(current: Schedule,
                pre_failure: Optional[Schedule],
                world_size: int,
                dead_ranks: Set[int],
                layer_costs: Optional[Sequence[float]] = None,
                align: int = 1) -> Optional[Schedule]:
    """Plan the capacity-restoring heal after a dead rank rejoined
    (`--on-peer-rejoin heal`): the inverse of `plan_failover`.

    Strategy cascade, most-faithful first:

    1. **Restore**: when every rank the `pre_failure` schedule names is
       alive again (the common one-transient-crash case), bring that
       schedule back verbatim — partition, quant, and placement exactly as
       before the death, so the healed run's numerics are bit-identical
       to a fault-free run.
    2. **Re-expand**: when the failover contracted the partition onto
       fewer stages (a scheduler re-solve over fewer survivors) and idle
       capacity is back, re-cut the span over more stages with the
       rebalance DP (`sched/rebalance.py expand_partition`), assigning
       the added stages to the idle survivors in rank order. Interior
       quant resets to 0 — the old per-stage settings do not map onto the
       new cut points.

    Returns None when neither applies (the rejoiner simply stays an idle
    spare for the NEXT failover) — including when the current schedule
    already has full capacity."""
    alive = {r for r in range(world_size) if r not in set(dead_ranks)}
    if pre_failure is not None:
        layers, quant, ranks = pre_failure
        if all(r in alive for r in ranks):
            return list(layers), list(quant), list(ranks)
    cur_layers, _cur_quant, cur_ranks = current
    spares = sorted(alive - set(cur_ranks))
    target = len(pre_failure[0]) if pre_failure else len(cur_layers) + 1
    target = min(target, len(cur_layers) + len(spares))
    if target <= len(cur_layers) or not spares:
        return None
    from . import rebalance
    try:
        expanded = rebalance.expand_partition(list(cur_layers), target,
                                              layer_costs=layer_costs,
                                              align=align)
    except ValueError as exc:
        logger.warning("rejoin: expansion to %d stages rejected (%s); "
                       "the rejoined rank stays a spare", target, exc)
        return None
    new_ranks = list(cur_ranks) + spares[:target - len(cur_layers)]
    logger.info("rejoin: re-expanding %s -> %s over ranks %s",
                list(cur_layers), expanded, new_ranks)
    return list(expanded), [0] * target, new_ranks


def substitute_spares(stage_layers: Sequence[Tuple[int, int]],
                      stage_quant: Sequence[int],
                      stage_ranks: Sequence[int],
                      survivors: Sequence[int],
                      benched: Optional[Set[int]] = None) \
        -> Optional[Schedule]:
    """Move each lost stage onto an idle survivor, keeping the partition
    (and therefore the numerics) exactly as scheduled. Returns None when
    there are fewer spares than lost stages — no capacity to fail over.

    `benched` ranks lose any stage the schedule assigns them but stay in
    the spare pool at LOWEST priority (fresh spares are preferred; a
    benched rank is picked only when nothing else is idle)."""
    alive = set(survivors)
    benched = set(benched or ()) & alive
    lost = [i for i, r in enumerate(stage_ranks)
            if r not in alive or r in benched]
    assigned = {r for i, r in enumerate(stage_ranks)
                if r in alive and i not in set(lost)}
    pool = alive - assigned
    spares = sorted(pool - benched) + sorted(pool & benched)
    if len(spares) < len(lost):
        logger.warning("failover: %d stage(s) lost but only %d spare "
                       "rank(s) idle; no capacity", len(lost), len(spares))
        return None
    new_ranks = list(stage_ranks)
    for i, spare in zip(lost, spares):
        logger.info("failover: stage %d (layers %s) moves rank %d -> %d",
                    i, tuple(stage_layers[i]), stage_ranks[i], spare)
        new_ranks[i] = spare
    return list(stage_layers), list(stage_quant), new_ranks
