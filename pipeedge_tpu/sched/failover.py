"""Failover re-scheduling: rebuild a pipeline schedule over the survivors
of a mid-run stage death.

The runtime's failover path (runtime.py, `--on-peer-death failover`) calls
`plan_failover` when a rank carrying a stage dies. The planner cascades
through three strategies, most-informed first:

1. **Native scheduler** (`sched/scheduler.py` `sched_pipeline`), when the
   caller passes profile files: re-solve the partition over the surviving
   ranks' device profiles. Produces the best schedule but may CHANGE the
   cut points, so the recovered run is numerically equivalent, not
   necessarily bit-identical, to the original partition.
2. **Reverse-auction bids** (`sched/revauct.py`), when the caller passes a
   `bid_fn` that can collect fresh bids from the survivors (the runtime's
   CMD_BID round over the DCN BIDS channel). Same caveat as (1).
3. **Spare substitution**: keep the stage_layers/stage_quant exactly as
   scheduled and move each dead rank's stage onto an idle survivor (a rank
   in the fleet but not in the schedule). Because the partition is
   unchanged and every stage runs the same jitted program, replayed
   microbatches are bit-identical to a no-fault run — the property the
   chaos acceptance test asserts.

Returns None when no strategy yields a schedule the survivors can run —
the caller then aborts, naming the dead rank (the pre-failover semantics).
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence, Set, Tuple

logger = logging.getLogger(__name__)

Schedule = Tuple[List[Tuple[int, int]], List[int], List[int]]


def plan_failover(stage_layers: Sequence[Tuple[int, int]],
                  stage_quant: Sequence[int],
                  stage_ranks: Sequence[int],
                  world_size: int,
                  dead_ranks: Set[int],
                  scheduler_fn: Optional[Callable[[int], Schedule]] = None,
                  bid_fn: Optional[Callable[[List[int]], Schedule]] = None) \
        -> Optional[Schedule]:
    """Plan a schedule for the surviving ranks after `dead_ranks` died.

    `scheduler_fn(n_survivors)` re-runs the native scheduler for a fleet of
    that size and returns (stage_layers, stage_quant, stage_ranks) with
    ranks as indices 0..n-1 INTO the survivor list (remapped here);
    `bid_fn(survivors)` does the same from fresh reverse-auction bids.
    Either may raise or return None to fall through to spare substitution.
    """
    dead_ranks = set(dead_ranks)
    survivors = [r for r in range(world_size) if r not in dead_ranks]
    lost = [i for i, r in enumerate(stage_ranks) if r in dead_ranks]
    if not lost:
        # the dead rank carried no stage (an idle spare died): the running
        # schedule is untouched
        return list(stage_layers), list(stage_quant), list(stage_ranks)
    if not survivors:
        return None

    for name, attempt in (("scheduler", scheduler_fn), ("revauct", bid_fn)):
        if attempt is None:
            continue
        try:
            arg = len(survivors) if name == "scheduler" else survivors
            planned = attempt(arg)
        except Exception as exc:  # noqa: BLE001 — every strategy may fail;
            logger.warning("failover: %s re-schedule failed (%s); falling "
                           "through", name, exc)   # the cascade continues
            continue
        if planned is None:
            continue
        layers, quant, ranks = planned
        if len(layers) > len(survivors):
            logger.warning("failover: %s produced %d stages for %d "
                           "survivors; falling through", name, len(layers),
                           len(survivors))
            continue
        # scheduler ranks are indices into the survivor list; remap them
        # onto the fleet's real rank ids
        remapped = [survivors[r] for r in ranks]
        logger.info("failover: %s re-schedule: layers=%s ranks=%s",
                    name, layers, remapped)
        return list(layers), list(quant), remapped

    return substitute_spares(stage_layers, stage_quant, stage_ranks,
                             survivors)


def substitute_spares(stage_layers: Sequence[Tuple[int, int]],
                      stage_quant: Sequence[int],
                      stage_ranks: Sequence[int],
                      survivors: Sequence[int]) -> Optional[Schedule]:
    """Move each lost stage onto an idle survivor, keeping the partition
    (and therefore the numerics) exactly as scheduled. Returns None when
    there are fewer spares than lost stages — no capacity to fail over."""
    alive = set(survivors)
    lost = [i for i, r in enumerate(stage_ranks) if r not in alive]
    assigned = {r for r in stage_ranks if r in alive}
    spares = sorted(alive - assigned)
    if len(spares) < len(lost):
        logger.warning("failover: %d stage(s) lost but only %d spare "
                       "rank(s) idle; no capacity", len(lost), len(spares))
        return None
    new_ranks = list(stage_ranks)
    for i, spare in zip(lost, spares):
        logger.info("failover: stage %d (layers %s) moves rank %d -> %d",
                    i, tuple(stage_layers[i]), stage_ranks[i], spare)
        new_ranks[i] = spare
    return list(stage_layers), list(stage_quant), new_ranks
