"""Reverse-auction scheduling: bids, filters, greedy and DAG-optimal schedulers.

Capability parity with /root/reference/src/pipeedge/sched/revauct.py. Each
device "bids" every memory-feasible contiguous layer range with its compute
latency as cost (bid_latency, revauct.py:18-29); the auctioneer assembles a
pipeline from the bids with one of three schedulers:

- greedy host count (revauct.py:53-116): fewest devices, data host first/last;
- optimal latency over a device order: shortest path over the shard-bid DAG
  (nodes = (device, shard) weighted by compute, edges weighted by comm time
  with link bw = min of both directions — revauct.py:121-223);
- optimal throughput: minimax path minimizing the max stage latency
  (revauct.py:225-251). The reference implements this as a stateful weight
  function inside networkx Dijkstra; here it is a direct minimax Dijkstra
  (max is monotone, so Dijkstra's greedy invariant holds) over a hand-rolled
  graph — no networkx dependency.
"""
from __future__ import annotations

import heapq
import logging
import time
from typing import Dict, List, Mapping, Optional, Tuple

from . import communication_time_bw, computation_time, mem_bytes, ubatch_bytes

logger = logging.getLogger(__name__)

ShardBid = Tuple[Tuple[int, int], float]
"""A shard bid: ((start_layer, end_layer), cost) — layers 0-based here."""

DeviceBidData = Tuple[Mapping[Tuple[int, int], float], Mapping[str, dict]]
"""A device's bids: (shard -> cost, neighbor host -> link properties)."""

NodeID = Tuple[str, Tuple[int, int]]
"""DAG node: (device, (m, n)); dummies use (-1, -1) and (L, L)."""


def bid_latency(yml_model: dict, yml_dev_type: dict, yml_dtm_profile: dict,
                ubatch_size: int, dtype: str = 'torch.float32') -> List[ShardBid]:
    """All memory-feasible O(L^2) shards with compute-latency costs."""
    bids = []
    dev_mem = yml_dev_type['mem_MB'] * 1024 * 1024
    n_layers = yml_model['layers']
    for layer_l in range(n_layers):
        for layer_r in range(layer_l, n_layers):
            if dev_mem > mem_bytes(yml_model, layer_l, layer_r, dtype, ubatch_size):
                cost = computation_time(yml_dtm_profile, layer_l, layer_r)
                bids.append(((layer_l, layer_r), cost))
    return bids


def filter_bids_chunk(yml_model: dict, bids: Mapping[Tuple[int, int], float],
                      chunk: int = 4) -> Dict[Tuple[int, int], float]:
    """Keep only shards aligned to `chunk`-sublayer boundaries (the tail shard
    may be short if chunk doesn't divide the layer count)."""
    model_layers = yml_model['layers']
    return {shard: cost for shard, cost in bids.items()
            if shard[0] % chunk == 0 and
            (shard[1] + 1 >= model_layers or (shard[1] + 1) % chunk == 0)}


def filter_bids_largest(bids: Mapping[Tuple[int, int], float]) \
        -> Dict[Tuple[int, int], float]:
    """Keep only the largest shard for each start layer."""
    best: Dict[int, ShardBid] = {}
    for shard, cost in bids.items():
        if shard[0] not in best or shard[1] > best[shard[0]][0][1]:
            best[shard[0]] = (shard, cost)
    return {shard: cost for shard, cost in best.values()}


def sched_greedy_host_count(yml_model: dict, _ubatch_size: int, _dtype: str,
                            bids: Mapping[str, DeviceBidData], host_src: str,
                            host_dest: str) -> List[Mapping[str, List[int]]]:
    """Schedule for minimum device count: full connectivity assumed,
    bandwidths ignored (reference revauct.py:53-116).

    Source host gets the largest shard starting at layer 0, dest host the
    largest shard ending at the last layer, remaining layers greedily filled
    with the largest supported shards (ties broken by lower cost). May fail
    (return []) even when a feasible pipeline exists.
    """
    # host -> {start_layer: (max_end_layer, cost)}
    max_lut: Dict[str, Dict[int, Tuple[int, float]]] = {h: {} for h in bids}
    for host, (shard_bids, _) in bids.items():
        for shard, cost in shard_bids.items():
            if max_lut[host].get(shard[0], (-1, -1))[0] < shard[1]:
                max_lut[host][shard[0]] = (shard[1], cost)

    sched: List[Mapping[str, List[int]]] = []
    insert_offset = 0
    lay_start = 0
    lay_end = yml_model['layers'] - 1
    used = set()
    if host_src in max_lut and lay_start in max_lut[host_src]:
        lay_max = max_lut[host_src][lay_start][0]
        sched.append({host_src: [lay_start, lay_max]})
        used.add(host_src)
        lay_start = lay_max + 1
    # dest gets the tail (src may not, unless it already took the whole model)
    if host_dest in max_lut and host_src != host_dest:
        lay_min = lay_end + 1
        for lay_s, (lay_e, _) in max_lut[host_dest].items():
            if lay_e == lay_end:
                lay_min = min(lay_s, lay_min)
        if lay_min <= lay_end:
            sched.append({host_dest: [lay_min, lay_end]})
            used.add(host_dest)
            lay_end = lay_min - 1
            insert_offset = 1
    while lay_start <= lay_end:
        best: Tuple[Optional[str], int, float] = (None, -1, -1.0)
        for dev, lut in max_lut.items():
            if dev not in used and lay_start in lut:
                cand_end, cand_cost = lut[lay_start]
                if cand_end > best[1] or (cand_end == best[1] and cand_cost < best[2]):
                    best = (dev, cand_end, cand_cost)
        if best[0] is None:
            return []
        sched.insert(len(sched) - insert_offset, {best[0]: [lay_start, best[1]]})
        used.add(best[0])
        lay_start = best[1] + 1
    if host_dest not in sched[-1]:
        sched.append({host_dest: []})
    return sched


class _ShardDag:
    """Shard-bid DAG with node weights (compute) and edge weights (comm)."""

    def __init__(self):
        self.node_weight: Dict[NodeID, float] = {}
        self.adj: Dict[NodeID, List[Tuple[NodeID, float]]] = {}

    def add_node(self, node: NodeID, weight: float) -> None:
        self.node_weight[node] = weight
        self.adj.setdefault(node, [])

    def add_edge(self, src: NodeID, dst: NodeID, weight: float) -> None:
        self.adj[src].append((dst, weight))


def _link_bw_mbps(bids: Mapping[str, DeviceBidData], dev_a: str, dev_b: str) -> float:
    """Effective link bandwidth: min of what each side reports for the other."""
    return min(bids[dev_a][1].get(dev_b, {}).get('bw_Mbps', 0),
               bids[dev_b][1].get(dev_a, {}).get('bw_Mbps', 0))


def _build_dag(bids: Mapping[str, DeviceBidData], yml_model: dict,
               ubatch_size: int, dtype: str, devices: List[str],
               strict_order: bool) -> _ShardDag:
    """Nodes for every (device, bid shard); edges where shards abut and the
    devices are adjacent in (strict) or consistent with (relaxed) the order."""
    dag = _ShardDag()
    n_layers = yml_model['layers']
    start_lut: Dict[str, Dict[int, List[NodeID]]] = \
        {d: {i: [] for i in range(n_layers)} for d in devices}
    for dev in devices:
        for shard, cost in bids[dev][0].items():
            node = (dev, shard)
            dag.add_node(node, cost)
            start_lut[dev][shard[0]].append(node)
    edge_bytes = [ubatch_bytes(yml_model['parameters_out'][l], ubatch_size,
                               dtype=dtype) for l in range(n_layers)]
    for idx, dev_a in enumerate(devices[:-1]):
        successors = devices[idx + 1:idx + 2] if strict_order else devices[idx + 1:]
        for dev_b in successors:
            bw = _link_bw_mbps(bids, dev_a, dev_b)
            if bw <= 0:
                continue
            for starts in start_lut[dev_a].values():
                for node_a in starts:
                    lay_end = node_a[1][1]
                    comm = communication_time_bw(bw, edge_bytes[lay_end])
                    for node_b in start_lut[dev_b].get(lay_end + 1, []):
                        dag.add_edge(node_a, node_b, comm)
    return dag


def _add_dummies(dag: _ShardDag, yml_model: dict, ubatch_size: int, dtype: str,
                 bids: Mapping[str, DeviceBidData], host_src: str,
                 host_dest: str, devices: List[str], strict_first: bool,
                 strict_last: bool) -> Tuple[NodeID, NodeID]:
    """Dummy source/dest nodes wired to first-layer / last-layer shards."""
    n_layers = yml_model['layers']
    node_src: NodeID = (host_src, (-1, -1))
    node_dest: NodeID = (host_dest, (n_layers, n_layers))
    dag.add_node(node_src, 0)
    dag.add_node(node_dest, 0)
    in_bytes = ubatch_bytes(yml_model['parameters_in'], ubatch_size, dtype=dtype)
    out_bytes = ubatch_bytes(yml_model['parameters_out'][-1], ubatch_size,
                             dtype=dtype)
    for node in list(dag.node_weight):
        dev, (lay_start, lay_end) = node
        if node in (node_src, node_dest):
            continue
        if lay_start == 0 and (dev == devices[0] or not strict_first):
            if dev == host_src:
                dag.add_edge(node_src, node, 0)
            else:
                bw = _link_bw_mbps(bids, host_src, dev)
                if bw > 0:
                    dag.add_edge(node_src, node,
                                 communication_time_bw(bw, in_bytes))
        if lay_end == n_layers - 1 and (dev == devices[-1] or not strict_last):
            if dev == host_dest:
                dag.add_edge(node, node_dest, 0)
            else:
                bw = _link_bw_mbps(bids, dev, host_dest)
                if bw > 0:
                    dag.add_edge(node, node_dest,
                                 communication_time_bw(bw, out_bytes))
    return node_src, node_dest


def _dijkstra(dag: _ShardDag, source: NodeID, target: NodeID,
              objective: str) -> Tuple[List[NodeID], float]:
    """Shortest path under 'latency' (additive node+edge weights) or
    'throughput' (minimax over max(edge, node) stage latencies). Both
    relaxations are monotone, so plain Dijkstra applies."""
    inf = float('inf')
    dist = {source: dag.node_weight[source]}
    prev: Dict[NodeID, NodeID] = {}
    heap = [(dist[source], source)]
    visited = set()
    while heap:
        d, u = heapq.heappop(heap)
        if u in visited:
            continue
        visited.add(u)
        if u == target:
            break
        for v, edge_w in dag.adj.get(u, []):
            if objective == 'latency':
                cand = d + edge_w + dag.node_weight[v]
            else:  # throughput: minimize the bottleneck stage latency
                cand = max(d, edge_w, dag.node_weight[v])
            if cand < dist.get(v, inf):
                dist[v] = cand
                prev[v] = u
                heapq.heappush(heap, (cand, v))
    if target not in visited:
        return [], inf
    path = [target]
    while path[-1] != source:
        path.append(prev[path[-1]])
    path.reverse()
    return path, dist[target]


def _path_to_sched(path: List[NodeID], host_src: str, host_dest: str) \
        -> List[Mapping[str, List[int]]]:
    """Collapse/replace dummy endpoints (reference revauct.py:254-273)."""
    if len(path) > 0:
        assert len(path) > 2
        if path[0][0] == path[1][0]:
            path.pop(0)  # source device took the first shard
        else:
            path[0] = (host_src, ())
        if path[-1][0] == path[-2][0]:
            path.pop()   # dest device took the last shard
        else:
            path[-1] = (host_dest, ())
    return [{node[0]: list(node[1])} for node in path]


def _sched_optimal(objective: str, yml_model: dict, ubatch_size: int,
                   dtype: str, bids: Mapping[str, DeviceBidData],
                   host_src: str, host_dest: str, devices: List[str],
                   strict_order: bool, strict_first: bool,
                   strict_last: bool) -> Tuple[List[Mapping[str, List[int]]], float]:
    if host_src in devices:
        assert devices[0] == host_src
    if host_dest != host_src and host_dest in devices:
        assert devices[-1] == host_dest
    t_start = time.time()
    dag = _build_dag(bids, yml_model, ubatch_size, dtype, devices, strict_order)
    node_src, node_dest = _add_dummies(dag, yml_model, ubatch_size, dtype, bids,
                                       host_src, host_dest, devices,
                                       strict_first, strict_last)
    logger.info("DAG construction time (sec): %f", time.time() - t_start)
    t_start = time.time()
    path, cost = _dijkstra(dag, node_src, node_dest, objective)
    logger.info("DAG search time (sec): %f", time.time() - t_start)
    if not path:
        logger.debug("No possible paths.")
    return _path_to_sched(path, host_src, host_dest), cost


def sched_optimal_latency_dev_order(yml_model: dict, ubatch_size: int,
                                    dtype: str, bids: Mapping[str, DeviceBidData],
                                    host_src: str, host_dest: str,
                                    devices: List[str], strict_order: bool = True,
                                    strict_first: bool = True,
                                    strict_last: bool = True) \
        -> Tuple[List[Mapping[str, List[int]]], float]:
    """Optimal end-to-end latency subject to the device order; returns
    (schedule, predicted latency seconds)."""
    return _sched_optimal('latency', yml_model, ubatch_size, dtype, bids,
                          host_src, host_dest, devices, strict_order,
                          strict_first, strict_last)


def sched_optimal_throughput_dev_order(yml_model: dict, ubatch_size: int,
                                       dtype: str,
                                       bids: Mapping[str, DeviceBidData],
                                       host_src: str, host_dest: str,
                                       devices: List[str],
                                       strict_order: bool = True,
                                       strict_first: bool = True,
                                       strict_last: bool = True) \
        -> Tuple[List[Mapping[str, List[int]]], float]:
    """Optimal pipeline throughput (compute/comm overlapped) subject to the
    device order; returns (schedule, predicted items/sec)."""
    sched, cost = _sched_optimal('throughput', yml_model, ubatch_size, dtype,
                                 bids, host_src, host_dest, devices,
                                 strict_order, strict_first, strict_last)
    return sched, (1 / cost if cost > 0 else float('inf'))
