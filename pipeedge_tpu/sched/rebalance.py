"""Closed-loop pipeline rebalancing: re-solve the layer partition from
MEASURED per-stage timings.

The offline DP scheduler (`sched/scheduler.py`, the native `sched-pipeline`
binary) maps layer ranges from profiles recorded before the run. A
mispredicted or drifting stage — thermal throttle, contended host, wrong
profile — then bubbles the whole pipeline for the rest of the run, which is
exactly the heterogeneity problem the paper targets (PAPERS.md 2412.14374
feeds live MPMD timings back into placement; 2110.14895 attributes the loss
to inter-stage skew). This module closes the loop at runtime:

- `solve_partition` is the same objective as the native solver's DP —
  minimize the bottleneck stage time over contiguous layer ranges — run
  in-process over live costs: a per-layer cost vector (measured stage
  times spread over their ranges) plus a per-STAGE fixed cost (the
  emit/wire time a stage pays per microbatch no matter how few layers it
  carries — a slow link must not be "solved" by moving layers that cannot
  remove it).
- `RebalancePolicy` wraps the solver with the guards that keep a balanced
  fleet from churning: a proposal must differ from the running partition,
  predict at least `threshold` relative bottleneck gain (hysteresis /
  minimum-gain), and respect a cooldown of full rounds after the previous
  rebalance (no oscillation on noisy windows).

The runtime applies an accepted proposal at the next round boundary through
the existing CMD_SCHED broadcast — the machinery failover already
exercises (sched/failover.py), now driven by performance instead of death.
Offline, the same measurements reach the NATIVE solver via
`tools/trace_report.py --emit-profiles` (sched/profiles.py ingestion).
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

Partition = List[Tuple[int, int]]


def spread_layer_costs(partition: Sequence[Tuple[int, int]],
                       stage_layer_s: Sequence[float]) -> List[float]:
    """Per-layer cost vector from per-stage measured times: stage i's
    layer-proportional seconds (`StageEstimate.layer_s`) spread uniformly
    over its `[l, r]` range — the per-layer resolution a per-stage
    measurement supports. Layers are 1-based inclusive, ranges contiguous
    from 1 (the repo's partition convention)."""
    if len(partition) != len(stage_layer_s):
        raise ValueError(f"{len(partition)} stages != "
                         f"{len(stage_layer_s)} stage costs")
    costs: List[float] = []
    expect = 1
    for (l, r), total_s in zip(partition, stage_layer_s):
        if l != expect or r < l:
            raise ValueError(f"partition {list(partition)} is not "
                             "contiguous from layer 1")
        costs.extend([float(total_s) / (r - l + 1)] * (r - l + 1))
        expect = r + 1
    return costs


def solve_partition(layer_costs: Sequence[float], n_stages: int,
                    fixed_costs: Optional[Sequence[float]] = None,
                    align: int = 1) -> Tuple[Partition, float]:
    """Minimize the bottleneck stage time: partition layers 1..L into
    `n_stages` contiguous non-empty ranges minimizing
    `max_i(fixed_costs[i] + sum(layer_costs in range_i))` — the native DP
    solver's objective, over live costs. `align` constrains every cut to a
    multiple of `align` layers (the `--stage-tp` block-alignment rule).
    Returns `(partition, bottleneck)` — the optimum AND its objective
    value, so callers never re-derive the cost model the DP optimized.
    Deterministic: ties resolve to the earliest cut."""
    n_layers = len(layer_costs)
    if n_stages < 1 or n_layers < n_stages:
        raise ValueError(f"cannot split {n_layers} layers into "
                         f"{n_stages} non-empty stages")
    if fixed_costs is None:
        fixed_costs = [0.0] * n_stages
    if len(fixed_costs) != n_stages:
        raise ValueError(f"{len(fixed_costs)} fixed costs != "
                         f"{n_stages} stages")
    if align > 1:
        if n_layers % align:
            raise ValueError(f"{n_layers} layers not a multiple of "
                             f"align={align}")
        groups = [sum(layer_costs[g * align:(g + 1) * align])
                  for g in range(n_layers // align)]
        grouped, bottleneck = solve_partition(groups, n_stages,
                                              fixed_costs, align=1)
        return ([((l - 1) * align + 1, r * align) for l, r in grouped],
                bottleneck)

    prefix = [0.0]
    for c in layer_costs:
        prefix.append(prefix[-1] + float(c))

    inf = float("inf")
    # best[i][j]: minimal bottleneck splitting the first j layers over the
    # first i stages (each non-empty); cut[i][j]: the j' that achieves it
    best = [[inf] * (n_layers + 1) for _ in range(n_stages + 1)]
    cut = [[0] * (n_layers + 1) for _ in range(n_stages + 1)]
    best[0][0] = 0.0
    for i in range(1, n_stages + 1):
        fixed = float(fixed_costs[i - 1])
        # stages after this one each need >= 1 layer
        for j in range(i, n_layers - (n_stages - i) + 1):
            for k in range(i - 1, j):
                if best[i - 1][k] == inf:
                    continue
                cand = max(best[i - 1][k], fixed + prefix[j] - prefix[k])
                if cand < best[i][j]:
                    best[i][j] = cand
                    cut[i][j] = k
    partition: Partition = []
    j = n_layers
    for i in range(n_stages, 0, -1):
        k = cut[i][j]
        partition.append((k + 1, j))
        j = k
    partition.reverse()
    return partition, best[n_stages][n_layers]


def expand_partition(partition: Sequence[Tuple[int, int]],
                     n_stages: int,
                     layer_costs: Optional[Sequence[float]] = None,
                     align: int = 1) -> Partition:
    """Re-cut `partition`'s layer span over MORE stages — the capacity-
    restoring side of the closed loop (docs/FAULT_TOLERANCE.md healing):
    a rank that died forced a contraction (scheduler re-solve over fewer
    survivors); when it rejoins, the span is re-expanded onto the restored
    capacity with the same bottleneck-minimizing DP the rebalancer uses.

    `layer_costs` (one cost per layer, e.g. measured via
    telemetry/feedback.py) weights the cuts; None = uniform layers.
    Raises ValueError when `n_stages` is not an actual expansion or the
    span cannot be split that many ways."""
    if not partition:
        raise ValueError("cannot expand an empty partition")
    n_layers = partition[-1][1]
    if n_stages <= len(partition):
        raise ValueError(f"expansion needs more stages than the current "
                         f"{len(partition)}, got {n_stages}")
    if layer_costs is None:
        layer_costs = [1.0] * n_layers
    elif len(layer_costs) != n_layers:
        raise ValueError(f"{len(layer_costs)} layer costs != "
                         f"{n_layers} layers")
    expanded, _ = solve_partition(layer_costs, n_stages, align=align)
    return expanded


@dataclasses.dataclass(frozen=True)
class Proposal:
    """An accepted rebalance: the new partition plus the prediction that
    justified it (recorded in logs/bench JSON for post-hoc audit)."""
    partition: Partition
    bottleneck_before_s: float
    bottleneck_after_s: float

    @property
    def gain(self) -> float:
        """Predicted relative bottleneck reduction (0..1)."""
        if self.bottleneck_before_s <= 0:
            return 0.0
        return (self.bottleneck_before_s - self.bottleneck_after_s) \
            / self.bottleneck_before_s


class RebalancePolicy:
    """The decision loop's guardrails around `solve_partition`.

    `consider(partition, estimates, rnd)` returns a `Proposal` only when
    ALL of: the re-solved partition differs from the running one, the
    predicted relative bottleneck gain is at least `threshold`
    (hysteresis: a balanced fleet's near-zero gains never churn), the
    SAME stage has been the measured bottleneck for `confirm`+1
    consecutive windows (a real straggler persists; round-to-round drift
    — compile caches warming, host contention — flips direction and is
    filtered out), and at least `cooldown` full rounds have completed
    since the last accepted proposal (no oscillation while a previous
    re-plan's effect is still being measured). `events` counts accepted
    proposals.
    """

    def __init__(self, threshold: float = 0.10, cooldown: int = 1,
                 align: int = 1, confirm: int = 1):
        if threshold < 0:
            raise ValueError(f"threshold must be >= 0, got {threshold}")
        if cooldown < 0:
            raise ValueError(f"cooldown must be >= 0, got {cooldown}")
        if confirm < 0:
            raise ValueError(f"confirm must be >= 0, got {confirm}")
        self.threshold = float(threshold)
        self.cooldown = int(cooldown)
        self.align = int(align)
        self.confirm = int(confirm)
        self.events = 0
        self._last_round: Optional[int] = None
        # consecutive actionable windows blaming the same bottleneck stage
        self._streak_stage: Optional[int] = None
        self._streak = 0

    def consider(self, partition: Sequence[Tuple[int, int]],
                 estimates: Dict[int, "object"],
                 rnd: int) -> Optional[Proposal]:
        """One decision over a measured round window. `estimates` maps
        stage index -> telemetry.feedback.StageEstimate for the partition
        as it ran (caller validates completeness via
        feedback.check_estimates first)."""
        n_stages = len(partition)
        ordered = [estimates[i] for i in range(n_stages)]
        layer_costs = spread_layer_costs(partition,
                                         [e.layer_s for e in ordered])
        fixed = [e.fixed_s for e in ordered]
        before = max(e.service_s for e in ordered)
        try:
            proposed, after = solve_partition(layer_costs, n_stages, fixed,
                                              align=self.align)
        except ValueError as exc:
            logger.warning("rebalance: solver rejected the measured "
                           "profile (%s); keeping partition", exc)
            return None
        proposal = Proposal(partition=proposed,
                            bottleneck_before_s=before,
                            bottleneck_after_s=after)
        if proposed == [tuple(p) for p in partition]:
            self._streak_stage = None
            self._streak = 0
            return None
        if proposal.gain < self.threshold:
            logger.info("rebalance: predicted gain %.1f%% below the "
                        "%.1f%% threshold; keeping partition",
                        100 * proposal.gain, 100 * self.threshold)
            self._streak_stage = None
            self._streak = 0
            return None
        bottleneck = max(range(n_stages), key=lambda i: ordered[i].service_s)
        if bottleneck == self._streak_stage:
            self._streak += 1
        else:
            self._streak_stage = bottleneck
            self._streak = 1
        if self._streak < self.confirm + 1:
            logger.info("rebalance: stage %d measured as bottleneck "
                        "(window %d of %d needed); awaiting confirmation",
                        bottleneck, self._streak, self.confirm + 1)
            return None
        if self._last_round is not None \
                and rnd - self._last_round <= self.cooldown:
            logger.info("rebalance: in cooldown (last rebalance at round "
                        "%d, cooldown %d); keeping partition",
                        self._last_round, self.cooldown)
            return None
        self._last_round = rnd
        self._streak_stage = None
        self._streak = 0
        self.events += 1
        return proposal
