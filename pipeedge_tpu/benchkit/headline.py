"""Headline recipes: the exact streamed pipeline bench + the standalone
quantized-collectives A/B (moved from the monolithic bench.py; the CLI
there is now a thin dispatcher over the benchkit registry).

`exact` prints the same record keys bench.py always printed (metric,
value, vs_baseline, mfu, fast_numerics, quant_collectives, ...) — they
ride the trajectory envelope as the merged `legacy` block, so BENCH
records stay backward-greppable while gaining the schema-versioned
envelope (scenario, config fingerprint, env stamp, noise-banded
throughput block) bench_report diffs on.

Method notes (unchanged from bench.py — docs/PERF.md):
- microbatches stream through ONE jitted `lax.scan` program; a scalar
  readback fences execution (block_until_ready does not fence on the
  tunneled axon platform).
- the headline `value` is the MEDIAN img/s of REPS repetitions with
  min/max spread and raw samples in the record, so session drift is
  visible inside one line.
- MFU reports against BOTH denominators: the session-calibrated peak
  (pinned CALIBRATION_RECIPE, versioned) and the nominal device spec.
"""
import statistics
import time

BASELINE_IMG_PER_SEC = 0.22  # ViT-L b=8 on RCC-VE-C2000 (BASELINE.md)

REPS = 5  # timed repetitions of the streaming loop (median reported)

# Nominal dense bf16 peak FLOP/s by device kind (public TPU spec sheets).
# Used as the second MFU denominator; absent kinds report null.
NOMINAL_BF16_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


# The PINNED peak-TFLOP calibration recipe (round-5 verdict item 7).
# Version it; never change a field without bumping `version` — the MFU
# denominators of different BENCH records are only comparable within one
# recipe version. Per-session spread is recorded alongside every result
# so the ±% error bars on calibrated MFU are explicit in the record.
CALIBRATION_RECIPE = {
    "version": "cal-v1",
    "matmul_mnk": [8192, 8192, 8192],
    "chain_length": 32,
    "dtype": "bfloat16",
    "accumulate": "float32",
    "protocol": "one jitted lax.scan chain; 1 compile+warm call, then "
                "3 timed reps fenced by scalar readback; peak = best "
                "rep, spread = all reps",
}


def calibrate_peak_samples(m: int = None) -> list:
    """Per-rep implied bf16 FLOP/s (2*M*N*K) under CALIBRATION_RECIPE;
    the chain amortizes dispatch/tunnel latency out of the measurement.
    max(samples) is the session peak; the spread IS the error bar on
    every calibrated-MFU number this session. A non-default `m`
    (--cal-dim, CPU-loopback A/B runs) is off-recipe: its MFU numbers
    are marked and never comparable across records."""
    import jax
    import jax.numpy as jnp
    if m is None:
        m = CALIBRATION_RECIPE["matmul_mnk"][0]
    k_iters = CALIBRATION_RECIPE["chain_length"]
    a = jnp.ones((m, m), jnp.bfloat16)
    b = jnp.ones((m, m), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        def step(c, _):
            y = jnp.dot(c, b, preferred_element_type=jnp.float32)
            return y.astype(jnp.bfloat16) * 1e-4, None

        out, _ = jax.lax.scan(step, a, None, length=k_iters)
        return jnp.sum(out.astype(jnp.float32))

    float(mm(a, b))  # compile + warm
    samples = []
    for _ in range(3):
        tik = time.monotonic()
        float(mm(a, b))
        samples.append(2 * k_iters * m**3 / (time.monotonic() - tik))
    return samples


def calibrate_peak_flops() -> float:
    """Session peak FLOP/s under the pinned recipe (best rep)."""
    return max(calibrate_peak_samples())


def model_flops_per_image(cfg) -> float:
    """Analytic ViT forward FLOPs per image (2*MAC convention)."""
    s = cfg.num_patches + 1
    d, i, l = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    per_block = 8 * s * d * d + 4 * s * s * d + 4 * s * d * i
    embed = 2 * s * (cfg.patch_size**2 * cfg.num_channels) * d
    head = 2 * d * max(cfg.num_labels, 1)
    return l * per_block + embed + head


def top1_agreement(logits_exact, logits_var) -> dict:
    """The accuracy-delta fields EVERY non-exact bench variant reports
    beside its throughput (fast_numerics, quant_collectives, ...): a
    non-exact number without its agreement is not self-describing."""
    import numpy as np
    return {
        "top1_agreement_vs_exact": round(float(np.mean(
            np.argmax(logits_exact, -1) == np.argmax(logits_var, -1))), 4),
        "max_abs_logit_delta": round(
            float(np.max(np.abs(logits_exact - logits_var))), 4),
    }


def quant_collectives_ab(name, bits: int, xs, flops_img: float,
                         peak_flops: float, nominal_peak) -> dict:
    """A/B for the quantized-ICI-collectives claim: the SAME streamed TP
    run with exact full-width psums vs int`bits` quantized collectives
    (ops/qcollectives.py qpsum at every Megatron psum site in
    parallel/tensor.py), interleaved rounds so session drift hits both
    sides equally. Reports img/s for both, the speedup quotient, the
    top-1 agreement + max-abs logit delta vs the exact side, and the
    traced wire footprint (docs/QUANT_COLLECTIVES.md).

    Needs >= 2 devices on the TP axis — a single-device backend has no
    ICI collective site to quantize, and the block says so instead of
    reporting a vacuous measurement."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..models import registry
    from ..ops import qcollectives
    from ..parallel import tensor as tp
    from ..utils import jax_compat

    entry = registry.get_model_entry(name)
    cfg = entry.config
    devs = jax.devices()
    n_tp, d = 1, 2
    while (d <= len(devs) and cfg.num_attention_heads % d == 0
           and cfg.intermediate_size % d == 0 and cfg.kv_heads % d == 0):
        n_tp, d = d, d * 2
    if n_tp < 2:
        return {"mode": "skipped", "bits": bits,
                "reason": f"{len(devs)} device(s) available: no ICI "
                          "collective sites (the TP axis needs >= 2 "
                          "devices dividing the head/FFN dims)"}
    _, params, _ = registry.module_shard_factory(
        name, None, 1, registry.get_model_layers(name),
        dtype=jnp.bfloat16, unroll=True)
    mesh = Mesh(np.asarray(devs[:n_tp]), ("tp",))
    blocks = tuple(tp.shard_block_params(cfg, bp, mesh)
                   for bp in params["blocks"])
    family = entry.family
    embed_p = jax.device_put(params.get("embeddings"))
    final_p = jax.device_put(params.get("final"))
    specs, local = tp.family_tp_plan(cfg)

    def build_and_warm(mode_bits: int):
        # the collective bitwidth is a trace-time flag: pin it across the
        # fresh shard_map body + jit wrapper AND their first (tracing)
        # call, then restore exact for everything else in this process
        tp.set_tp_quant_bits(mode_bits)
        try:
            body = jax_compat.shard_map(
                partial(local, cfg=cfg, axis="tp"), mesh=mesh,
                in_specs=(specs, P()), out_specs=P())

            @jax.jit
            def run_all(ep, fp, bps, xs):
                def step(carry, x):
                    h = family.embed(ep, x, cfg)
                    for bp in bps:
                        h = body(bp, h)
                    logits = family.finalize(fp, h, cfg)
                    return carry + jnp.sum(logits.astype(jnp.float32)), None

                total, _ = jax.lax.scan(step, jnp.float32(0), xs)
                return total

            @jax.jit
            def run_one(ep, fp, bps, x):
                h = family.embed(ep, x, cfg)
                for bp in bps:
                    h = body(bp, h)
                return family.finalize(fp, h, cfg)

            logits = np.asarray(run_one(embed_p, final_p, blocks,
                                        xs[0]).astype(jnp.float32))
            # run_one traced the SAME psum sites run_all is about to: drop
            # its tally entries so the wire accounting below counts each
            # site once, with run_all's execution multiplier
            qcollectives.reset_trace_tally()
            float(run_all(embed_p, final_p, blocks, xs))   # compile + warm
        finally:
            tp.set_tp_quant_bits(0)
        return run_all, logits

    n_ubatch, batch = xs.shape[0], xs.shape[1]
    run_exact, logits_exact = build_and_warm(0)
    run_q, logits_q = build_and_warm(bits)
    q_times, exact_times = [], []
    for _ in range(3):
        tik = time.monotonic()
        float(run_exact(embed_p, final_p, blocks, xs))
        exact_times.append(time.monotonic() - tik)
        tik = time.monotonic()
        float(run_q(embed_p, final_p, blocks, xs))
        q_times.append(time.monotonic() - tik)
    q_img = statistics.median(n_ubatch * batch / t for t in q_times)
    exact_img = statistics.median(n_ubatch * batch / t for t in exact_times)
    # per-run executions of each traced qpsum site: the block loop is
    # unrolled, so every site runs once per scan step (per microbatch)
    # over 1 warm + 3 timed run_all calls; run_one's single execution per
    # site was dropped from the tally above (one logits probe, < 1% of
    # the streamed traffic)
    collectives = qcollectives.record_collectives(
        executions=4 * n_ubatch)
    q_achieved = q_img * flops_img
    return {
        "mode": "tp-shard-map",
        "bits": bits,
        "tp": n_tp,
        "images_per_sec": round(q_img, 3),
        "exact_interleaved_images_per_sec": round(exact_img, 3),
        "speedup_vs_exact": round(q_img / exact_img, 3),
        "mfu_calibrated": round(q_achieved / peak_flops, 3),
        "mfu_nominal": (round(q_achieved / nominal_peak, 3)
                        if nominal_peak else None),
        "achieved_tflops": round(q_achieved / 1e12, 1),
        **top1_agreement(logits_exact, logits_q),
        "collectives": collectives,
    }


def _image_inputs(name, parser_error, n_ubatch: int, batch: int = 8):
    """(cfg, metric name, device-resident [U, B, C, H, W] input set) for
    an image-family model — the shared setup of both headline recipes."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import registry
    entry = registry.get_model_entry(name)
    family_name = entry.family.FAMILY.name
    if family_name not in ("vit", "deit"):
        # the streamed loop builds pixel inputs from patch geometry and
        # the TP A/B assumes the dense column/row kernel plan — token
        # families would crash mid-bench after the compile time is spent
        parser_error(f"--model must be an image family (vit/deit) for "
                     f"this bench; {name} is family '{family_name}'")
    metric = ("vit_large_images_per_sec_b8"
              if name == "google/vit-large-patch16-224"
              else f"{name.rsplit('/', 1)[-1].replace('-', '_')}"
                   "_images_per_sec_b8")
    cfg = entry.config
    rng = np.random.default_rng(0)
    side = int(round(cfg.num_patches ** 0.5)) * cfg.patch_size
    xs = jax.device_put(jnp.asarray(
        rng.normal(size=(n_ubatch, batch, cfg.num_channels, side, side)),
        dtype=jnp.bfloat16))
    return cfg, metric, xs


def _common_args(p) -> None:
    p.add_argument("--model", default="google/vit-large-patch16-224",
                   help="model to bench (default: the ViT-L headline; "
                        "non-default models re-name the metric)")
    p.add_argument("--ubatches", type=int, default=128,
                   help="microbatches in the streamed set (128 amortizes "
                        "dispatch overhead on TPU; lower for CPU-"
                        "loopback A/B evidence runs)")
    p.add_argument("--tp-quant-bits", type=int, default=8, choices=[8, 4],
                   help="bitwidth of the quant_collectives variant "
                        "(runtime.py --tp-quant-bits; "
                        "docs/QUANT_COLLECTIVES.md)")
    p.add_argument("--cal-dim", type=int,
                   default=CALIBRATION_RECIPE["matmul_mnk"][0],
                   help="calibration matmul dimension; non-default "
                        "values are off-recipe (MFU marked, not "
                        "comparable across records) — for CPU-loopback "
                        "A/B runs where 8192^3 is infeasible")


def _exact_args(p) -> None:
    _common_args(p)
    p.add_argument("--reps", type=int, default=REPS,
                   help="timed repetitions (median reported)")


def run_exact(args) -> dict:
    """The headline record (bench.py's historical main), returned as
    trajectory blocks: envelope throughput/latency/mfu + the full legacy
    record merged at top level."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import telemetry
    from ..models import registry
    from ..models.layers import set_fast_numerics
    from ..monitoring.energy import default_energy_source
    from ..telemetry import report as span_report
    from ..utils import require_live_backend

    # Pin exact numerics for the headline/calibration passes BEFORE any
    # trace: an inherited PIPEEDGE_FAST_NUMERICS=1 would otherwise compile
    # the "exact" side of the A/B in fast mode too, reporting a ~1.0
    # speedup while claiming exact-parity numerics (ADVICE.md r5).
    set_fast_numerics(False)

    name = args.model
    batch = 8   # reference profiles use batch=8 (README_Scheduler.md)
    n_ubatch = args.ubatches

    def parser_error(msg):
        raise SystemExit(f"bench.py --recipe exact: {msg}")

    cfg, metric, xs = _image_inputs(name, parser_error, n_ubatch, batch)
    # lease-neutral wedge diagnostic (shared with bench_decode.py)
    require_live_backend(metric, unit="images/sec")
    fn, params, _ = registry.module_shard_factory(
        name, None, 1, registry.get_model_layers(name), dtype=jnp.bfloat16)
    params = jax.device_put(params)

    cal_samples = calibrate_peak_samples(args.cal_dim)
    peak_flops = max(cal_samples)

    # the UN-jitted shard apply: the factory's fn is jitted, and jit
    # caches by function identity — a numerics-mode change (trace-time
    # flag) only binds through a fresh trace of the raw callable
    raw_fn = fn.__wrapped__

    def make_run_all():
        # a FRESH jit wrapper (and fresh inner trace via raw_fn) per
        # numerics mode
        @jax.jit
        def run_all(p, xs):
            def step(carry, x):
                logits = raw_fn(p, x)
                return carry + jnp.sum(logits.astype(jnp.float32)), None

            total, _ = jax.lax.scan(step, jnp.float32(0), xs)
            return total

        return run_all

    run_all = make_run_all()

    # Host-side energy (reference's energy-first monitoring demo): RAPL
    # powercap when readable, else an explicit unreadable record — never
    # silent omission.
    energy_src = default_energy_source()
    if energy_src is not None:
        energy_src.init()

    float(run_all(params, xs))  # compile + warmup (readback fences)
    e0 = energy_src.get_uj() if energy_src is not None else 0
    times = []
    for _ in range(args.reps):
        tik = time.monotonic()
        float(run_all(params, xs))
        times.append(time.monotonic() - tik)
    e1 = energy_src.get_uj() if energy_src is not None else 0
    samples = sorted(n_ubatch * batch / t for t in times)
    img_per_sec = statistics.median(samples)
    if energy_src is not None:
        wall = sum(times)
        energy_fields = {
            "host_energy_j_per_image": round(
                (e1 - e0) / 1e6 / (args.reps * n_ubatch * batch), 4),
            "host_power_w": round((e1 - e0) / 1e6 / wall, 1),
            "energy_source": "rapl-powercap (host CPU packages; TPU chip "
                             "power not exposed through JAX)",
        }
        energy_src.finish()
    else:
        energy_fields = {
            "energy_source": "unreadable on this host (no readable RAPL "
                             "powercap domains)"}

    # p50 microbatch latency: individual dispatch, fenced per microbatch.
    # Segmented (dispatch / transfer / emit) through telemetry spans so
    # the medians come out of the same span machinery the DCN trace
    # reports use.
    @jax.jit
    def run_one(p, x):
        return jnp.sum(fn(p, x).astype(jnp.float32))

    float(run_one(params, xs[0]))  # compile + warm
    rec = telemetry.configure(rank=0)
    lats = []
    for i in range(n_ubatch):
        tik = time.monotonic()
        with telemetry.span("stage", "dispatch", mb=i):
            fut = run_one(params, xs[i])
        with telemetry.span("stage", "transfer", mb=i):
            fut.block_until_ready()
        with telemetry.span("stage", "emit", mb=i):
            float(fut)
        lats.append(time.monotonic() - tik)
    segments = span_report.segment_medians(rec.snapshot(),
                                           cats=frozenset(("stage",)))
    telemetry.disable()
    p50_ms = statistics.median(lats) * 1e3
    steady_lats = sorted(lats[1:])
    latency_breakdown = {
        # first measured microbatch vs the warm rest: the fill/steady
        # split BENCH rounds track against steady_state_ubatch_ms
        "fill_ms": round(lats[0] * 1e3, 2),
        "steady_p50_ms": round(
            span_report.percentile(steady_lats, 50) * 1e3, 2),
        "steady_p99_ms": round(
            span_report.percentile(steady_lats, 99) * 1e3, 2),
        "segments_p50_ms": {
            key.split("/", 1)[1]: val["p50_ms"]
            for key, val in segments.items()},
    }

    flops_img = model_flops_per_image(cfg)
    achieved = img_per_sec * flops_img

    device_kind = jax.devices()[0].device_kind
    nominal_peak = NOMINAL_BF16_PEAK.get(device_kind)

    # fast-numerics headline (round-5 verdict item 1): the SAME streamed
    # loop with model-dtype LayerNorm/softmax and tanh GeLU, measured
    # interleaved with exact rounds so session drift hits both equally
    logits_exact = np.asarray(
        jax.jit(lambda p, x: raw_fn(p, x))(params,
                                           xs[0]).astype(jnp.float32))
    set_fast_numerics(True)
    try:
        run_all_fast = make_run_all()
        float(run_all_fast(params, xs))          # compile + warm
        fast_times, exact_times = [], []
        for _ in range(3):
            tik = time.monotonic()
            float(run_all(params, xs))
            exact_times.append(time.monotonic() - tik)
            tik = time.monotonic()
            float(run_all_fast(params, xs))
            fast_times.append(time.monotonic() - tik)
        fast_img_per_sec = statistics.median(
            n_ubatch * batch / t for t in fast_times)
        exact_adjacent = statistics.median(
            n_ubatch * batch / t for t in exact_times)
        logits_fast = np.asarray(
            jax.jit(lambda p, x: raw_fn(p, x))(params,
                                               xs[0]).astype(jnp.float32))
    finally:
        # None would re-defer to the env var — this bench's records must
        # stay exact-mode regardless of the inherited environment
        set_fast_numerics(False)
    fast_achieved = fast_img_per_sec * flops_img
    fast_fields = {
        "images_per_sec": round(fast_img_per_sec, 3),
        "exact_interleaved_images_per_sec": round(exact_adjacent, 3),
        "speedup_vs_exact": round(fast_img_per_sec / exact_adjacent, 3),
        "mfu_calibrated": round(fast_achieved / peak_flops, 3),
        "mfu_nominal": (round(fast_achieved / nominal_peak, 3)
                        if nominal_peak else None),
        "achieved_tflops": round(fast_achieved / 1e12, 1),
        **top1_agreement(logits_exact, logits_fast),
    }

    # quantized-collectives A/B: exact math, quantized ICI comms — the
    # variant meant to land between the exact and fast-numerics
    # endpoints at near-1.0 agreement
    qc_fields = quant_collectives_ab(name, args.tp_quant_bits, xs,
                                     flops_img, peak_flops, nominal_peak)

    off_recipe = args.cal_dim != CALIBRATION_RECIPE["matmul_mnk"][0]
    legacy = {
        "metric": metric,
        "value": round(img_per_sec, 3),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 1),
        "value_median": round(img_per_sec, 3),
        "value_spread": [round(samples[0], 3), round(samples[-1], 3)],
        "value_samples": [round(s, 3) for s in samples],
        "p50_microbatch_latency_ms": round(p50_ms, 2),
        "latency_breakdown": latency_breakdown,
        "steady_state_ubatch_ms": round(min(times) / n_ubatch * 1e3, 2),
        "mfu": round(achieved / peak_flops, 3),
        "mfu_calibrated": round(achieved / peak_flops, 3),
        "mfu_nominal": (round(achieved / nominal_peak, 3)
                        if nominal_peak else None),
        "achieved_tflops": round(achieved / 1e12, 1),
        # both names kept: calibrated_peak_tflops is the original record
        # key (BENCH_r01), peak_calibrated_tflops pairs with peak_nominal
        "calibrated_peak_tflops": round(peak_flops / 1e12, 1),
        "peak_calibrated_tflops": round(peak_flops / 1e12, 1),
        "peak_nominal_tflops": (round(nominal_peak / 1e12, 1)
                                if nominal_peak else None),
        # pinned calibration recipe + per-session spread (verdict item
        # 7): calibrated MFU carries explicit error bars
        "calibration": dict(
            CALIBRATION_RECIPE,
            matmul_mnk=[args.cal_dim] * 3,
            off_recipe=off_recipe or None,
            session_samples_tflops=[round(s / 1e12, 1)
                                    for s in cal_samples],
            calibration_spread=[round(min(cal_samples) / 1e12, 1),
                                round(max(cal_samples) / 1e12, 1)]),
        "mfu_calibrated_range": [
            round(achieved / max(cal_samples), 3),
            round(achieved / min(cal_samples), 3)],
        "fast_numerics": fast_fields,
        "quant_collectives": qc_fields,
        # the active collective bitwidth rides the record so BENCH_r0N
        # trajectories are self-describing (which knob produced this line)
        "tp_quant_bits": args.tp_quant_bits,
        "device_kind": device_kind,
        **energy_fields,
    }
    return {
        "throughput": {"value": legacy["value"], "unit": "images/sec",
                       "samples": legacy["value_samples"],
                       "spread": legacy["value_spread"]},
        "latency_ms": {"p50": latency_breakdown["steady_p50_ms"],
                       "p99": latency_breakdown["steady_p99_ms"],
                       "n": len(steady_lats)},
        "mfu": {"calibrated": legacy["mfu_calibrated"],
                "nominal": legacy["mfu_nominal"],
                "achieved_tflops": legacy["achieved_tflops"],
                "peak_calibrated_tflops":
                    legacy["peak_calibrated_tflops"],
                "calibration_version": CALIBRATION_RECIPE["version"],
                "off_recipe": off_recipe},
        "legacy": legacy,
    }


def _qc_args(p) -> None:
    _common_args(p)


def run_quant_collectives(args) -> dict:
    """Standalone quantized-collectives record (the exact recipe embeds
    the same A/B; this recipe re-arms just that scenario without paying
    the full headline run)."""
    import jax

    from ..models.layers import set_fast_numerics
    from ..utils import require_live_backend

    set_fast_numerics(False)

    def parser_error(msg):
        raise SystemExit(f"bench.py --recipe quant_collectives: {msg}")

    cfg, metric, xs = _image_inputs(args.model, parser_error,
                                    args.ubatches)
    require_live_backend(metric, unit="images/sec")
    cal_samples = calibrate_peak_samples(args.cal_dim)
    peak_flops = max(cal_samples)
    nominal_peak = NOMINAL_BF16_PEAK.get(jax.devices()[0].device_kind)
    qc = quant_collectives_ab(args.model, args.tp_quant_bits, xs,
                              model_flops_per_image(cfg), peak_flops,
                              nominal_peak)
    if qc.get("mode") == "skipped":
        return {"extras": qc,
                "notes": f"skipped: {qc['reason']}"}
    quality = {"top1_agreement_vs_exact": qc["top1_agreement_vs_exact"],
               "max_abs_logit_delta": qc["max_abs_logit_delta"]}
    return {
        "throughput": {"value": qc["images_per_sec"],
                       "unit": "images/sec"},
        "quality": quality,
        "mfu": {"calibrated": qc["mfu_calibrated"],
                "nominal": qc["mfu_nominal"],
                "achieved_tflops": qc["achieved_tflops"],
                "calibration_version": CALIBRATION_RECIPE["version"],
                "off_recipe": (args.cal_dim
                               != CALIBRATION_RECIPE["matmul_mnk"][0])},
        "extras": qc,
    }


def _register():
    from . import Recipe, register
    register(Recipe(
        "exact", "headline streamed-pipeline bench: exact img/s, "
                 "calibrated MFU, fast-numerics + quant-collectives A/Bs",
        _exact_args, run_exact, tier="chip"))
    register(Recipe(
        "quant_collectives", "standalone int8/int4 quantized-ICI-"
                             "collective A/B (needs tp >= 2 devices)",
        _qc_args, run_quant_collectives, tier="fast"))


_register()
