"""`int8_compute` recipe: exact vs fast-numerics vs int8 compute A/B.

Three traces of the SAME streamed image pipeline, timed interleaved so
session drift hits every side equally (the headline A/B discipline):

  exact — f32/bf16 matmuls, exact-parity numerics pinned
          (`set_fast_numerics(False)`, quantize-compute pinned OFF);
  fast  — model-dtype LayerNorm/softmax + tanh GeLU (the PR 9 knob);
  int8  — every tagged dense routed through the block-scaled int8
          Pallas matmul (ops/int8_matmul.py) behind `QuantizeCompute`,
          with Banner clamp alphas calibrated inline from the first
          microbatch (utils/calibrate.py) unless a sidecar is given.

Each non-exact side reports img/s plus top-1 agreement / max-abs logit
delta vs the interleaved exact logits — a quantized number without its
agreement is not self-describing. The headline quality gate for the
int8 side is >= 0.99 top-1 agreement; the chip-window throughput target
(1126 img/s, ViT-L b8) rides the record as `chip_window_target_img_s`
so bench_report trajectories can gate on it (docs/QUANTIZATION.md).

Both numerics knobs are TRACE-time config: each mode gets a fresh jit
wrapper over the raw (unjitted) shard apply, and the finally-blocks pin
exact mode back rather than re-deferring to the environment (the
ADVICE.md r5 env-poisoning lesson, same as headline.py).
"""
import statistics
import time

# ViT-L b8 int8 chip-window target (ISSUE 19 acceptance): recorded, and
# gated only when the backend is a real TPU — a CPU A/B run records the
# agreement evidence without pretending to the throughput claim.
CHIP_WINDOW_TARGET_IMG_S = 1126.0


def _args(p) -> None:
    p.add_argument("--model", default="google/vit-large-patch16-224",
                   help="image-family model to A/B (default: the ViT-L "
                        "headline)")
    p.add_argument("--ubatches", type=int, default=32,
                   help="microbatches in the streamed set (three modes "
                        "run interleaved; smaller than the headline's "
                        "128 keeps the A/B affordable)")
    p.add_argument("--reps", type=int, default=3,
                   help="interleaved timing rounds (median reported)")
    p.add_argument("--block-k", type=int, default=128,
                   help="activation K-block for the block-scaled int8 "
                        "matmul (ops/int8_matmul.py)")
    p.add_argument("--skip-tags", default="",
                   help="comma-separated dense tags kept exact in the "
                        "int8 mode (per-layer opt-out, e.g. attn.out)")
    p.add_argument("--sidecar", default=None,
                   help="calibration sidecar (tools/calibrate.py) for "
                        "the clamp alphas; default: inline calibration "
                        "from the first microbatch")
    p.add_argument("--no-clamp", action="store_true",
                   help="skip activation clamping entirely (no "
                        "calibration pass; pure dynamic block scales)")


def _calibrated_alphas(args, name, x0) -> dict:
    """Clamp alphas for the int8 mode: sidecar if given, else a one-batch
    inline sweep with the tag observer (eager, unrolled)."""
    from ..utils import calibrate
    if args.sidecar:
        return calibrate.load_sidecar(args.sidecar)["alphas"]
    from ..models import registry
    import numpy as np
    fn, params, _ = registry.module_shard_factory(
        name, None, 1, registry.get_model_layers(name), unroll=True)
    raw_fn = getattr(fn, "__wrapped__", fn)
    stats = calibrate.collect_activation_stats(
        raw_fn, params, [np.asarray(x0, np.float32)])
    return calibrate.compute_alphas(stats, bit=8)


def run_int8_compute(args) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import registry
    from ..models.layers import (QuantizeCompute, set_fast_numerics,
                                 set_quantize_compute)
    from ..ops import int8_matmul
    from ..utils import require_live_backend
    from .headline import _image_inputs, top1_agreement

    # Pin exact numerics AND quantize-compute OFF before any trace: an
    # inherited PIPEEDGE_FAST_NUMERICS=1 / PIPEEDGE_QUANTIZE_COMPUTE=1
    # would otherwise poison the "exact" side of the A/B (ADVICE.md r5).
    set_fast_numerics(False)
    set_quantize_compute(False)

    def parser_error(msg):
        raise SystemExit(f"bench.py --recipe int8_compute: {msg}")

    name = args.model
    batch = 8
    n_ubatch = args.ubatches
    cfg, metric, xs = _image_inputs(name, parser_error, n_ubatch, batch)
    require_live_backend(f"int8_{metric}", unit="images/sec")

    fn, params, _ = registry.module_shard_factory(
        name, None, 1, registry.get_model_layers(name), dtype=jnp.bfloat16)
    params = jax.device_put(params)
    raw_fn = fn.__wrapped__

    alphas = None
    if not args.no_clamp:
        alphas = _calibrated_alphas(args, name, xs[0])
    skip = frozenset(t for t in args.skip_tags.split(",") if t)
    qc = QuantizeCompute(enabled=True, block_k=args.block_k,
                         skip_tags=skip, clamp_alphas=alphas)

    def make_run_all():
        # fresh jit wrapper (and fresh inner trace via raw_fn) per mode —
        # jit caches by function identity, trace-time flags don't rebind
        @jax.jit
        def run_all(p, xs):
            def step(carry, x):
                logits = raw_fn(p, x)
                return carry + jnp.sum(logits.astype(jnp.float32)), None

            total, _ = jax.lax.scan(step, jnp.float32(0), xs)
            return total

        return run_all

    def probe_logits(p, x):
        return np.asarray(
            jax.jit(lambda p, x: raw_fn(p, x))(p, x).astype(jnp.float32))

    # --- trace + warm all three modes, capturing per-mode logits -------
    run_exact = make_run_all()
    float(run_exact(params, xs))
    logits_exact = probe_logits(params, xs[0])

    set_fast_numerics(True)
    try:
        run_fast = make_run_all()
        float(run_fast(params, xs))
        logits_fast = probe_logits(params, xs[0])
    finally:
        set_fast_numerics(False)

    set_quantize_compute(qc)
    try:
        run_q = make_run_all()
        float(run_q(params, xs))
        logits_q = probe_logits(params, xs[0])
    finally:
        # False, not None — None would re-defer to the env var, and this
        # bench's exact side must stay exact regardless of environment
        set_quantize_compute(False)

    # --- interleaved timing rounds ------------------------------------
    times = {"exact": [], "fast": [], "int8": []}
    for _ in range(args.reps):
        for key, run in (("exact", run_exact), ("fast", run_fast),
                         ("int8", run_q)):
            tik = time.monotonic()
            float(run(params, xs))
            times[key].append(time.monotonic() - tik)
    img = {key: statistics.median(n_ubatch * batch / t for t in ts)
           for key, ts in times.items()}

    fast_agree = top1_agreement(logits_exact, logits_fast)
    int8_agree = top1_agreement(logits_exact, logits_q)

    on_tpu = jax.devices()[0].platform == "tpu"
    extras = {
        "metric": f"int8_{metric}",
        "exact_images_per_sec": round(img["exact"], 3),
        "fast_images_per_sec": round(img["fast"], 3),
        "int8_images_per_sec": round(img["int8"], 3),
        "int8_speedup_vs_exact": round(img["int8"] / img["exact"], 3),
        "fast_speedup_vs_exact": round(img["fast"] / img["exact"], 3),
        "fast_numerics": fast_agree,
        "block_k": args.block_k,
        "skip_tags": sorted(skip),
        "clamp": ("sidecar" if args.sidecar
                  else "off" if args.no_clamp else "inline-1-batch"),
        "kernel": {
            "mode": int8_matmul._mode(),
            "native_available": bool(int8_matmul.kernel_available()),
        },
        "chip_window_target_img_s": CHIP_WINDOW_TARGET_IMG_S,
        # only a real chip window may claim the throughput target; CPU
        # runs record null here and carry the agreement evidence only
        "chip_window_met": (bool(img["int8"] >= CHIP_WINDOW_TARGET_IMG_S)
                            if on_tpu else None),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }
    return {
        "throughput": {"value": extras["int8_images_per_sec"],
                       "unit": "images/sec"},
        "quality": dict(int8_agree),
        "extras": extras,
    }


def _register():
    from . import Recipe, register
    register(Recipe(
        "int8_compute", "exact vs fast-numerics vs int8-compute A/B: "
                        "img/s + top-1 agreement through the block-"
                        "scaled Pallas int8 matmul path",
        _args, run_int8_compute, tier="fast"))


_register()
