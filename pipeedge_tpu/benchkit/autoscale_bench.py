"""The autoscale A/B recipe: advise vs auto under one seeded load ramp.

The self-driving-capacity question is not "can the controller spawn a
replica" (tools/chaos_dcn.py --target autoscale proves that under
chaos) — it is "what does closing the loop BUY": the same seeded
piecewise-linear ramp (`loadgen --arrival ramp:LO:HI[:HOLD]`) is offered
twice against an identical 1-replica-floor router fleet, once with the
controller in `--autoscale advise` (decisions logged, nothing actuated —
the control arm) and once in `--autoscale auto` (decisions applied).
The record carries both arms side by side: time-to-scale-up, per-class
SLO attainment during the ramp, aggregate goodput, and the decision
count, so `bench_report --gate` catches a controller that stopped
scaling (attainment/goodput collapse to the advise arm's numbers) or
started flapping (decision count explodes) the same way it catches a
throughput regression.

Mechanics per arm: spawn `tools/serve.py --role router` parked at the
floor with `--max-active 1` replicas (one replica's honest capacity is
a few req/s, so the ramp's plateau genuinely queues), warm the floor
replica with the exact load shape (an unwarmed page-boundary XLA
compile masquerades as a capacity shortfall), offer the ramp, then for
the auto arm wait for the drain back to the floor. Both arms share the
loadgen seed: identical arrival offsets and prompts, so the A/B delta
is the controller, not the traffic.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# outcome keys copied into serve.shed (the loadgen taxonomy,
# tools/loadgen.py module doc)
SHED_TAXONOMY = ("shed", "degraded", "deadline", "error", "ok_late")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Reader:
    """Timestamped line capture off a subprocess's merged stdout (the
    router narrates `autoscale_spawn` / `autoscale_decision` lines; the
    timestamps turn them into time-to-scale-up)."""

    def __init__(self, proc):
        self.proc = proc
        self.lines = []
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        for line in self.proc.stdout:
            self.lines.append((time.monotonic(), line.rstrip("\n")))

    def join(self):
        self._t.join(timeout=5)

    def first(self, *prefixes):
        for t, line in self.lines:
            if line.startswith(prefixes):
                return t, line
        return None


def _autoscale_args(p) -> None:
    p.add_argument("--model", default="pipeedge/test-tiny-gpt2")
    p.add_argument("--partition", default="1,4,5,8",
                   help="pipeline layer partition (serve.py -pt)")
    p.add_argument("--max-len", type=int, default=64)
    p.add_argument("--ramp", default="ramp:1:8:0.4",
                   help="seeded piecewise-linear arrival spec offered "
                        "identically to BOTH arms")
    p.add_argument("--duration", type=float, default=12.0,
                   help="seconds of ramp per arm")
    p.add_argument("--new-tokens", type=int, default=24,
                   help="decode tokens per request (24 keeps one "
                        "--max-active 1 replica's capacity around "
                        "~3 req/s so the ramp's plateau queues)")
    p.add_argument("--prompt-len", type=int, default=6)
    p.add_argument("--seed", type=int, default=7,
                   help="loadgen seed shared by both arms (identical "
                        "arrivals + prompts)")
    p.add_argument("--floor", type=int, default=1)
    p.add_argument("--ceiling", type=int, default=2)
    p.add_argument("--kv-pages", type=int, default=96)
    p.add_argument("--kv-page-size", type=int, default=8)
    p.add_argument("--settle-s", type=float, default=60.0,
                   help="post-ramp wait for the auto arm's drain back "
                        "to the floor")
    p.add_argument("--startup-timeout", type=float, default=180.0)


def _spawn_fleet(args, mode: str):
    port = _free_port()
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve.py"),
           "--role", "router", "--replicas", str(args.floor),
           "-m", args.model, "-pt", args.partition,
           "--max-len", str(args.max_len), "-t", "float32",
           "--port", str(port),
           "--kv-pages", str(args.kv_pages),
           "--kv-page-size", str(args.kv_page_size),
           "--max-active", "1",
           "--router-poll-interval", "0.2",
           "--fleet-scrape-interval", "0.3",
           "--autoscale", mode,
           "--autoscale-min", str(args.floor),
           "--autoscale-max", str(args.ceiling),
           "--autoscale-confirm", "2",
           "--autoscale-cooldown", "2.0",
           "--autoscale-interval", "0.3",
           "--autoscale-dwell-down", "1.0",
           "--autoscale-queue-high", "2.0",
           "--autoscale-queue-low", "0.5"]
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    return proc, f"http://127.0.0.1:{port}"


def _get_json(url: str, path: str, timeout=10.0):
    with urllib.request.urlopen(f"{url}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _run_arm(args, mode: str, loadgen) -> dict:
    proc, url = _spawn_fleet(args, mode)
    reader = _Reader(proc)
    try:
        deadline = time.monotonic() + args.startup_timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"{mode} arm router died during startup")
            try:
                h = _get_json(url, "/healthz", timeout=5)
                if h.get("ok") and all(r["state"] == "healthy"
                                       for r in h["fleet"].values()):
                    break
            except OSError:
                time.sleep(0.5)
        else:
            raise RuntimeError(f"{mode} arm fleet never became healthy")
        # warm with the exact load shape: the first request crossing a
        # KV page boundary pays a multi-second XLA compile, and an
        # unwarmed compile stall reads as a capacity shortfall
        payload = json.dumps({"ids": [7] * args.prompt_len,
                              "new_tokens": args.new_tokens}).encode()
        for rep in h["fleet"].values():
            req = urllib.request.Request(
                f"{rep['url']}/generate", data=payload,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                resp.read()
        load_t0 = time.monotonic()
        report = loadgen.run_load(
            f"{url}/generate", args.duration, None,
            mix={"interactive": 1.0}, deadline_from_slo=False,
            new_tokens=args.new_tokens, prompt_len=str(args.prompt_len),
            seed=args.seed, arrival=args.ramp)
        scale_down_s = None
        if mode == "auto":
            settle_deadline = time.monotonic() + args.settle_s
            while time.monotonic() < settle_deadline:
                a = _get_json(url, "/healthz",
                              timeout=5).get("autoscale") or {}
                if a.get("size") == args.floor and (
                        a.get("decisions") or {}).get("applied", 0) >= 2:
                    scale_down_s = round(
                        time.monotonic() - load_t0 - args.duration, 3)
                    break
                time.sleep(0.5)
        asnap = _get_json(url, "/healthz",
                          timeout=5).get("autoscale") or {}
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        reader.join()
    # the actuated arm's first spawn vs the advisory arm's first logged
    # up-decision: both are "when did the controller move", comparable
    first_up = (reader.first("autoscale_spawn") if mode == "auto"
                else next(((t, line) for t, line in reader.lines
                           if line.startswith("autoscale_decision")
                           and "direction=up" in line), None))
    classes = report["classes"]
    goodput = {c: classes[c]["goodput_rps"] for c in classes}
    goodput["total"] = round(sum(goodput.values()), 3)
    decisions = asnap.get("decisions") or {}
    return {
        "mode": mode,
        "requests": report["requests"],
        "offered_qps": report["offered_qps"],
        "ramp": report.get("ramp"),
        "goodput_rps": goodput,
        "slo_attainment": {c: classes[c]["slo_attainment"]
                           for c in classes},
        "shed": dict({k: report["totals"][k] for k in SHED_TAXONOMY},
                     client_dropped=report["client_dropped"]),
        "latency_ms": {q: report["latency_ms"][q]
                       for q in ("p50", "p95", "p99", "n")},
        "decisions": decisions,
        "decision_count": sum(decisions.values()),
        "ticks": asnap.get("ticks"),
        "final_size": asnap.get("size"),
        "time_to_first_up_s": (round(first_up[0] - load_t0, 3)
                               if first_up else None),
        "scale_down_s": scale_down_s,
    }


def _run(args) -> dict:
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools import loadgen

    advise = _run_arm(args, "advise", loadgen)
    auto = _run_arm(args, "auto", loadgen)

    notes = None
    errs = advise["shed"]["error"] + auto["shed"]["error"]
    if errs:
        notes = f"{errs} handler error(s) across the two arms"
    att_delta = {
        c: round(auto["slo_attainment"][c]
                 - advise["slo_attainment"].get(c, 0.0), 4)
        for c in auto["slo_attainment"]}
    return {
        # the headline is the CLOSED-LOOP arm: what the fleet actually
        # delivers when the controller is allowed to act
        "throughput": {"value": auto["goodput_rps"]["total"],
                       "unit": "req/s",
                       "detail": "aggregate goodput under the seeded "
                                 "ramp, --autoscale auto arm"},
        "latency_ms": auto["latency_ms"],
        "serve": {
            "goodput_rps": auto["goodput_rps"],
            "slo_attainment": auto["slo_attainment"],
            "shed": auto["shed"],
            "offered_qps": auto["offered_qps"],
            "requests": auto["requests"],
            "ramp": args.ramp,
            "seed": args.seed,
            "floor": args.floor,
            "ceiling": args.ceiling,
        },
        "notes": notes,
        "extras": {
            "ab": {"advise": advise, "auto": auto},
            "time_to_scale_up_s": auto["time_to_first_up_s"],
            "advise_first_up_s": advise["time_to_first_up_s"],
            "scale_down_s": auto["scale_down_s"],
            "decision_count": {"advise": advise["decision_count"],
                               "auto": auto["decision_count"]},
            "attainment_delta_auto_minus_advise": att_delta,
        },
    }


def _register():
    from . import Recipe, register
    register(Recipe(
        "autoscale", "advise-vs-auto capacity-controller A/B under one "
                     "seeded load ramp: time-to-scale-up, attainment "
                     "during the ramp, decision counts",
        _autoscale_args, _run, tier="fleet"))


_register()
