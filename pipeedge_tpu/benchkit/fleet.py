"""Fleet recipes: the SPMD one-process pipeline and the multi-process
loopback DCN pipeline, driven through runtime.py subprocesses.

Both recipes reuse the runtime's own measured output (the
`steady_state_throughput_items_sec=` / `throughput_items_sec=` stdout
lines every run prints) rather than re-timing from outside — the number
in the trajectory record is the same number a production fleet logs.
The `dcn` recipe additionally collects the merged `--trace-spans`
timeline and folds `trace_report`'s bubble % + per-microbatch latency
percentiles into the record, so a DCN regression names WHERE the round
went (bubble vs wire vs compute), not just that it slowed.
"""
from __future__ import annotations

import os
import re
import socket
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_STDOUT_KEYS = {
    "steady_state_throughput_items_sec": "steady_items_per_sec",
    "throughput_items_sec": "items_per_sec",
    "latency_sec": "round_latency_s",
}


def parse_runtime_stdout(text: str) -> Dict[str, float]:
    """Lift the runtime's `key=value` measurement lines into a dict
    (last occurrence wins — the final settled round is the record)."""
    out: Dict[str, float] = {}
    for m in re.finditer(r"(\w+)=([0-9.eE+-]+)", text):
        key = _STDOUT_KEYS.get(m.group(1))
        if key is not None:
            try:
                out[key] = float(m.group(2))
            except ValueError:
                pass
    return out


def _free_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _env(platform: str, devices: int) -> dict:
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
    if platform == "cpu" and devices > 1:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count"
                            f"={devices}").strip()
    env.setdefault("DCN_CONNECT_TIMEOUT", "30")
    env["PYTHONPATH"] = REPO
    return env


def _common_fleet_args(p) -> None:
    p.add_argument("--model", default="pipeedge/test-tiny-vit")
    p.add_argument("--partition", default="1,4,5,8")
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--ubatches", type=int, default=4)
    p.add_argument("--platform", default="cpu",
                   help="JAX platform for the spawned fleet (cpu for "
                        "loopback smokes; empty = inherit)")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="hard wall on the spawned fleet")


def _spmd_args(p) -> None:
    _common_fleet_args(p)
    p.add_argument("--world", type=int, default=4,
                   help="virtual SPMD world size (one process)")
    p.add_argument("--spmd-tp", type=int, default=0,
                   help="per-stage TP slice width (0 = none)")
    p.add_argument("--devices", type=int, default=8,
                   help="forced host device count on the cpu platform")


def _run_spmd(args) -> dict:
    cmd = [sys.executable, os.path.join(REPO, "runtime.py"),
           "0", str(args.world), "-c", "spmd", "-m", args.model,
           "-b", str(args.batch), "-u", str(args.ubatches),
           "-pt", args.partition]
    if args.platform:
        cmd += ["--platform", args.platform]
    if args.spmd_tp:
        cmd += ["--spmd-tp", str(args.spmd_tp)]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=args.timeout, cwd=REPO,
                          env=_env(args.platform, args.devices))
    if proc.returncode != 0:
        raise RuntimeError(f"spmd runtime exited {proc.returncode}:\n"
                           f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    measured = parse_runtime_stdout(proc.stdout)
    value = measured.get("steady_items_per_sec",
                         measured.get("items_per_sec"))
    if value is None:
        raise RuntimeError("spmd runtime printed no throughput line:\n"
                           f"{proc.stdout[-2000:]}")
    return {
        "throughput": {"value": round(value, 3), "unit": "items/sec"},
        "extras": {"measured": measured, "world": args.world,
                   "spmd_tp": args.spmd_tp or None},
    }


def _dcn_args(p) -> None:
    _common_fleet_args(p)
    p.add_argument("--world", type=int, default=2,
                   help="loopback fleet size (one OS process per rank)")
    p.add_argument("--trace-out", default=None,
                   help="merged trace path (default: a temp file; the "
                        "bubble/latency blocks are folded into the "
                        "record either way)")


def _run_dcn(args) -> dict:
    import json

    from ..telemetry import chrome_trace, report
    trace_out = args.trace_out or os.path.join(
        tempfile.mkdtemp(prefix="benchkit_dcn_"), "trace.json")
    ports = _free_ports(args.world)
    addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
    base = [sys.executable, os.path.join(REPO, "runtime.py")]
    opts = ["-c", "dcn", "-m", args.model, "-pt", args.partition,
            "-q", "8,0", "-r", "0,1", "-b", str(args.batch),
            "-u", str(args.ubatches), "--dcn-addrs", addrs,
            "--sched-timeout", "120", "--trace-spans", trace_out]
    if args.platform:
        opts += ["--platform", args.platform]
    env = _env(args.platform, 1)
    workers = [subprocess.Popen(base + [str(r), str(args.world)] + opts,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                cwd=REPO, env=env)
               for r in range(1, args.world)]
    data_rank: Optional[subprocess.CompletedProcess] = None
    try:
        data_rank = subprocess.run(
            base + ["0", str(args.world)] + opts, capture_output=True,
            text=True, timeout=args.timeout, cwd=REPO, env=env)
        for w in workers:
            w.wait(timeout=60)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
    if data_rank.returncode != 0:
        raise RuntimeError(
            f"dcn data rank exited {data_rank.returncode}:\n"
            f"{data_rank.stdout[-2000:]}\n{data_rank.stderr[-2000:]}")
    measured = parse_runtime_stdout(data_rank.stdout)
    value = measured.get("steady_items_per_sec",
                         measured.get("items_per_sec"))
    if value is None:
        raise RuntimeError("dcn data rank printed no throughput line:\n"
                           f"{data_rank.stdout[-2000:]}")
    blocks = {
        "throughput": {"value": round(value, 3), "unit": "items/sec"},
        "extras": {"measured": measured, "world": args.world,
                   "trace": trace_out},
    }
    try:
        with open(trace_out, encoding="utf8") as fh:
            spans = chrome_trace.trace_to_spans(json.load(fh))
        analysis = report.analyze_spans(spans)
        mb = analysis.get("mb_latency") or {}
        if mb.get("n"):
            blocks["latency_ms"] = {"p50": mb.get("p50_ms"),
                                    "p95": mb.get("p95_ms"),
                                    "p99": mb.get("p99_ms"),
                                    "n": mb["n"]}
        blocks["extras"]["bubble_pct"] = analysis.get("bubble_pct")
        blocks["extras"]["transport"] = analysis.get("transport")
    except (OSError, ValueError) as exc:
        blocks["notes"] = f"trace analysis unavailable: {exc!r}"
    return blocks


def _register():
    from . import Recipe, register
    register(Recipe(
        "spmd", "one-process SPMD pipeline (virtual world) via "
                "runtime.py: steady items/sec",
        _spmd_args, _run_spmd, tier="fleet"))
    register(Recipe(
        "dcn", "multi-process loopback DCN pipeline fleet with a merged "
               "trace: steady items/sec + bubble % + mb latency",
        _dcn_args, _run_dcn, tier="fleet"))


_register()
