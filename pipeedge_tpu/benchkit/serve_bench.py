"""The goodput-first serve recipe: loadgen vs tools/serve.py at N x
calibrated overload.

The honest headline for a serving plane under heavy traffic is not
img/s — it is per-class goodput and SLO attainment at overload, with the
excess converted to taxonomized sheds instead of collapse (PR 7), and
every p99 bucket cross-linked to a request trace id (PR 10's exemplar
machinery) so a regression names the request class and the dominant
stall, not just a number.

Mechanics: `setup` spawns `tools/serve.py` (loopback, CPU-capable,
`--max-active` pins capacity so "3x overload" is deterministic) with
`--trace-spans`, `run` calibrates the closed-loop sequential service
rate, offers `--overload-factor` times it through `tools/loadgen.py`'s
open-loop generator (seeded arrivals + prompts — reproducible), then
scrapes /metrics for the latency histogram's `# EXEMPLAR` lines (the
p99-bucket -> trace-id link), and `teardown` SIGTERMs the server so it
writes the merged trace. The record's `serve.trace` +
`latency_ms.exemplars` rows make `tools/trace_report.py --request RID`
the one-command "explain this p99" follow-up.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# outcome keys copied into serve.shed (the loadgen taxonomy,
# tools/loadgen.py module doc)
SHED_TAXONOMY = ("shed", "degraded", "deadline", "error", "ok_late")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _serve_args(p) -> None:
    p.add_argument("--model", default="pipeedge/test-tiny-gpt2",
                   help="model tools/serve.py loads (default: the tiny "
                        "CI loopback model)")
    p.add_argument("--partition", default="1,4,5,8",
                   help="pipeline layer partition (serve.py -pt)")
    p.add_argument("--max-len", type=int, default=48)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--executor", default="wave",
                   choices=["wave", "stage"])
    p.add_argument("--max-active", type=int, default=1,
                   help="execution slots (1 pins capacity so the "
                        "overload factor is deterministic)")
    p.add_argument("--replicas", type=int, default=1,
                   help="decode replicas behind a `--role router` "
                        "front-end (serving/router.py); 1 = the classic "
                        "single-process server. The 1-vs-2 A/B arms of "
                        "one overload run are the routed fleet's "
                        "capacity-scaling record (use --scenario-suffix "
                        "to keep both arms in one artifact)")
    p.add_argument("--queue-capacity", type=int, default=16)
    p.add_argument("--overload-factor", type=float, default=3.0,
                   help="offered load as a multiple of the calibrated "
                        "sequential service rate")
    p.add_argument("--overload-factors", default=None,
                   metavar="F1,F2,...",
                   help="sweep MULTIPLE overload factors (e.g. 1,3,5) "
                        "in one run: each factor gets --duration seconds "
                        "of offered load against ONE calibration, and "
                        "the record carries the full goodput-vs-offered-"
                        "load curve (serve.overload_curve) with the "
                        "LAST factor as the headline blocks; overrides "
                        "--overload-factor")
    p.add_argument("--duration", type=float, default=6.0,
                   help="seconds of offered load")
    p.add_argument("--calibrate-s", type=float, default=2.0,
                   help="closed-loop capacity measurement window")
    p.add_argument("--new-tokens", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=6)
    p.add_argument("--mix", action="append", metavar="CLASS=WEIGHT",
                   help="per-class arrival weight (loadgen default mix)")
    p.add_argument("--slo", action="append", metavar="CLASS=MS",
                   help="per-class SLO / deadline budget")
    p.add_argument("--seed", type=int, default=0,
                   help="loadgen seed: arrival process, class draw, and "
                        "prompt sampling (rides the record)")
    p.add_argument("--arrival", default="uniform",
                   choices=["uniform", "poisson"],
                   help="arrival process (seeded; poisson models bursty "
                        "open-loop traffic)")
    p.add_argument("--trace-out", default="bench_serve_trace.json",
                   help="merged span trace the server writes on "
                        "shutdown (trace_report --request input)")
    p.add_argument("--postmortem-dir", default=None,
                   help="flight-recorder bundle dir (serve.py default "
                        "when unset)")
    p.add_argument("--startup-timeout", type=float, default=180.0)
    p.add_argument("--extra-serve-arg", action="append",
                   dest="extra_serve_args", metavar="ARG", default=[],
                   help="extra tools/serve.py argv token, repeatable "
                        "(e.g. --extra-serve-arg=--kv-pages "
                        "--extra-serve-arg=64 arms the paged plane for "
                        "an overload sweep A/B arm)")


def _setup(args) -> dict:
    port = _free_port()
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve.py"),
           "-m", args.model, "-pt", args.partition,
           "--max-len", str(args.max_len), "-t", args.dtype,
           "--executor", args.executor, "--port", str(port),
           "--queue-capacity", str(args.queue_capacity),
           "--trace-spans", args.trace_out,
           # brownout watermarks scaled for a 1-slot loopback server:
           # the ladder must engage inside a ~6 s overload window
           "--brownout-queue-high", "4", "--brownout-queue-low", "1",
           "--brownout-p95-high", "0.75", "--brownout-p95-low", "0.3",
           "--brownout-dwell-up", "0.3", "--brownout-dwell-down", "0.7",
           "--brownout-clamp-tokens", "8", "--governor-interval", "0.1"]
    # 0/absent = let the executor choose (the serve_kv recipe's paged
    # servers are page-bounded, not slot-bounded)
    if getattr(args, "max_active", 0):
        cmd += ["--max-active", str(args.max_active)]
    # extra flags a composing recipe appends (serve_kv: --kv-pages ...)
    cmd += list(getattr(args, "extra_serve_args", ()))
    if args.postmortem_dir:
        cmd += ["--postmortem-dir", args.postmortem_dir]
    replicas = getattr(args, "replicas", 1)
    if replicas > 1:
        # the routed arm: same knobs, but serve.py becomes a router
        # front-end forwarding them to `replicas` supervised replica
        # processes (each gets its own --max-active slots, so capacity
        # scales with the fleet)
        cmd += ["--role", "router", "--replicas", str(replicas),
                "--router-poll-interval", "0.3"]
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    state = {"proc": proc, "port": port,
             "url": f"http://127.0.0.1:{port}"}
    # setup owns its cleanup: run_recipe only reaches teardown once setup
    # has RETURNED, so a startup failure must not leak the server process
    try:
        deadline = time.monotonic() + args.startup_timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError("serve.py died during startup:\n"
                                   f"{proc.stdout.read()}")
            try:
                with urllib.request.urlopen(f"{state['url']}/healthz",
                                            timeout=5):
                    break
            except OSError:
                time.sleep(0.5)
        else:
            raise RuntimeError("serve.py never became healthy "
                               f"within {args.startup_timeout}s")
        if replicas > 1:
            # warm EVERY replica directly: the router's deterministic
            # least-loaded tie-break would otherwise leave replica 2+
            # cold and fold its first XLA compile into the measured
            # overload window
            with urllib.request.urlopen(f"{state['url']}/healthz",
                                        timeout=10) as resp:
                fleet = json.loads(resp.read())["fleet"]
            payload = json.dumps({"ids": [7] * args.prompt_len,
                                  "new_tokens": args.new_tokens}).encode()
            for rep in fleet.values():
                req = urllib.request.Request(
                    f"{rep['url']}/generate", data=payload,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=300) as resp:
                    resp.read()
    except BaseException:
        _teardown(state)     # SIGTERM + reap (kill on a wedged server)
        raise
    return state


def _scrape_exemplars(url: str) -> list:
    """`{le, trace_id, value_s}` rows from the server's request-latency
    histogram — the p99-bucket -> trace-id cross-link the record carries
    (pipeedge_tpu/telemetry/metrics.py renders them, parse_exemplars
    reads them back)."""
    from ..telemetry import metrics as prom
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
        text = resp.read().decode()
    return prom.parse_exemplars(
        text, "pipeedge_serve_request_latency_seconds")


def _run(args, state) -> dict:
    # tools/ is a sibling top-level package of pipeedge_tpu; both resolve
    # from the repo root, which REPO re-adds for non-repo-cwd callers
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools import loadgen

    url = f"{state['url']}/generate"
    mix = loadgen.merge_class_map(args.mix, "--mix", loadgen.DEFAULT_MIX)
    slo = loadgen.merge_class_map(args.slo, "--slo",
                                  loadgen.DEFAULT_SLO_MS)
    capacity = loadgen.calibrate(url, args.calibrate_s, args.new_tokens,
                                 args.prompt_len, timeout=120.0,
                                 seed=args.seed)
    factors = [args.overload_factor]
    if args.overload_factors:
        factors = [float(f) for f in args.overload_factors.split(",")]
        if not factors or any(f <= 0 for f in factors):
            raise ValueError(f"bad --overload-factors "
                             f"{args.overload_factors!r}")
    # sweep: each factor offers `duration` seconds against the SAME
    # calibration, so the curve is goodput vs offered load on one
    # capacity baseline (ROADMAP item 5's 1x/3x/5x goodput curve);
    # the LAST factor's full report feeds the headline blocks below
    curve = []
    report = None
    for f in factors:
        report = loadgen.run_load(
            url, args.duration, capacity * f, mix=mix, slo_ms=slo,
            new_tokens=args.new_tokens, prompt_len=args.prompt_len,
            seed=args.seed, arrival=args.arrival)
        inter = report["classes"].get("interactive", {})
        curve.append({
            "factor": f,
            "offered_qps": report["offered_qps"],
            "goodput_rps": round(sum(
                c["goodput_rps"] for c in report["classes"].values()), 3),
            "interactive_slo_attainment": inter.get("slo_attainment"),
            "shed": report["totals"]["shed"],
            "deadline": report["totals"]["deadline"],
            "errors": report["totals"]["error"],
            "p99_ms": report["latency_ms"]["p99"],
        })
    report["calibrated_capacity_rps"] = round(capacity, 3)
    report["overload_factor"] = factors[-1]

    exemplars = _scrape_exemplars(state["url"])
    # the worst (highest-value) exemplar is by construction in the
    # bucket the p99 lives in or above it: THE trace id to pull first
    p99_rid = (max(exemplars, key=lambda e: e["value"])["trace_id"]
               if exemplars else None)

    classes = report["classes"]
    goodput = {c: classes[c]["goodput_rps"] for c in classes}
    goodput["total"] = round(sum(goodput.values()), 3)
    attainment = {c: classes[c]["slo_attainment"] for c in classes}
    shed = {k: report["totals"][k] for k in SHED_TAXONOMY}
    shed["client_dropped"] = report["client_dropped"]
    agg = report["latency_ms"]

    notes = None
    if report["totals"]["error"]:
        notes = (f"{report['totals']['error']} handler error(s); first: "
                 f"{report['first_error']}")
    return {
        "throughput": {"value": goodput["total"], "unit": "req/s",
                       "detail": "aggregate goodput (ok responses / "
                                 "wall time) at overload"},
        "latency_ms": {
            "p50": agg["p50"], "p95": agg["p95"], "p99": agg["p99"],
            "n": agg["n"],
            "exemplars": [{"le": e["le"], "trace_id": e["trace_id"],
                           "value_s": e["value"]} for e in exemplars]},
        "serve": {
            "goodput_rps": goodput,
            "slo_attainment": attainment,
            "shed": shed,
            "per_class": classes,
            "offered_qps": report["offered_qps"],
            "requests": report["requests"],
            "calibrated_capacity_rps": report["calibrated_capacity_rps"],
            "overload_factor": factors[-1],
            "replicas": getattr(args, "replicas", 1),
            "overload_curve": curve,
            "retry_after": report["retry_after"],
            "deadline_rids": report["deadline_rids"],
            "p99_exemplar_rid": p99_rid,
            "seed": args.seed,
            "arrival": args.arrival,
            "trace": args.trace_out,
        },
        "notes": notes,
        "extras": {"loadgen": report},
    }


def _teardown(state) -> None:
    if state is None:
        return
    proc = state["proc"]
    if proc.poll() is None:
        # SIGTERM, not kill: the server's handler unwinds through the
        # trace dump (tools/serve.py --trace-spans contract)
        proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)


def _register():
    from . import Recipe, register
    register(Recipe(
        "serve", "loadgen-driven goodput bench: per-class goodput / SLO "
                 "attainment / shed taxonomy at calibrated overload, "
                 "p99 exemplars cross-linked to the span trace",
        _serve_args, _run, setup=_setup, teardown=_teardown,
        tier="fast"))


_register()
