"""The benchmark observatory: scenario recipes behind one registry.

ROADMAP item 5 ("re-arm the headline benches; gate on goodput"): every
benchmark this repo can run is a *recipe* — a named scenario with its own
argparse surface and a `run()` that returns metric blocks — and every
recipe emits the SAME one-JSON-line trajectory record (schema.py), so
`BENCH_r0N.json` is a multi-scenario artifact and `tools/bench_report.py`
can difference any two rounds with per-metric noise bands.

Recipes (see docs/PERF.md for the catalog + flags):

- `exact`              the headline streamed pipeline bench (img/s,
                       calibrated MFU, fast-numerics + quant-collectives
                       A/Bs beside it) — bench.py's historical record
- `quant_collectives`  standalone int8/int4 ICI-collective A/B (tp >= 2)
- `spmd`               one-process SPMD pipeline via runtime.py
- `dcn`                multi-process loopback DCN pipeline fleet with a
                       merged trace (bubble % + mb latency percentiles)
- `decode`             KV-cache decode tokens/sec (bench_decode.py)
- `train`              pipeline train step img/s (tools/bench_train.py)
- `serve`              loadgen-driven goodput-first serving bench: N x
                       calibrated overload against tools/serve.py, per-
                       class goodput/SLO attainment/shed taxonomy, p99
                       cross-linked to trace exemplars; --overload-
                       factors 1,3,5 sweeps a goodput-vs-offered-load
                       curve in one record
- `serve_kv`           paged-KV serving bench (--kv-pages server):
                       shared-prefix hit rate, page-pool occupancy, and
                       decode p99 with/without a concurrent prefill
                       burst (colocated vs --disaggregate A/B)
- `autoscale`          advise-vs-auto capacity-controller A/B under one
                       seeded load ramp: time-to-scale-up, per-class
                       attainment during the ramp, decision counts

Entry point: `python bench.py --recipe NAME [recipe flags]` (the default
recipe is `exact`, keeping `python bench.py` the headline record).

Lifecycle telemetry: each run emits paired `bench` spans
(`setup:<recipe>` / `run:<recipe>` / `teardown:<recipe>`, PL502-clean)
and counts on `pipeedge_bench_runs_total{recipe,status}` — the full
matrix is pre-declared at registration (PL501), so a dashboard sees
every recipe's series before its first run.
"""
from __future__ import annotations

import argparse
from typing import Callable, Dict, List, Optional

from .. import telemetry
from ..telemetry import metrics as prom
from . import schema

RUN_STATUSES = ("started", "ok", "error")


class Recipe:
    """One benchmark scenario. `setup` builds state (e.g. spawns a
    server), `run` measures and returns schema.BLOCK_KEYS blocks,
    `teardown` releases the state on every path."""

    def __init__(self, name: str, help_text: str,
                 add_args: Callable[[argparse.ArgumentParser], None],
                 run: Callable, setup: Optional[Callable] = None,
                 teardown: Optional[Callable] = None,
                 tier: str = "chip"):
        self.name = name
        self.help = help_text
        self.add_args = add_args
        self.setup = setup
        self.run = run
        self.teardown = teardown
        # "fast": CPU-loopback-capable, CI bench-smoke material;
        # "chip": needs a live accelerator for a meaningful number;
        # "fleet": spawns subprocess fleets
        self.tier = tier

    def parser(self) -> argparse.ArgumentParser:
        p = argparse.ArgumentParser(prog=f"bench.py --recipe {self.name}",
                                    description=self.help)
        self.add_args(p)
        return p


_RECIPES: Dict[str, Recipe] = {}

# recipe x status run counter: declared per-recipe at registration so the
# matrix renders before any recipe ever runs (PL501)
_M_RUNS = prom.REGISTRY.counter(
    "pipeedge_bench_runs_total",
    "benchmark recipe runs by recipe and status "
    "(started / ok / error)")


def register(recipe: Recipe) -> Recipe:
    if recipe.name in _RECIPES:
        raise ValueError(f"recipe already registered: {recipe.name}")
    _RECIPES[recipe.name] = recipe
    for status in RUN_STATUSES:
        _M_RUNS.declare(recipe=recipe.name, status=status)
    return recipe


def get_recipe(name: str) -> Recipe:
    _ensure_loaded()
    try:
        return _RECIPES[name]
    except KeyError:
        raise KeyError(f"unknown recipe {name!r} (available: "
                       f"{', '.join(sorted(_RECIPES))})") from None


def list_recipes() -> List[Recipe]:
    _ensure_loaded()
    return [_RECIPES[k] for k in sorted(_RECIPES)]


_loaded = False


def _ensure_loaded() -> None:
    """Import the recipe modules exactly once (they register on import).
    Deferred so `import pipeedge_tpu.benchkit` stays light — schema
    validation and bench_report never pull jax in."""
    global _loaded  # pylint: disable=global-statement
    if _loaded:
        return
    # flag AFTER the imports succeed: a failed recipe import must
    # re-raise on the next lookup, not leave a silently partial registry
    # (sys.modules caches the modules that DID import, and register()
    # only runs at first import, so a retry never double-registers)
    from . import (autoscale_bench, fleet, headline,  # noqa: F401
                   int8_compute, offline, serve_bench,  # noqa: F401
                   serve_kv_bench)  # noqa: F401
    _loaded = True


def run_recipe(name: str, argv: Optional[List[str]] = None,
               notes: Optional[str] = None) -> dict:
    """Parse `argv` with the recipe's parser, run setup -> run ->
    teardown under paired bench spans, and return the assembled
    trajectory record (NOT printed — the caller owns stdout)."""
    recipe = get_recipe(name)
    args = recipe.parser().parse_args(argv or [])
    config = {k: v for k, v in sorted(vars(args).items())}
    _M_RUNS.inc(recipe=name, status="started")
    state = None
    try:
        if recipe.setup is not None:
            with telemetry.span("bench", f"setup:{name}"):
                state = recipe.setup(args)
        try:
            with telemetry.span("bench", f"run:{name}"):
                blocks = (recipe.run(args) if recipe.setup is None
                          else recipe.run(args, state))
        finally:
            if recipe.teardown is not None:
                with telemetry.span("bench", f"teardown:{name}"):
                    recipe.teardown(state)
    except BaseException:
        _M_RUNS.inc(recipe=name, status="error")
        raise
    _M_RUNS.inc(recipe=name, status="ok")
    if notes:
        existing = blocks.get("notes")
        blocks["notes"] = notes if not existing else f"{existing} {notes}"
    return schema.make_record(name, config, blocks)
