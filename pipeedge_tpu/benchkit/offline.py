"""Offline recipes: decode (KV-cache generation) and train (pipeline
train step), wrapping the existing single-JSON-line benches
(`bench_decode.py`, `tools/bench_train.py`) into the trajectory
envelope. The wrapped tool's full record rides under `extras` (nothing
is lost), while the envelope lifts the headline throughput into the
block bench_report diffs on.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_json_tool(cmd: List[str], timeout: float,
                  env_extra: dict = None) -> dict:
    """Run a tool that prints ONE JSON line (the chaos_dcn idiom) and
    return it parsed — the last parseable `{...}` stdout line wins, so
    warmup chatter above it is harmless."""
    env = dict(os.environ, PYTHONPATH=REPO)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"{cmd[1]} exited {proc.returncode}:\n"
                           f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    raise RuntimeError(f"{cmd[1]} printed no JSON record:\n"
                       f"{proc.stdout[-2000:]}")


def _decode_args(p) -> None:
    p.add_argument("--model", default="gpt2")
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=64)
    p.add_argument("--max-len", type=int, default=1024)
    p.add_argument("--batches", default="1,16")
    p.add_argument("--timeout", type=float, default=1800.0)


def _run_decode(args) -> dict:
    rec = run_json_tool(
        [sys.executable, os.path.join(REPO, "bench_decode.py"),
         "-m", args.model, "--prompt-len", str(args.prompt_len),
         "--new-tokens", str(args.new_tokens),
         "--max-len", str(args.max_len), "--batches", args.batches],
        args.timeout)
    return {
        "throughput": {"value": rec["value"], "unit": rec["unit"]},
        "extras": rec,
    }


def _train_args(p) -> None:
    p.add_argument("--model", default="google/vit-large-patch16-224")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--ubatches", type=int, default=4)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--mixed-precision", action="store_true")
    p.add_argument("--timeout", type=float, default=1800.0)


def _run_train(args) -> dict:
    cmd = [sys.executable, os.path.join(REPO, "tools", "bench_train.py"),
           "-m", args.model, "-b", str(args.batch),
           "-u", str(args.ubatches), "--steps", str(args.steps)]
    if args.mixed_precision:
        cmd.append("--mixed-precision")
    rec = run_json_tool(cmd, args.timeout)
    return {
        "throughput": {"value": rec["value"], "unit": rec["unit"]},
        "extras": rec,
    }


def _register():
    from . import Recipe, register
    register(Recipe(
        "decode", "KV-cache decode throughput (bench_decode.py wrapped "
                  "into the trajectory envelope)",
        _decode_args, _run_decode, tier="chip"))
    register(Recipe(
        "train", "pipeline train-step throughput (tools/bench_train.py "
                 "wrapped into the trajectory envelope)",
        _train_args, _run_train, tier="chip"))


_register()
