"""Trajectory-record schema for the benchmark observatory.

Every recipe in `pipeedge_tpu/benchkit/` emits ONE JSON line in the same
schema-versioned envelope, so `BENCH_*.json` is a multi-scenario artifact
that `tools/bench_report.py` can difference across rounds without knowing
which recipe produced a record. The envelope (docs/PERF.md has the full
field reference):

- `schema`        "pipeedge-bench/v1" — bump on ANY field-shape change;
                  records are only comparable within one schema version
- `scenario`      the recipe name (benchkit registry key)
- `config`        the recipe's resolved parameters (model, sizes, knobs)
- `config_fingerprint`  sha256[:12] of the canonical config JSON — two
                  records compare apples-to-apples iff fingerprints match
                  (bench_report warns, and refuses under --strict-config,
                  otherwise)
- `env`           environment stamp: backend platform, device kind/count,
                  python/jax versions — the "which machine was this"
                  block that explains cross-record drift
- `throughput`    {value, unit, samples, spread} — the headline number
- `latency_ms`    {p50, p95, p99, n, exemplars} — exemplars are
                  `{le, trace_id, value_s}` rows linking a latency bucket
                  to a request trace id (`trace_report --request`)
- `quality`       accuracy-beside-throughput block (top-1 agreement, max
                  abs logit delta) for any non-exact variant
- `mfu`           calibrated + nominal MFU with the pinned calibration
                  recipe version (bench headline recipes only)
- `serve`         per-class goodput_rps / slo_attainment / shed taxonomy
                  (the serve recipe's goodput-first block; with
                  --overload-factors also `overload_curve` — one
                  goodput-vs-offered-load row per swept factor)
- `kv`            the paged-KV serving block (serve_kv recipe): prefix
                  hit rate, pages reused/cached, pool occupancy, and
                  decode p99 with/without a concurrent prefill burst
- `notes`         free-form provenance (e.g. the r05 -> r06 gap record)
- `extras`        recipe-specific raw fields, never gated on

`validate_record` is the machine-checkable contract tests and
bench_report share; `artifact_append` maintains the multi-scenario
`BENCH_r0N.json` artifact (one record per scenario, newest wins).
"""
from __future__ import annotations

import hashlib
import json
import math
import sys
import time
from typing import Dict, List, Optional

SCHEMA = "pipeedge-bench/v1"
ARTIFACT_SCHEMA = "pipeedge-bench-artifact/v1"

# envelope keys a recipe's block dict may fill (everything else it
# returns is an error — keeps records greppable across recipes)
BLOCK_KEYS = ("throughput", "latency_ms", "quality", "mfu", "serve",
              "kv", "notes", "extras", "legacy")


def config_fingerprint(config: dict) -> str:
    """sha256[:12] of the canonical (sorted, compact) config JSON: the
    comparability key — bench_report only trusts a diff between records
    whose fingerprints match."""
    blob = json.dumps(config, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def environment_stamp() -> dict:
    """Which machine/backend produced this record. Imports jax lazily so
    schema validation (tests, bench_report) never initializes a backend."""
    stamp = {"python": sys.version.split()[0]}
    try:
        import jax
        devs = jax.devices()
        stamp.update(platform=jax.default_backend(),
                     device_kind=devs[0].device_kind if devs else None,
                     device_count=len(devs),
                     jax=jax.__version__)
    except Exception as exc:  # noqa: BLE001 — a record without a backend
        stamp.update(platform=None, error=repr(exc))   # is still a record
    return stamp


def make_record(scenario: str, config: dict, blocks: dict,
                env: Optional[dict] = None) -> dict:
    """Assemble the envelope. `blocks` may only use BLOCK_KEYS; the
    `legacy` block (exact headline's pre-benchkit record shape) merges
    into the top level so old consumers keep finding `metric`/`value`."""
    unknown = set(blocks) - set(BLOCK_KEYS)
    if unknown:
        raise ValueError(f"recipe returned unknown block(s): "
                         f"{sorted(unknown)} (allowed: {BLOCK_KEYS})")
    record = {
        "schema": SCHEMA,
        "scenario": scenario,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": dict(config),
        "config_fingerprint": config_fingerprint(config),
        "env": environment_stamp() if env is None else env,
    }
    legacy = blocks.get("legacy") or {}
    for key in BLOCK_KEYS:
        if key == "legacy":
            continue
        val = blocks.get(key)
        if val is not None:
            record[key] = val
    # legacy keys merge at top level but never clobber envelope fields
    for key, val in legacy.items():
        record.setdefault(key, val)
    return record


def _check_pcts(lat: dict, problems: List[str]) -> None:
    pcts = [lat.get(k) for k in ("p50", "p95", "p99")]
    nums = [p for p in pcts if p is not None]
    if any(not isinstance(p, (int, float)) or p < 0 for p in nums):
        problems.append("latency_ms percentiles must be numbers >= 0")
        return
    if nums != sorted(nums):
        problems.append(f"latency_ms percentiles not monotonic: {pcts}")
    for row in lat.get("exemplars", ()):
        if not isinstance(row, dict) or "trace_id" not in row \
                or "le" not in row:
            problems.append(f"malformed exemplar row: {row!r}")


def validate_record(record: dict) -> List[str]:
    """The machine-checkable record contract: a list of problems, empty
    when the record is a valid v1 trajectory line. Shared by
    tests/test_benchkit.py and bench_report's input loading."""
    problems: List[str] = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, not an object"]
    if record.get("schema") != SCHEMA:
        problems.append(f"schema is {record.get('schema')!r}, "
                        f"expected {SCHEMA!r}")
    if not isinstance(record.get("scenario"), str) \
            or not record.get("scenario"):
        problems.append("scenario missing or not a string")
    cfg = record.get("config")
    if not isinstance(cfg, dict):
        problems.append("config missing or not an object")
    else:
        fp = record.get("config_fingerprint")
        if fp != config_fingerprint(cfg):
            problems.append(f"config_fingerprint {fp!r} does not match "
                            "the config block")
    if not isinstance(record.get("env"), dict):
        problems.append("env stamp missing")
    thr = record.get("throughput")
    if thr is not None:
        if not isinstance(thr, dict) or "value" not in thr \
                or "unit" not in thr:
            problems.append("throughput must be {value, unit, ...}")
        elif not isinstance(thr["value"], (int, float)) \
                or not math.isfinite(thr["value"]) or thr["value"] < 0:
            problems.append(f"throughput.value invalid: {thr['value']!r}")
    lat = record.get("latency_ms")
    if lat is not None:
        if not isinstance(lat, dict):
            problems.append("latency_ms must be an object")
        else:
            _check_pcts(lat, problems)
    serve = record.get("serve")
    if serve is not None:
        if not isinstance(serve, dict):
            problems.append("serve must be an object")
        else:
            for key in ("goodput_rps", "slo_attainment"):
                block = serve.get(key)
                if not isinstance(block, dict) or not block:
                    problems.append(f"serve.{key} must be a non-empty "
                                    "per-class object")
            shed = serve.get("shed")
            if shed is not None and not isinstance(shed, dict):
                problems.append("serve.shed must be an object (outcome "
                                "taxonomy counts)")
    quality = record.get("quality")
    if quality is not None:
        agree = quality.get("top1_agreement_vs_exact",
                            quality.get("top1_agreement"))
        if agree is not None and not 0.0 <= float(agree) <= 1.0:
            problems.append(f"quality agreement out of [0, 1]: {agree}")
    return problems


# -- multi-scenario artifact (BENCH_r0N.json) ----------------------------

def artifact_load(path: str) -> dict:
    """Load (or initialize) a multi-scenario artifact."""
    try:
        with open(path, encoding="utf8") as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return {"schema": ARTIFACT_SCHEMA, "records": []}
    if isinstance(doc, dict) and doc.get("schema") == ARTIFACT_SCHEMA:
        return doc
    raise ValueError(f"{path} is not a {ARTIFACT_SCHEMA} artifact")


def artifact_append(path: str, record: dict) -> dict:
    """Append `record` to the artifact at `path` (created when missing),
    replacing any previous record of the same scenario — re-running one
    recipe re-arms that scenario without touching the others."""
    doc = artifact_load(path)
    doc["records"] = [r for r in doc.get("records", ())
                      if r.get("scenario") != record.get("scenario")]
    doc["records"].append(record)
    with open(path, "w", encoding="utf8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc


def records_from_any(doc) -> Dict[str, dict]:
    """{scenario: record} from any accepted input shape: a single v1
    record, a multi-scenario artifact, or a list of records (JSONL loads
    to this). bench_report's one input loader."""
    if isinstance(doc, dict) and doc.get("schema") == SCHEMA:
        return {doc["scenario"]: doc}
    if isinstance(doc, dict) and doc.get("schema") == ARTIFACT_SCHEMA:
        return {r["scenario"]: r for r in doc.get("records", ())}
    if isinstance(doc, list):
        return {r["scenario"]: r for r in doc}
    raise ValueError("unrecognized bench record shape (expected a "
                     f"{SCHEMA} record, a {ARTIFACT_SCHEMA} artifact, "
                     "or a list of records)")
