"""`serve_kv`: the paged-KV serving bench — prefix sharing, page-pool
occupancy, and decode-p99 isolation under concurrent prefill.

Four measured phases against one `tools/serve.py --kv-pages` server
(optionally disaggregated, `--disaggregate local|wire`; optionally
continuous+chunked, `--chunked N`):

1. **prefix burst** — a shared-prefix workload (`loadgen`'s
   `shared:PFX:TOTAL:POOL` prompt distribution): every prompt repeats
   one of POOL deterministic prefixes, so after each prefix's first
   prefill the trie should serve the rest from shared pages. Reported:
   prefix hit rate, pages reused, pool occupancy.
2. **decode solo** — short fixed prompts at a fixed rate: the baseline
   decode p99.
3. **decode + prefill burst** — the SAME short-prompt load while a
   background thread hammers long-prompt requests. The ratio of phase-3
   to phase-2 p99 is the number disaggregation exists to hold down:
   colocated, prefill ticks steal stage-time from decode waves;
   disaggregated, the prefill fleet absorbs them (the A/B in
   docs/evidence/ runs this recipe both ways).
4. **decode + mid-run spike** — the same short-prompt load with
   loadgen's `--burst`: N long prompts launched back-to-back at the
   midpoint. The served latencies inside the spike's blast-radius
   window (`kv.chunked.burst_decode_p99_ms`) are the continuous-
   batching A/B's headline: run `--chunked 0` vs `--chunked N` with
   the same seed — chunked prefill should hold the burst decode p99
   down while goodput/attainment hold.

The record's `kv` block carries all four; `serve`-style goodput/shed
blocks come from phase 1. Gates the CI `kv-serve` smoke cares about:
zero handler errors everywhere, prefix hits > 0.
"""
from __future__ import annotations

import json
import sys
import threading
import urllib.request

from .serve_bench import REPO, _setup as _serve_setup, _teardown


def _args(p) -> None:
    p.add_argument("--model", default="pipeedge/test-tiny-gpt2")
    p.add_argument("--partition", default="1,4,5,8")
    p.add_argument("--max-len", type=int, default=64)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--executor", default="wave",
                   choices=["wave", "stage"])
    p.add_argument("--kv-pages", type=int, default=96,
                   help="page-pool size (tools/serve.py --kv-pages)")
    p.add_argument("--kv-page-size", type=int, default=8)
    p.add_argument("--disaggregate", default="off",
                   choices=["off", "local", "wire", "process"],
                   help="run the prefill fleet split (the A/B against "
                        "'off' is the docs/evidence record); 'process' "
                        "spawns REAL separate prefill worker processes "
                        "over DCN with the lease/ack ship protocol")
    p.add_argument("--prefill-ranks", type=int, default=2,
                   help="worker processes of --disaggregate process")
    p.add_argument("--fault", default="off",
                   choices=["off", "kill-prefill"],
                   help="kill-prefill (needs --disaggregate process): "
                        "run a FOURTH phase — the phase-2 decode load "
                        "while a prefill worker is SIGKILLed mid-window "
                        "— and record the fault window's decode p99, "
                        "goodput, recovery_s (respawn + readmission), "
                        "and pages leaked (the ISSUE 15 robustness A/B)")
    p.add_argument("--chunked", type=int, default=0, metavar="TOKENS",
                   help="serve with --chunked-prefill TOKENS --step-join "
                        "(iteration-level scheduling). The A/B against "
                        "0 — run-to-completion prefill, same seed — is "
                        "the continuous-batching evidence record: the "
                        "phase-4 burst decode p99 should drop while "
                        "goodput/attainment hold")
    p.add_argument("--chunked-budget", type=int, default=0,
                   metavar="TOKENS",
                   help="explicit --prefill-budget for the chunked arm "
                        "(0 = serve.py default: one chunk per tick). "
                        "Raising it past the chunk size keeps short "
                        "steady-state prompts from queueing behind a "
                        "long-prompt spike's chunk stream")
    p.add_argument("--burst-n", type=int, default=3,
                   help="phase-4 spike size (loadgen --burst long "
                        "prompts launched back-to-back mid-run)")
    p.add_argument("--qps", type=float, default=3.0,
                   help="offered rate for every phase (fixed, not "
                        "calibrated: the phases compare against each "
                        "other, so one knob keeps them comparable)")
    p.add_argument("--duration", type=float, default=6.0)
    p.add_argument("--new-tokens", type=int, default=6)
    p.add_argument("--shared-spec", default="shared:16:20:2",
                   help="phase-1 prompt distribution "
                        "(loadgen shared:PFX:TOTAL:POOL)")
    p.add_argument("--short-len", type=int, default=6,
                   help="phase-2/3 decode-load prompt length")
    p.add_argument("--long-len", type=int, default=48,
                   help="phase-3 background prefill-burst prompt length "
                        "(clamped to max_len - new_tokens)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--queue-capacity", type=int, default=32)
    p.add_argument("--max-active", type=int, default=0,
                   help="0 = executor default (page-bounded)")
    p.add_argument("--trace-out", default="bench_serve_kv_trace.json")
    p.add_argument("--postmortem-dir", default=None)
    p.add_argument("--startup-timeout", type=float, default=180.0)
    p.add_argument("--calibrate-s", type=float, default=0.0,
                   help="unused (fixed --qps); kept for arg parity")


def _setup(args) -> dict:
    # reuse the serve recipe's spawn/readiness/teardown machinery with
    # the paged-KV flags appended (one copy of the lifecycle logic)
    class _A:
        pass

    a = _A()
    for k, v in vars(args).items():
        setattr(a, k, v)
    a.overload_factor = 1.0
    if args.fault != "off" and args.disaggregate != "process":
        raise ValueError("--fault kill-prefill needs --disaggregate "
                         "process (there is no worker process to kill "
                         "otherwise)")
    extra = ["--kv-pages", str(args.kv_pages),
             "--kv-page-size", str(args.kv_page_size)]
    if args.disaggregate == "process":
        extra += ["--disaggregate", "process",
                  "--prefill-ranks", str(args.prefill_ranks),
                  "--prefill-lease-timeout", "5",
                  "--prefill-heartbeat-interval", "0.5"]
    elif args.disaggregate != "off":
        extra += ["--disaggregate", args.disaggregate]
    if args.chunked:
        extra += ["--chunked-prefill", str(args.chunked), "--step-join"]
        if args.chunked_budget:
            extra += ["--prefill-budget", str(args.chunked_budget)]
    if args.max_active:
        extra += ["--max-active", str(args.max_active)]
    a.extra_serve_args = extra
    return _serve_setup(a)


def _healthz(url: str) -> dict:
    with urllib.request.urlopen(f"{url}/healthz", timeout=30) as resp:
        return json.loads(resp.read())


def _post(gen_url: str, obj: dict, timeout: float = 120.0):
    req = urllib.request.Request(
        gen_url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _run(args, state) -> dict:
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools import loadgen

    url = state["url"]
    gen_url = f"{url}/generate"
    mix = {"interactive": 1.0}
    slo = dict(loadgen.DEFAULT_SLO_MS)

    # warmup: compile each phase's EXACT (prompt shape x page bucket)
    # programs once so phase p99s measure steady state, not XLA compiles
    # (paged decode compiles per page-count bucket, so new_tokens is
    # part of the shape). A process-mode prefill fleet compiles PER
    # WORKER: repeat each shape across the round-robin with DISTINCT
    # tokens (an identical prompt would hit the trie and never reach
    # the next worker)
    reps = (getattr(args, "prefill_ranks", 1)
            if args.disaggregate == "process" else 1)
    long_len = min(args.long_len, args.max_len - args.new_tokens - 1)
    for n, nt in {(loadgen.spec_max_len(args.shared_spec),
                   args.new_tokens),
                  (args.short_len, args.new_tokens), (long_len, 2),
                  (long_len, args.new_tokens)}:
        for rep in range(reps):
            _post(gen_url, {"ids": [[7 + rep] * n], "new_tokens": nt})

    # -- phase 1: shared-prefix burst --------------------------------
    kv0 = _healthz(url)["serving"]["kv"]
    watch = {"max_in_flight": 0, "min_tokens_free": None}
    watch_stop = threading.Event()

    def sample_admission():
        while not watch_stop.is_set():
            try:
                adm = _healthz(url)["serving"]["admission"]
                watch["max_in_flight"] = max(watch["max_in_flight"],
                                             adm["in_flight"])
                free = adm.get("tokens_free")
                if free is not None:
                    cur = watch["min_tokens_free"]
                    watch["min_tokens_free"] = (free if cur is None
                                                else min(cur, free))
            except OSError:
                pass
            watch_stop.wait(0.1)

    sampler = threading.Thread(target=sample_admission, daemon=True,
                               name="kv-admission-sampler")
    sampler.start()
    try:
        shared = loadgen.run_load(
            gen_url, args.duration, args.qps, mix=mix, slo_ms=slo,
            new_tokens=args.new_tokens, prompt_len=args.shared_spec,
            seed=args.seed, arrival="poisson")
    finally:
        watch_stop.set()
        sampler.join(timeout=30)
    kv1 = _healthz(url)["serving"]["kv"]

    # -- phase 2: decode load, no prefill pressure -------------------
    solo = loadgen.run_load(
        gen_url, args.duration, args.qps, mix=mix, slo_ms=slo,
        new_tokens=args.new_tokens, prompt_len=args.short_len,
        seed=args.seed + 1, arrival="uniform")

    # -- phase 3: same decode load + long-prompt prefill burst -------
    stop = threading.Event()
    burst_errors = [0]

    def prefill_burst():
        i = 0
        while not stop.is_set():
            try:
                _post(gen_url, {"ids": [[(i + j) % 97 for j in
                                         range(long_len)]],
                                "new_tokens": 2, "class": "batch"})
            except Exception:   # noqa: BLE001 — sheds are expected here
                burst_errors[0] += 1
            i += 1

    burster = threading.Thread(target=prefill_burst, daemon=True,
                               name="kv-prefill-burst")
    burster.start()
    try:
        contended = loadgen.run_load(
            gen_url, args.duration, args.qps, mix=mix, slo_ms=slo,
            new_tokens=args.new_tokens, prompt_len=args.short_len,
            seed=args.seed + 2, arrival="uniform")
    finally:
        stop.set()
        burster.join(timeout=120)
    kv2 = _healthz(url)["serving"]["kv"]

    # -- phase 4: decode load + seeded mid-run long-prompt SPIKE -----
    # Unlike phase 3's continuous hammering, this is loadgen's --burst:
    # N long prompts launch back-to-back at the run's midpoint, and the
    # steady-state decode latencies inside the spike's blast-radius
    # window report as burst.during_ms — the number chunked prefill
    # exists to hold down (run-to-completion prefill stalls every
    # decode step behind each long prompt pass; chunked interleaves).
    # Runs in BOTH arms so the --chunked 0 vs N records A/B cleanly.
    spike = loadgen.run_load(
        gen_url, args.duration, args.qps, mix=mix, slo_ms=slo,
        new_tokens=args.new_tokens, prompt_len=args.short_len,
        seed=args.seed + 4, arrival="uniform",
        burst={"at": 0.5, "n": args.burst_n, "len": long_len,
               "window_s": 2.0})
    sched = _healthz(url)["serving"].get("scheduler")

    # -- phase 5 (opt-in): decode load through a prefill-worker kill --
    # the robustness half of the disaggregation A/B (ISSUE 15): the
    # SAME decode load as phase 2, but a prefill worker is SIGKILLed
    # mid-window — the lease protocol must re-dispatch / fall back
    # (zero lost, zero errors), the supervisor must respawn + readmit
    # (recovery_s), and the page pool must close with zero leaks
    fault_block = None
    if args.fault == "kill-prefill":
        import os as os_mod
        import signal as signal_mod
        import threading as threading_mod
        import time as time_mod
        kv_pre = _healthz(url)["serving"]["kv"]
        workers = kv_pre["prefill"]["workers"]
        victim_rank, victim = sorted(workers.items())[0]
        t_kill = [None]
        t_readmit = [None]

        def kill_and_watch():
            # the killer thread ALSO watches for readmission, so a
            # worker that respawns mid-burst gets its true recovery
            # time — polling only after the load window would alias
            # recovery_s to the window length
            time_mod.sleep(min(1.0, args.duration / 4))
            os_mod.kill(victim["pid"], signal_mod.SIGKILL)
            t_kill[0] = time_mod.monotonic()
            deadline = t_kill[0] + args.duration + 60
            seen_down = False       # death detection lags the SIGKILL:
            while time_mod.monotonic() < deadline:   # a full live set
                try:                 # only counts as READMISSION after
                    prefill = _healthz(url)["serving"]["kv"]["prefill"]
                except OSError:      # the rank was observed gone
                    time_mod.sleep(0.3)
                    continue
                if len(prefill["live"]) < len(workers):
                    seen_down = True
                elif seen_down:
                    t_readmit[0] = time_mod.monotonic()
                    return
                time_mod.sleep(0.2)

        kt = threading_mod.Thread(target=kill_and_watch, daemon=True,
                                  name="kv-prefill-killer")
        kt.start()
        faulted = loadgen.run_load(
            gen_url, args.duration, args.qps, mix=mix, slo_ms=slo,
            new_tokens=args.new_tokens, prompt_len=args.short_len,
            seed=args.seed + 3, arrival="uniform")
        kt.join(timeout=args.duration + 90)
        recovery_s = (round(t_readmit[0] - t_kill[0], 3)
                      if t_kill[0] is not None and t_readmit[0] is not None
                      else None)
        kv_after = _healthz(url)["serving"]["kv"]
        # FAULT-WINDOW deltas, not server-lifetime cumulatives — the
        # same discipline the phase-1 prefix stats follow above:
        # leases shipped during warmup/phases 1-3 must not be
        # attributed to the fault window
        lease_delta = {
            k: kv_after["prefill"]["leases"][k]
            - kv_pre["prefill"]["leases"].get(k, 0)
            for k in kv_after["prefill"]["leases"]}
        colo_pre = kv_pre["prefill"].get("colocated") or {}
        colo_delta = {
            k: v - colo_pre.get(k, 0)
            for k, v in (kv_after["prefill"].get("colocated")
                         or {}).items()} or None
        fault_block = {
            "victim_rank": int(victim_rank),
            "decode_p99_ms": faulted["latency_ms"]["p99"],
            "goodput_rps": round(sum(
                c["goodput_rps"]
                for c in faulted["classes"].values()), 3),
            "errors": faulted["totals"]["error"],
            "lost": faulted["client_dropped"],
            "recovery_s": recovery_s,
            "readmitted": recovery_s is not None,
            "leases": lease_delta,
            "colocated": colo_delta,
            "pages_leaked": kv_after["leaked"]
            - kv_pre.get("leaked", 0),
        }

    # PHASE-1 deltas, not server-lifetime cumulatives: the warmup posts
    # (guaranteed misses) and later phases must not dilute the shared-
    # prefix phase's hit rate
    lookups = kv1["prefix"]["lookups"] - kv0["prefix"]["lookups"]
    hits = kv1["prefix"]["hits"] - kv0["prefix"]["hits"]
    hit_rate = None if lookups <= 0 else round(hits / lookups, 4)
    p99_solo = solo["latency_ms"]["p99"]
    p99_contended = contended["latency_ms"]["p99"]
    errors = (shared["totals"]["error"] + solo["totals"]["error"]
              + contended["totals"]["error"] + spike["totals"]["error"]
              + spike["burst"]["error"])
    notes = None
    if errors:
        notes = (f"{errors} handler error(s); first: "
                 f"{shared['first_error'] or solo['first_error'] or contended['first_error'] or spike['first_error'] or spike['burst']['first_error']}")
    goodput = round(sum(c["goodput_rps"]
                        for c in shared["classes"].values()), 3)
    return {
        "throughput": {"value": goodput, "unit": "req/s",
                       "detail": "shared-prefix phase goodput"},
        "latency_ms": {"p50": solo["latency_ms"]["p50"],
                       "p95": solo["latency_ms"]["p95"],
                       "p99": p99_solo, "n": solo["latency_ms"]["n"]},
        "kv": {
            "pages": args.kv_pages, "page_size": args.kv_page_size,
            "disaggregate": args.disaggregate,
            # the token-budget-vs-dense-slots claim in record form: the
            # budget's token capacity, how many max_len dense slots the
            # same memory would be, and the observed concurrency peak
            "token_budget": args.kv_pages * args.kv_page_size,
            "dense_slots_equivalent": (args.kv_pages
                                       * args.kv_page_size)
            // args.max_len,
            "max_in_flight": watch["max_in_flight"],
            "min_tokens_free": watch["min_tokens_free"],
            "prefix_hit_rate": hit_rate,
            "prefix_lookups": lookups,
            "pages_reused_total": kv1["prefix"]["pages_reused_total"],
            "pages_cached": kv1["prefix"]["pages_cached"],
            "pool_occupancy_after": kv2["pool"]["occupancy"],
            "pages_evicted_total": kv2["pool"]["pages_evicted_total"],
            "decode_p99_ms": {"solo": p99_solo,
                              "with_prefill": p99_contended},
            "decode_p99_ratio": (None if not p99_solo or not p99_contended
                                 else round(p99_contended / p99_solo, 3)),
            # the continuous-batching A/B's headline block: chunk
            # config + the spike phase's decode-under-burst latency
            # (--chunked 0 vs N, same seed — docs/SERVING.md)
            "chunked": {
                "chunk_tokens": args.chunked,
                "step_join": bool(args.chunked),
                "prefill_chunks": (None if sched is None
                                   else sched["prefill_chunks"]),
                "burst_n": args.burst_n,
                "burst_prompt_len": long_len,
                "burst_decode_p99_ms": spike["burst"]["during_ms"]["p99"],
                "burst_decode_p50_ms": spike["burst"]["during_ms"]["p50"],
                "burst_window_served": spike["burst"]["during_ms"]["n"],
                "spike_p99_ms": spike["burst"]["latency_ms"]["p99"],
                "goodput_rps": round(sum(
                    c["goodput_rps"]
                    for c in spike["classes"].values()), 3),
                "attainment": spike["classes"]["interactive"]
                ["slo_attainment"],
            },
            "fault": fault_block,
            "shed": {"shared": shared["totals"]["shed"],
                     "solo": solo["totals"]["shed"],
                     "with_prefill": contended["totals"]["shed"]},
            "errors": errors,
        },
        "notes": notes,
        "extras": {"shared": shared, "solo": solo,
                   "contended": contended, "spike": spike},
    }


def _register():
    from . import Recipe, register
    register(Recipe(
        "serve_kv", "paged-KV serving bench: shared-prefix hit rate, "
                    "page-pool occupancy, and decode p99 with/without a "
                    "concurrent prefill burst (colocated vs "
                    "--disaggregate is the docs/evidence A/B)",
        _args, _run, setup=_setup, teardown=_teardown, tier="fast"))


_register()
