"""Pipeline-parallel inference runtime CLI.

Parity with /root/reference/runtime.py (the main application, 605-730),
re-architected for a single-controller JAX/TPU world:

- The reference launches one OS process per rank (`runtime.py RANK WORLDSIZE`)
  and wires them with gloo TCP or TensorPipe RPC. Here ONE controller process
  drives all chips: `rank` must be 0 and `worldsize` becomes the number of
  pipeline stages (devices). There is no network bring-up, no wire protocol,
  and no command plane — the schedule broadcast (CMD_SCHED) and stop
  (CMD_STOP) of the reference (runtime.py:404-452) are plain function calls.
- `--comm spmd` compiles the whole pipeline into one XLA program with
  ppermute edges (block-aligned partitions); `--comm host` drives per-stage
  jit programs with device_put edges and supports arbitrary sublayer cuts
  and runtime-adaptive quantization. `p2p`/`rpc` are accepted as aliases
  for host mode (their capability equivalent).
- Schedule resolution precedence is identical (runtime.py:291-355): manual
  `-pt` partition > single-stage degenerate > native sched-pipeline.
- Monitoring keys, window adaptation via env ADAPTIVE_QUANT /
  SEND_CONSTRAINT / WINDOW_SIZE, result accuracy vs labels or softmax
  confidence (runtime.py:236-257) are preserved.
"""
import argparse
import logging
import os
import queue
import sys
import time
from typing import List, Optional, Tuple

import numpy as np

import monitoring
from pipeedge_tpu.models import get_microbatch_size, registry
from pipeedge_tpu.parallel import pipeline as host_pipeline
from pipeedge_tpu.parallel import spmd
from pipeedge_tpu.sched.scheduler import sched_pipeline
from pipeedge_tpu.utils import data as data_utils
from pipeedge_tpu.utils import quant as quantutil
from pipeedge_tpu.utils.threads import ThreadSafeCounter

logger = logging.getLogger(__name__)

# Env knobs (reference runtime.py:40-52)
ENV_WINDOW_SIZE = "WINDOW_SIZE"
ENV_SEND_CONSTRAINT = "SEND_CONSTRAINT"
ENV_ADAPTIVE_QUANT = "ADAPTIVE_QUANT"
ADAPTIVE_QUANT_HEURISTIC = "HEURISTIC"
ADAPTIVE_QUANT_HEURISTIC2 = "HEURISTIC2"
ADAPTIVE_QUANT_CONTROLLER = "CONTROLLER"

MONITORING_KEY_MODEL = 'shard'
MONITORING_KEY_OUTPUT = 'output'
MONITORING_KEY_QUANT_ENCODE = 'quant_encode'
MONITORING_KEY_QUANT_DECODE = 'quant_decode'
MONITORING_KEY_SEND = 'send'
MONITORING_KEY_RECV = 'recv'

results_counter = ThreadSafeCounter()
label_queue = queue.Queue()


def get_window_size() -> int:
    """Window period for monitoring/adaptation (reference runtime.py:40-44)."""
    return int(os.getenv(ENV_WINDOW_SIZE, "10"))


def handle_results(tensors) -> None:
    """Process result tensors (reference runtime.py:236-257): accuracy from
    labels when available (FIFO order guaranteed here), else softmax
    confidence."""
    outputs = np.asarray(tensors)
    n_items = get_microbatch_size(outputs, verify=True)
    if label_queue.empty():
        exp = np.exp(outputs - outputs.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        acc = float(probs.max(axis=-1).sum())
    else:
        ubatch_labels = label_queue.get()
        assert len(outputs) == len(ubatch_labels)
        pred = outputs.argmax(axis=-1)
        acc = int((pred == np.asarray(ubatch_labels)).sum())
    monitoring.iteration(MONITORING_KEY_OUTPUT, work=n_items, accuracy=acc,
                         safe=False)
    logger.debug("outputs is %s", outputs)
    results_counter.add(n_items)


def parse_yaml_sched(sched: List[dict], hosts: Optional[List[str]]) -> \
        Tuple[List[Tuple[int, int]], List[int]]:
    """Parse the scheduler's YAML into stage_layers + stage_ranks
    (reference runtime.py:260-288). Ranks here are device indices."""
    assert isinstance(sched, list)
    if len(sched) == 0:
        raise RuntimeError("No viable schedule found")
    stage_layers = []
    stage_ranks = []
    # numeric host names round-trip through YAML as ints
    hosts_s = [str(h) for h in hosts] if hosts else None
    for stage in sched:
        assert len(stage) == 1
        for host, layers in stage.items():
            assert len(layers) == 2
            stage_layers.append((int(layers[0]), int(layers[1])))
            if hosts_s:
                try:
                    stage_ranks.append(hosts_s.index(str(host)))
                except ValueError:
                    logger.error("Scheduling: host not in hosts list: %s", host)
                    raise
            else:
                try:
                    stage_ranks.append(int(host))
                except ValueError:
                    logger.error("Scheduling: 'hosts' not specified, failed "
                                 "to parse as device index: %s", host)
                    raise
    return stage_layers, stage_ranks


def get_pipeline_sched(world_size: int, hosts: Optional[List[str]],
                       partition: Optional[List[Tuple[int, int]]],
                       quant: Optional[List[int]],
                       rank_order: Optional[List[int]], model_name: str,
                       microbatch_size: int, s_models_file: Optional[str],
                       s_dev_types_file: Optional[str],
                       s_dev_file: Optional[str]) -> \
        Tuple[List[Tuple[int, int]], List[int], List[int]]:
    """Schedule resolution: manual partition > single-stage degenerate >
    native scheduler (reference runtime.py:291-355)."""
    if partition:
        logger.info("Scheduling: using user-defined partitioning")
        stage_layers = partition
        stage_quant = quant if quant else [0] * len(stage_layers)
        stage_ranks = rank_order if rank_order else list(range(len(stage_layers)))
    elif quant:
        raise RuntimeError("Must specify partition with quantization")
    elif rank_order:
        raise RuntimeError("Must specify partition with rank stage ordering")
    elif world_size <= 1:
        logger.info("Scheduling: single-node execution (degenerate case)")
        stage_layers = [(1, registry.get_model_layers(model_name))]
        stage_quant = [0]
        stage_ranks = [0]
    else:
        logger.info("Scheduling: using scheduler algorithm")
        if hosts and len(hosts) != world_size:
            raise RuntimeError("Specified hosts count != world size")
        sched = sched_pipeline(model_name, 2, 2, microbatch_size,
                               models_file=s_models_file,
                               dev_types_file=s_dev_types_file,
                               dev_file=s_dev_file)
        stage_layers, stage_ranks = parse_yaml_sched(sched, hosts)
        stage_quant = [0] * len(stage_layers)
    logger.info("Scheduling: stage-to-layer mapping: %s", stage_layers)
    logger.info("Scheduling: stage output quantization: %s", stage_quant)
    logger.info("Scheduling: stage-to-device mapping: %s", stage_ranks)
    return stage_layers, stage_quant, stage_ranks


def load_dataset(dataset_cfg: dict, model_name: str, batch_size: int,
                 ubatch_size: int):
    """Load inputs based on model (reference runtime.py:358-401); synthetic
    data replaces network-fetched samples under zero egress."""
    cfg = registry.get_model_config(model_name)
    name = dataset_cfg['name']
    root = dataset_cfg['root']
    split = dataset_cfg['split']
    indices = dataset_cfg['indices']
    shuffle = dataset_cfg['shuffle']
    if name == 'CoLA':
        try:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model_name)
            dataset = data_utils.load_dataset_glue(tokenizer, 'cola', split,
                                                   ubatch_size)
            dataset = data_utils.load_dataset_subset(
                dataset, indices=indices, max_size=batch_size, shuffle=shuffle)
        except Exception as exc:
            logger.warning("CoLA unavailable offline (%s); using synthetic "
                           "token data", exc)
            dataset = data_utils.synthetic_token_dataset(
                batch_size, seq_len=64, vocab_size=cfg.vocab_size or 30522,
                n_labels=max(cfg.num_labels, 2))
    elif name == 'ImageNet':
        try:
            from transformers import AutoImageProcessor
            extractor = AutoImageProcessor.from_pretrained(model_name)
            dataset = data_utils.load_dataset_imagenet(extractor, root or
                                                       'ImageNet', split=split)
            dataset = data_utils.load_dataset_subset(
                dataset, indices=indices, max_size=batch_size, shuffle=shuffle)
        except Exception as exc:
            logger.warning("ImageNet unavailable (%s); using synthetic images",
                           exc)
            dataset = data_utils.synthetic_image_dataset(
                batch_size, shape=(cfg.num_channels, cfg.image_size,
                                   cfg.image_size),
                n_labels=max(cfg.num_labels, 2))
    elif cfg.model_type == 'bert':
        dataset = data_utils.synthetic_token_dataset(
            batch_size, seq_len=64, vocab_size=cfg.vocab_size or 30522,
            n_labels=max(cfg.num_labels, 2))
    else:
        dataset = data_utils.synthetic_image_dataset(
            batch_size, shape=(cfg.num_channels, cfg.image_size, cfg.image_size),
            n_labels=max(cfg.num_labels, 2))
    return dataset


def _make_adaptive_callback(stages, window_size: int):
    """Window-period bitwidth adaptation (reference runtime.py:121-216).

    Runs host-side between microbatches, reading the 'send' monitor window
    and mutating each non-final stage's quant_bit; the host pipeline swaps in
    the pre-compiled program for the chosen bitwidth.
    """
    policy = os.getenv(ENV_ADAPTIVE_QUANT)
    if not policy:
        return None
    rate_constraint = float(os.getenv(ENV_SEND_CONSTRAINT, "0"))
    controllers = {}
    ctl_state = {}

    def callback(i: int, out) -> None:
        tag = i + 1
        if tag % window_size != 0:
            # controller policy counts down its bitwidth1 window split
            if policy == ADAPTIVE_QUANT_CONTROLLER:
                for stage in stages[:-1]:
                    st = ctl_state.get(id(stage))
                    if st:
                        bw1, bw2, it1 = st
                        stage.quant_bit = (bw1 if it1 > 0 else bw2) % max(
                            quantutil.BITWIDTHS)
                        ctl_state[id(stage)] = (bw1, bw2, max(0, it1 - 1))
            return
        with monitoring.get_locked_context(MONITORING_KEY_SEND) as mctx:
            if mctx is None:
                return
            window_perf = mctx.get_window_perf(key=MONITORING_KEY_SEND)
            window_work = mctx.get_window_work(key=MONITORING_KEY_SEND)
            heartrate = mctx.get_window_heartrate(key=MONITORING_KEY_SEND)
        ubatch_size = get_microbatch_size(np.asarray(out))
        for stage in stages[:-1]:
            if policy == ADAPTIVE_QUANT_HEURISTIC:
                # discrete compress-ratio ladder (runtime.py:121-154)
                if rate_constraint > 0:
                    target_time = ubatch_size * window_size / rate_constraint
                else:
                    target_time = float('inf')
                target_datasize = target_time * max(window_perf, 1e-12)
                qbit = stage.quant_bit
                eff = window_work * (32 / qbit if qbit > 0 else 1)
                ratio = int(eff / target_datasize) + 1 if target_datasize > 0 else 1
                for bound, bit in ((1, 0), (2, 16), (4, 8), (5, 6), (8, 4)):
                    if ratio <= bound:
                        stage.quant_bit = bit
                        break
                else:
                    stage.quant_bit = 2
            elif policy == ADAPTIVE_QUANT_HEURISTIC2:
                # analytic largest-feasible bitwidth (runtime.py:156-174)
                if rate_constraint <= 0:
                    continue
                ubatch_time = ubatch_size / rate_constraint
                src_bit = 32
                qbit = quantutil.constrain_max_bitwidth(
                    ubatch_time, max(window_work, 1e-12) / window_size,
                    max(window_perf, 1e-12), src_bit)
                stage.quant_bit = max(2, qbit) % src_bit
            elif policy == ADAPTIVE_QUANT_CONTROLLER:
                # Kalman/integral controller window split (runtime.py:177-216)
                if id(stage) not in controllers:
                    bw_start = stage.quant_bit or max(quantutil.BITWIDTHS)
                    controllers[id(stage)] = \
                        quantutil.AdaptiveBitwidthPerformanceController(
                            rate_constraint, quantutil.BITWIDTHS, bw_start)
                ctl = controllers[id(stage)]
                ctl.reference = rate_constraint
                send_rate = heartrate * ubatch_size
                bw1, bw2, it1 = ctl(send_rate, window_size)
                ctl_state[id(stage)] = (bw1, bw2, it1)
                stage.quant_bit = (bw1 if it1 > 0 else bw2) % max(
                    quantutil.BITWIDTHS)
            logger.info("Adaptive quantization (%s): bitwidth=%d", policy,
                        stage.quant_bit)

    return callback


def run_pipeline_host(args, stage_layers, stage_quant, stage_ranks,
                      ubatches, labels) -> None:
    """Host-driven pipeline (arbitrary cut points, adaptive quantization)."""
    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    dtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32
    pipe = host_pipeline.build_pipeline(
        args.model_name, stage_layers, model_file=args.model_file,
        devices=[devices[r % len(devices)] for r in stage_ranks],
        quant_bits=stage_quant, dtype=dtype)
    window_size = get_window_size()
    adaptive = _make_adaptive_callback(pipe.stages, window_size)

    for lb in labels:
        label_queue.put(lb)

    def on_result(i, out):
        # send monitor: wire bytes of the quantized edge payloads (Mbits),
        # the reference's p2p_post_hook_monitor semantics (runtime.py:219-230)
        mbits = sum(np.asarray(t).nbytes for t in
                    (out if isinstance(out, tuple) else (out,))) * 8 / 1e6
        monitoring.iteration(MONITORING_KEY_SEND, work=mbits, safe=False)
        handle_results(out)
        if adaptive is not None:
            adaptive(i, out)

    pipe.ubatch_callback = on_result
    tik = time.monotonic()
    _, stats = pipe.run([jnp.asarray(u, dtype=dtype if u.dtype.kind == 'f'
                                     else None) for u in ubatches])
    tok = time.monotonic()
    _report(tik, tok, ubatches)


def run_pipeline_spmd(args, stage_layers, stage_quant, ubatches, labels) -> None:
    """SPMD pipeline: one XLA program, ppermute edges (block-aligned)."""
    import jax
    import jax.numpy as jnp

    entry = registry.get_model_entry(args.model_name)
    dtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32
    total = registry.get_model_layers(args.model_name)
    stage_params = []
    for i, (l, r) in enumerate(stage_layers):
        _, params, _ = registry.module_shard_factory(
            args.model_name, args.model_file, l, r, stage=i, dtype=dtype)
        stage_params.append(params)
    mesh = spmd.make_pipeline_mesh(len(stage_layers))
    quant_bit = stage_quant[0] if stage_quant else 0
    pipe = spmd.build_spmd_pipeline(entry.family.FAMILY, entry.config,
                                    stage_layers, stage_params, mesh,
                                    quant_bit=quant_bit)
    for lb in labels:
        label_queue.put(lb)
    inputs = jnp.asarray(np.stack(ubatches),
                         dtype=dtype if ubatches[0].dtype.kind == 'f' else None)
    pipe.run(inputs)  # compile + warmup
    tik = time.monotonic()
    outputs = np.asarray(pipe.run(inputs))
    tok = time.monotonic()
    for out in outputs:
        handle_results(out)
    _report(tik, tok, ubatches)


def _report(tik, tok, ubatches):
    batch_size = sum(len(u) for u in ubatches)
    latency = tok - tik
    throughput = batch_size / latency if latency > 0 else 0
    logger.info("Latency: %f seconds", latency)
    logger.info("Throughput: %f items/sec", throughput)
    print(f"latency_sec={latency:.6f} throughput_items_sec={throughput:.3f}")


def main():
    parser = argparse.ArgumentParser(
        description="Pipeline-parallel inference runtime (TPU-native)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("rank", type=int, help="must be 0 (single controller)")
    parser.add_argument("worldsize", type=int,
                        help="number of pipeline stages (devices)")
    parser.add_argument("-c", "--comm", type=str, default="host",
                        choices=["host", "spmd", "p2p", "rpc"],
                        help="pipeline driver; p2p/rpc are host aliases")
    parser.add_argument("-m", "--model-name", type=str,
                        default="google/vit-base-patch16-224",
                        choices=registry.get_model_names())
    parser.add_argument("-M", "--model-file", type=str,
                        help="model weights file (.npz)")
    parser.add_argument("-b", "--batch-size", default=64, type=int)
    parser.add_argument("-u", "--ubatch-size", default=8, type=int)
    parser.add_argument("-t", "--dtype", default="float32",
                        choices=["float32", "bfloat16"])
    # scheduling (reference runtime.py:657-687)
    parser.add_argument("-pt", "--partition", type=str,
                        help="comma-delimited layer pairs, e.g. '1,24,25,48'")
    parser.add_argument("-q", "--quant", type=str,
                        help="comma-delimited per-stage output quant bitwidths")
    parser.add_argument("-r", "--rank-order", type=str, default=None,
                        help="comma-delimited stage-to-device mapping")
    parser.add_argument("-D", "--data-rank", type=int, default=0,
                        help="accepted for compatibility; single-controller "
                             "runtime always drives from the host")
    parser.add_argument("-sm", "--sched-models-file", default=None, type=str)
    parser.add_argument("-sdt", "--sched-dev-types-file", default=None, type=str)
    parser.add_argument("-sd", "--sched-dev-file", default=None, type=str)
    parser.add_argument("-H", "--hosts", type=str,
                        help="comma-delimited hosts/chips for schedule mapping")
    # dataset (reference runtime.py:688-705)
    parser.add_argument("--dataset-name", type=str, default="synthetic",
                        choices=["synthetic", "ImageNet", "CoLA"])
    parser.add_argument("--dataset-root", type=str)
    parser.add_argument("--dataset-split", default='val', type=str)
    parser.add_argument("--dataset-indices-tsv", type=str,
                        help="TSV file with dataset indices to use")
    parser.add_argument("--dataset-shuffle", action="store_true")
    args = parser.parse_args()

    if args.rank != 0:
        logger.warning("Single-controller runtime: only rank 0 runs; "
                       "rank %d exits immediately (all devices are driven "
                       "from rank 0)", args.rank)
        return

    partition = None
    if args.partition:
        nums = [int(x) for x in args.partition.split(',')]
        assert len(nums) % 2 == 0
        partition = list(zip(nums[::2], nums[1::2]))
    quant = [int(x) for x in args.quant.split(',')] if args.quant else None
    rank_order = [int(x) for x in args.rank_order.split(',')] \
        if args.rank_order else None
    hosts = args.hosts.split(',') if args.hosts else None
    indices = None
    if args.dataset_indices_tsv:
        with open(args.dataset_indices_tsv) as f:
            indices = [int(line.split('\t')[0]) for line in f if line.strip()]

    stage_layers, stage_quant, stage_ranks = get_pipeline_sched(
        args.worldsize, hosts, partition, quant, rank_order, args.model_name,
        args.ubatch_size, args.sched_models_file, args.sched_dev_types_file,
        args.sched_dev_file)

    dataset = load_dataset(
        {'name': args.dataset_name, 'root': args.dataset_root,
         'split': args.dataset_split, 'indices': indices,
         'shuffle': args.dataset_shuffle},
        args.model_name, args.batch_size, args.ubatch_size)
    ubatches, labels = [], []
    for inputs, lbls in data_utils.batch_dataset(dataset, args.ubatch_size):
        ubatches.append(inputs)
        labels.append(lbls)

    window_size = get_window_size()
    monitoring.init(MONITORING_KEY_MODEL, window_size, work_type='items',
                    acc_type='layers')
    monitoring.add_key(MONITORING_KEY_OUTPUT, work_type='classifications',
                       acc_type='correct')
    monitoring.add_key(MONITORING_KEY_SEND, work_type='Mbits')
    monitoring.add_key(MONITORING_KEY_RECV, work_type='Mbits')
    monitoring.add_key(MONITORING_KEY_QUANT_ENCODE, acc_type='bits')
    monitoring.add_key(MONITORING_KEY_QUANT_DECODE, acc_type='bits')

    try:
        comm = args.comm
        if comm in ("p2p", "rpc"):
            comm = "host"
        if comm == "spmd":
            try:
                spmd.partition_to_blocks(stage_layers)
            except ValueError as exc:
                logger.warning("%s; falling back to host driver", exc)
                comm = "host"
        if comm == "spmd":
            run_pipeline_spmd(args, stage_layers, stage_quant, ubatches, labels)
        else:
            run_pipeline_host(args, stage_layers, stage_quant, stage_ranks,
                              ubatches, labels)
        assert results_counter.wait_gte(
            sum(len(u) for u in ubatches), timeout=300)
    finally:
        monitoring.finish()


if __name__ == "__main__":
    logging.basicConfig(
        level=logging.INFO,
        handlers=[logging.StreamHandler(sys.stdout),
                  logging.FileHandler("runtime.log", mode='a')])
    main()
