"""Pipeline-parallel inference runtime CLI.

Parity with /root/reference/runtime.py (the main application, 605-730),
re-architected for a single-controller JAX/TPU world:

- The reference launches one OS process per rank (`runtime.py RANK WORLDSIZE`)
  and wires them with gloo TCP or TensorPipe RPC. Here ONE controller process
  drives all chips: `rank` must be 0 and `worldsize` becomes the number of
  pipeline stages (devices). There is no network bring-up, no wire protocol,
  and no command plane — the schedule broadcast (CMD_SCHED) and stop
  (CMD_STOP) of the reference (runtime.py:404-452) are plain function calls.
- `--comm spmd` compiles the whole pipeline into one XLA program with
  ppermute edges (block-aligned partitions); `--comm host` drives per-stage
  jit programs with device_put edges and supports arbitrary sublayer cuts
  and runtime-adaptive quantization. `p2p`/`rpc` are accepted as aliases
  for host mode (their capability equivalent).
- Schedule resolution precedence is identical (runtime.py:291-355): manual
  `-pt` partition > single-stage degenerate > native sched-pipeline.
- Monitoring keys, window adaptation via env ADAPTIVE_QUANT /
  SEND_CONSTRAINT / WINDOW_SIZE, result accuracy vs labels or softmax
  confidence (runtime.py:236-257) are preserved.
"""
import argparse
import logging
import os
import queue
import sys
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

import monitoring
from pipeedge_tpu import telemetry
from pipeedge_tpu.comm import CMD_ADMIT, CMD_DEAD, CMD_SCHED, CMD_STOP
from pipeedge_tpu.health import guard as nan_guard
from pipeedge_tpu.telemetry import flight
from pipeedge_tpu.telemetry import metrics as prom
from pipeedge_tpu.models import get_microbatch_size, registry
from pipeedge_tpu.parallel import pipeline as host_pipeline
from pipeedge_tpu.parallel import spmd
from pipeedge_tpu.sched.scheduler import sched_pipeline
from pipeedge_tpu.utils import data as data_utils
from pipeedge_tpu.utils import quant as quantutil
from pipeedge_tpu.utils.threads import ThreadSafeCounter, make_lock

logger = logging.getLogger(__name__)

# Env knobs (reference runtime.py:40-52)
ENV_WINDOW_SIZE = "WINDOW_SIZE"
ENV_SEND_CONSTRAINT = "SEND_CONSTRAINT"
ENV_ADAPTIVE_QUANT = "ADAPTIVE_QUANT"
ADAPTIVE_QUANT_HEURISTIC = "HEURISTIC"
ADAPTIVE_QUANT_HEURISTIC2 = "HEURISTIC2"
ADAPTIVE_QUANT_CONTROLLER = "CONTROLLER"

MONITORING_KEY_MODEL = 'shard'
MONITORING_KEY_OUTPUT = 'output'
MONITORING_KEY_QUANT_ENCODE = 'quant_encode'
MONITORING_KEY_QUANT_DECODE = 'quant_decode'
MONITORING_KEY_SEND = 'send'
MONITORING_KEY_RECV = 'recv'
# liveness plane: one beat per received DCN heartbeat frame (accuracy
# column = sender rank), so the post-mortem CSV shows exactly when each
# peer's beats stopped
MONITORING_KEY_LIVENESS = 'liveness'
# heartbeat RTT: one row per completed beat round trip (work = rtt ms,
# accuracy column = peer rank) — beats prove liveness, these prove the
# command plane is still FAST; the monitoring snapshot and hb_rtt.csv
# carry the same series /metrics exports as pipeedge_heartbeat_rtt_ms
MONITORING_KEY_HB_RTT = 'hb_rtt'

results_counter = ThreadSafeCounter(name="runtime.results")
label_queue = queue.Queue()
# multi-process (dcn) command state (reference runtime.py:400-415)
stop_event = threading.Event()
sched_q = queue.Queue()
# why the fleet stopped: a CMD_STOP carrying a rank id means that rank died
# mid-run (peer-death protocol, beyond the reference's acknowledged
# non-fault-tolerance at rpc/__init__.py:83-86); None = clean stop
stop_info: List[Optional[int]] = [None]
# cumulative CMD_STOP count: round r of a multi-schedule run ends at the
# (r+1)-th stop, so a stop that lands while a worker is still tearing down
# the previous round is counted, not lost (stop_event alone would race)
stop_counter = ThreadSafeCounter(name="runtime.stops")
# set once the fleet is tearing down cleanly (empty CMD_SCHED sent/received):
# from then on, dropped connections are expected, not peer deaths
fleet_shutdown = threading.Event()
# failover mode state (--on-peer-death failover): ranks announced dead via
# CMD_DEAD or observed locally; deaths accumulate for the whole run
dead_ranks: set = set()
dead_lock = make_lock("runtime.dead")
# rejoined-but-not-healed ranks (guarded by dead_lock): alive spare
# capacity that must NOT silently reclaim its old stage at the next
# round's failover re-plan. --on-peer-rejoin spare keeps ranks here;
# heal clears the bench at the round boundary that restores capacity.
benched_ranks: set = set()
# gray-quarantined ranks (guarded by dead_lock): alive but benched by
# the peer-health plane (--on-peer-degraded quarantine) because their
# EWMA degradation score confirmed a straggler. Kept SEPARATE from
# benched_ranks so a rejoin heal clearing the bench can never silently
# readmit a quarantined straggler; only probation readmission
# (pipeedge_tpu/health/scorer.py) removes entries here.
quarantined_ranks: set = set()
# capacity-benched ranks (guarded by dead_lock): alive ranks the
# capacity controller (--autoscale-ranks, serving/autoscale.py) parked
# as spares because the pipeline is over-provisioned. Kept SEPARATE
# from benched_ranks/quarantined_ranks so a rejoin heal or a health
# readmission can never silently re-seat a capacity decision; only the
# controller's own scale-up (plan_rejoin onto idle survivors) removes
# entries here.
autoscaled_ranks: set = set()
# a death landed mid-round: the data rank ends the round, re-schedules over
# the survivors, and replays the unacknowledged microbatches
failover_event = threading.Event()
# elastic membership (--on-peer-rejoin): a confirmed-dead rank passed the
# JOIN admission handshake and is live again. The handler removes it from
# dead_ranks; `_heal_state` carries what the data rank's round loop needs
# to close the capacity loop at the next boundary (docs/FAULT_TOLERANCE.md
# rank lifecycle: alive -> grace -> dead -> rejoining -> spare/healed).
_heal_state: dict = {
    "detect_ns": None,    # first death detection of the open episode
    "rejoin_ns": None,    # admission stamp of the most recent rejoin
    "pre_failure": None,  # schedule running when the episode's death hit
    "pending": False,     # a heal should be attempted at the boundary
}
# optional result capture (--save-results): handle_results appends every
# delivered output here so runs can be compared bit-for-bit
_results_sink: Optional[list] = None
# failover telemetry: monotonic_ns stamps of each death detection, consumed
# by the data rank's recovery span (detection -> replay-round completion)
_failover_detect_ns: List[int] = []

# /metrics plane (pipeedge_tpu/telemetry/metrics.py): the DCN transport
# hooks feed these; tools/serve.py renders the same registry
_WIRE_BYTES = prom.REGISTRY.counter(
    "pipeedge_edge_wire_bytes_total",
    "bytes moved over DCN pipeline edges, by direction and peer rank")
_EDGE_BITS = prom.REGISTRY.gauge(
    "pipeedge_edge_bits",
    "negotiated wire bitwidth per DCN edge (0 = uncompressed)")
_EDGE_PATH = prom.REGISTRY.gauge(
    "pipeedge_edge_path",
    "negotiated transport tier per DCN edge "
    "(0 = socket_v2, 1 = zerocopy, 2 = local hand-off)")
_LEDGER_SNAPSHOTS = prom.REGISTRY.counter(
    "pipeedge_ledger_snapshots_total",
    "microbatch-ledger snapshots taken (bounds failover replay state)")
_HEARTBEATS_RX = prom.REGISTRY.counter(
    "pipeedge_heartbeats_received_total",
    "liveness-plane heartbeat frames received, by sender rank")
_FAILOVER_EVENTS = prom.REGISTRY.counter(
    "pipeedge_failover_events_total",
    "mid-run peer deaths entering the failover path")
_PEER_DEATHS = prom.REGISTRY.counter(
    "pipeedge_peer_deaths_total", "peer deaths observed (any mode)")
_REBALANCE_EVENTS = prom.REGISTRY.counter(
    "pipeedge_rebalance_events_total",
    "accepted telemetry-driven partition rebalances (--rebalance auto)")
_REJOINS = prom.REGISTRY.counter(
    "pipeedge_rejoins_total",
    "peers re-admitted through the JOIN handshake after a confirmed death")
_TTFC = prom.REGISTRY.gauge(
    "pipeedge_time_to_full_capacity_seconds",
    "latest heal episode: first death detection -> partition healed back "
    "to full capacity at a round boundary")
# gray-failure plane (docs/FAULT_TOLERANCE.md): the heartbeat RTT
# percentiles the peer-health scorer reads (q = p50 | p99). The frame-
# integrity counter (pipeedge_frames_corrupt_total) lives with its
# verification site in comm/dcn.py (`dcn.FRAMES_CORRUPT`).
_HB_RTT = prom.REGISTRY.gauge(
    "pipeedge_heartbeat_rtt_ms",
    "heartbeat round-trip percentiles per peer over the bounded sample "
    "window (q = p50 | p99)")


def _declare_fleet_metric_labels(world_size: int, rank: int) -> None:
    """Pre-declare the per-peer label matrices (pipelint PL501): the
    fleet's membership fixes every (direction, peer) series up front, so
    scrapers see the full matrix at 0 instead of series appearing at
    first increment."""
    for r in range(world_size):
        if r == rank:
            continue
        _HEARTBEATS_RX.declare(src=str(r))
        _PEER_DEATHS.declare(peer=str(r))
        _REJOINS.declare(peer=str(r))
        for key in (MONITORING_KEY_SEND, MONITORING_KEY_RECV):
            _WIRE_BYTES.declare(direction=key, peer=str(r))


def handle_cmd(cmd: int, tensors: Tuple) -> None:
    """Process a command (reference runtime.py:404-415)."""
    if cmd == CMD_STOP:
        logger.info("handle_cmd: stop")
        if tensors:
            stop_info[0] = int(np.asarray(tensors[0]))
            monitoring.flush()   # post-mortem CSVs must survive the abort
        stop_counter.add(1)
        stop_event.set()
    elif cmd == CMD_SCHED:
        logger.info("handle_cmd: sched")
        # pair the schedule with the stop count at its ARRIVAL (commands
        # from the data rank ride one connection, so this round's stop is
        # guaranteed not yet counted): the worker's round ends at base+1.
        # Relative counting is what lets a REJOINED worker — who missed
        # every earlier round's stop — fall straight into the sequence.
        sched_q.put((stop_counter.value, tensors))
    elif cmd == CMD_ADMIT:
        # the admission ack: purely informational on the worker — its
        # next CMD_SCHED carries everything it needs (global round index,
        # stop baseline); the log line is the operator's confirmation
        rnd_now = int(np.asarray(tensors[0])) if tensors else -1
        logger.warning("handle_cmd: re-admitted into the fleet "
                       "(current round %d)", rnd_now)
    elif cmd == CMD_DEAD:
        dead = int(np.asarray(tensors[0]))
        logger.warning("handle_cmd: rank %d announced dead (failover)", dead)
        with dead_lock:
            known = dead in dead_ranks
            dead_ranks.add(dead)
        if not known:
            # every survivor may broadcast the same death; count the EVENT
            # once and stamp detection once, or the failover metrics/spans
            # multiply by the fleet size
            _record_failover_detect(dead)
        failover_event.set()
        monitoring.flush()
    else:
        logger.warning("handle_cmd: Unknown command: %s", cmd)


def _record_failover_detect(dead: int, failover: bool = True) -> None:
    """First-observation bookkeeping for a peer death: one detect span,
    one detection stamp (the recovery span's start), one death count —
    callers dedupe against dead_ranks (or stop_info) before calling.
    `failover=False` (abort path) skips the failover-event counter."""
    now = time.monotonic_ns()
    telemetry.record("failover", "detect", now, now)
    flight.note("peer_death", dead_rank=dead, failover=failover)
    _failover_detect_ns.append(now)
    if _heal_state["detect_ns"] is None:
        # anchor of the time-to-full-capacity clock: the FIRST detection
        # of the episode a later heal closes
        _heal_state["detect_ns"] = now
    _PEER_DEATHS.inc(peer=str(dead))
    if failover:
        _FAILOVER_EVENTS.inc()


def get_window_size() -> int:
    """Window period for monitoring/adaptation (reference runtime.py:40-44)."""
    return int(os.getenv(ENV_WINDOW_SIZE, "10"))


def handle_results(tensors) -> None:
    """Process result tensors (reference runtime.py:236-257): accuracy from
    labels when available (FIFO order guaranteed here), else softmax
    confidence."""
    outputs = np.asarray(tensors)
    n_items = get_microbatch_size(outputs, verify=True)
    # class labels only apply to [B, n_classes] outputs; per-token logits
    # (causal LMs, [B, S, vocab]) fall back to softmax confidence. Pop the
    # label queue either way so it stays in sync with the microbatch stream.
    ubatch_labels = None if label_queue.empty() else label_queue.get()
    if ubatch_labels is not None and outputs.ndim == 2:
        assert len(outputs) == len(ubatch_labels)
        pred = outputs.argmax(axis=-1)
        acc = int((pred == np.asarray(ubatch_labels)).sum())
    else:
        exp = np.exp(outputs - outputs.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        conf = probs.max(axis=-1)   # [B] or [B, S]
        acc = float(conf.reshape(conf.shape[0], -1).mean(axis=1).sum())
    monitoring.iteration(MONITORING_KEY_OUTPUT, work=n_items, accuracy=acc,
                         safe=False)
    logger.debug("outputs is %s", outputs)
    if _results_sink is not None:
        _results_sink.append(outputs)
    results_counter.add(n_items)


def parse_yaml_sched(sched: List[dict], hosts: Optional[List[str]]) -> \
        Tuple[List[Tuple[int, int]], List[int]]:
    """Parse the scheduler's YAML into stage_layers + stage_ranks
    (reference runtime.py:260-288). Ranks here are device indices."""
    assert isinstance(sched, list)
    if len(sched) == 0:
        raise RuntimeError("No viable schedule found")
    stage_layers = []
    stage_ranks = []
    # numeric host names round-trip through YAML as ints
    hosts_s = [str(h) for h in hosts] if hosts else None
    for stage in sched:
        assert len(stage) == 1
        for host, layers in stage.items():
            assert len(layers) == 2
            stage_layers.append((int(layers[0]), int(layers[1])))
            if hosts_s:
                try:
                    stage_ranks.append(hosts_s.index(str(host)))
                except ValueError:
                    logger.error("Scheduling: host not in hosts list: %s", host)
                    raise
            else:
                try:
                    stage_ranks.append(int(host))
                except ValueError:
                    logger.error("Scheduling: 'hosts' not specified, failed "
                                 "to parse as device index: %s", host)
                    raise
    return stage_layers, stage_ranks


def get_pipeline_sched(world_size: int, hosts: Optional[List[str]],
                       partition: Optional[List[Tuple[int, int]]],
                       quant: Optional[List[int]],
                       rank_order: Optional[List[int]], model_name: str,
                       microbatch_size: int, s_models_file: Optional[str],
                       s_dev_types_file: Optional[str],
                       s_dev_file: Optional[str],
                       dtype: str = 'float32') -> \
        Tuple[List[Tuple[int, int]], List[int], List[int]]:
    """Schedule resolution: manual partition > single-stage degenerate >
    native scheduler (reference runtime.py:291-355)."""
    if partition:
        logger.info("Scheduling: using user-defined partitioning")
        # reject out-of-range/non-contiguous -pt up front: an oversized
        # partition otherwise marks an interior stage is_last (its r ==
        # model total), whose classifier logits then feed the next stage
        # and fail with an unrelated broadcast error deep in layer_norm
        from pipeedge_tpu.parallel.decode import validate_partition
        total = registry.get_model_layers(model_name)
        try:
            validate_partition(partition, total)
        except ValueError as exc:
            raise RuntimeError(
                f"-pt: {exc} ({model_name} has {total} sublayers)") from exc
        stage_layers = partition
        stage_quant = quant if quant else [0] * len(stage_layers)
        stage_ranks = rank_order if rank_order else list(range(len(stage_layers)))
    elif quant:
        raise RuntimeError("Must specify partition with quantization")
    elif rank_order:
        raise RuntimeError("Must specify partition with rank stage ordering")
    elif world_size <= 1:
        logger.info("Scheduling: single-node execution (degenerate case)")
        stage_layers = [(1, registry.get_model_layers(model_name))]
        stage_quant = [0]
        stage_ranks = [0]
    else:
        logger.info("Scheduling: using scheduler algorithm")
        if hosts and len(hosts) != world_size:
            raise RuntimeError("Specified hosts count != world size")
        # dtype must match the profile records' dtype key (the scheduler
        # selects the model profile by exact (dtype, batch_size) match,
        # native/sched_pipeline_main.cpp:135) — chip profiles are bfloat16
        sched = sched_pipeline(model_name, 2, 2, microbatch_size,
                               dtype=dtype,
                               models_file=s_models_file,
                               dev_types_file=s_dev_types_file,
                               dev_file=s_dev_file)
        stage_layers, stage_ranks = parse_yaml_sched(sched, hosts)
        stage_quant = [0] * len(stage_layers)
    logger.info("Scheduling: stage-to-layer mapping: %s", stage_layers)
    logger.info("Scheduling: stage output quantization: %s", stage_quant)
    logger.info("Scheduling: stage-to-device mapping: %s", stage_ranks)
    return stage_layers, stage_quant, stage_ranks


def load_dataset(dataset_cfg: dict, model_name: str, batch_size: int,
                 ubatch_size: int):
    """Load inputs based on model (reference runtime.py:358-401); synthetic
    data replaces network-fetched samples under zero egress."""
    cfg = registry.get_model_config(model_name)
    name = dataset_cfg['name']
    root = dataset_cfg['root']
    split = dataset_cfg['split']
    indices = dataset_cfg['indices']
    shuffle = dataset_cfg['shuffle']
    if name == 'CoLA':
        try:
            from transformers import AutoTokenizer
            tokenizer = AutoTokenizer.from_pretrained(model_name)
            dataset = data_utils.load_dataset_glue(tokenizer, 'cola', split,
                                                   ubatch_size)
            dataset = data_utils.load_dataset_subset(
                dataset, indices=indices, max_size=batch_size, shuffle=shuffle)
        except Exception as exc:
            logger.warning("CoLA unavailable offline (%s); using synthetic "
                           "token data", exc)
            dataset = data_utils.synthetic_token_dataset(
                batch_size, seq_len=64, vocab_size=cfg.vocab_size or 30522,
                n_labels=max(cfg.num_labels, 2))
    elif name == 'ImageNet':
        try:
            from transformers import AutoImageProcessor
            extractor = AutoImageProcessor.from_pretrained(model_name)
            dataset = data_utils.load_dataset_imagenet(extractor, root or
                                                       'ImageNet', split=split)
            dataset = data_utils.load_dataset_subset(
                dataset, indices=indices, max_size=batch_size, shuffle=shuffle)
        except Exception as exc:
            logger.warning("ImageNet unavailable (%s); using synthetic images",
                           exc)
            dataset = data_utils.synthetic_image_dataset(
                batch_size, shape=(cfg.num_channels, cfg.image_size,
                                   cfg.image_size),
                n_labels=max(cfg.num_labels, 2))
    elif cfg.vocab_size:  # token models: BERT and GPT-2
        dataset = data_utils.synthetic_token_dataset(
            batch_size, seq_len=min(64, cfg.max_position_embeddings or 64),
            vocab_size=cfg.vocab_size, n_labels=max(cfg.num_labels, 2))
    else:
        dataset = data_utils.synthetic_image_dataset(
            batch_size, shape=(cfg.num_channels, cfg.image_size, cfg.image_size),
            n_labels=max(cfg.num_labels, 2))
    return dataset


def _make_adaptive_callback(edge_stages, window_size: int, edge_keys=None):
    """Window-period bitwidth adaptation (reference runtime.py:121-216).

    `edge_stages` are the stages whose *output* edge is adaptive (i.e. all but
    the final stage); each must expose a mutable `quant_bit`. `edge_keys[i]`
    names the monitoring key carrying stage i's edge telemetry (wire Mbits
    per microbatch) — per-edge windows, so each stage adapts on its OWN
    edge's measured traffic, exactly as each reference rank reads its own
    local 'send' window (reference runtime.py:123-127). Default: every stage
    reads MONITORING_KEY_SEND (the per-process key — correct for a DCN rank,
    which owns exactly one edge).
    """
    policy = os.getenv(ENV_ADAPTIVE_QUANT)
    if not policy:
        return None
    if edge_keys is None:
        edge_keys = [MONITORING_KEY_SEND] * len(edge_stages)
    rate_constraint = float(os.getenv(ENV_SEND_CONSTRAINT, "0"))
    controllers = {}
    ctl_state = {}

    def callback(i: int, out) -> None:
        tag = i + 1
        if tag % window_size != 0:
            # controller policy counts down its bitwidth1 window split
            if policy == ADAPTIVE_QUANT_CONTROLLER:
                for stage in edge_stages:
                    st = ctl_state.get(id(stage))
                    if st:
                        bw1, bw2, it1 = st
                        stage.quant_bit = (bw1 if it1 > 0 else bw2) % max(
                            quantutil.BITWIDTHS)
                        ctl_state[id(stage)] = (bw1, bw2, max(0, it1 - 1))
            return
        out_arr = np.asarray(out[0] if isinstance(out, tuple) else out)
        ubatch_size = get_microbatch_size(out_arr)
        for stage_idx, stage in enumerate(edge_stages):
            key = edge_keys[stage_idx]
            with monitoring.get_locked_context(key) as mctx:
                if mctx is None:
                    return
                window_perf = mctx.get_window_perf(key=key)
                window_work = mctx.get_window_work(key=key)
                heartrate = mctx.get_window_heartrate(key=key)
            if policy == ADAPTIVE_QUANT_HEURISTIC:
                # discrete compress-ratio ladder (runtime.py:121-154)
                if rate_constraint > 0:
                    target_time = ubatch_size * window_size / rate_constraint
                else:
                    target_time = float('inf')
                target_datasize = target_time * max(window_perf, 1e-12)
                qbit = stage.quant_bit
                eff = window_work * (32 / qbit if qbit > 0 else 1)
                ratio = int(eff / target_datasize) + 1 if target_datasize > 0 else 1
                for bound, bit in ((1, 0), (2, 16), (4, 8), (5, 6), (8, 4)):
                    if ratio <= bound:
                        stage.quant_bit = bit
                        break
                else:
                    stage.quant_bit = 2
            elif policy == ADAPTIVE_QUANT_HEURISTIC2:
                # analytic largest-feasible bitwidth (runtime.py:156-174)
                if rate_constraint <= 0:
                    continue
                ubatch_time = ubatch_size / rate_constraint
                src_bit = 32
                qbit = quantutil.constrain_max_bitwidth(
                    ubatch_time, max(window_work, 1e-12) / window_size,
                    max(window_perf, 1e-12), src_bit)
                stage.quant_bit = max(2, qbit) % src_bit
            elif policy == ADAPTIVE_QUANT_CONTROLLER:
                # Kalman/integral controller window split (runtime.py:177-216)
                if id(stage) not in controllers:
                    bw_start = stage.quant_bit or max(quantutil.BITWIDTHS)
                    controllers[id(stage)] = \
                        quantutil.AdaptiveBitwidthPerformanceController(
                            rate_constraint, quantutil.BITWIDTHS, bw_start)
                ctl = controllers[id(stage)]
                ctl.reference = rate_constraint
                send_rate = heartrate * ubatch_size
                bw1, bw2, it1 = ctl(send_rate, window_size)
                ctl_state[id(stage)] = (bw1, bw2, it1)
                stage.quant_bit = (bw1 if it1 > 0 else bw2) % max(
                    quantutil.BITWIDTHS)
            logger.info("Adaptive quantization (%s): bitwidth=%d", policy,
                        stage.quant_bit)

    return callback


class _EdgeQuantState:
    """Mutable output-edge bitwidth for a DCN rank — the role of the
    reference's non-persistent `quant_bit` module buffer that adaptive hooks
    mutate (reference runtime.py:464-467, 143-153)."""

    def __init__(self, quant_bit: int):
        self.quant_bit = quant_bit


def _register_dcn_monitor_hooks(ctx) -> None:
    """Wire send/recv transport hooks to the monitoring keys, measuring the
    actual bytes and transfer time of every pipeline-edge frame on this rank
    (reference p2p:132-152 + runtime.py:219-230).

    Feed-channel frames (raw inputs from the data rank to the head stage)
    are excluded: the reference injects inputs locally (enqueue_tensor), so
    its 'send' telemetry — the adaptive policies' sensor — never contains
    feed bytes. A colocated data rank + stage would otherwise pollute the
    stage's edge window with uncompressed feed traffic."""
    from pipeedge_tpu.comm import dcn

    def make_hooks(key):
        def pre(peer, channel):
            if dcn.base_channel(channel) != dcn.CHANNEL_FEED:
                monitoring.iteration_start(key)

        def post(peer, channel, tensors):
            if dcn.base_channel(channel) == dcn.CHANNEL_FEED:
                return
            if tensors is None:  # transfer aborted mid-frame
                monitoring.iteration_abort(key)
                return
            nbytes = sum(int(t.nbytes) for t in tensors)
            _WIRE_BYTES.inc(nbytes, direction=key, peer=str(peer))
            monitoring.iteration(key, work=nbytes * 8 / 1e6)

        return pre, post

    ctx.register_send_hooks(*make_hooks(MONITORING_KEY_SEND))
    ctx.register_recv_hooks(*make_hooks(MONITORING_KEY_RECV))


def run_pipeline_host(args, stage_layers, stage_quant, stage_ranks,
                      ubatches, labels) -> None:
    """Host-driven pipeline (arbitrary cut points, adaptive quantization)."""
    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    dtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32
    pipe = host_pipeline.build_pipeline(
        args.model_name, stage_layers, model_file=args.model_file,
        devices=[devices[r % len(devices)] for r in stage_ranks],
        quant_bits=stage_quant, dtype=dtype)
    window_size = get_window_size()
    # Per-edge telemetry: one monitoring key per inter-stage edge, fed with
    # that edge's actual wire bytes each microbatch (the per-rank 'send' key
    # of the reference, p2p:132-152 + runtime.py:219-230 — qualified by
    # stage index since one controller process owns every edge). The plain
    # 'send' key aggregates all edges per microbatch.
    edge_keys = [f"{MONITORING_KEY_SEND}{i}"
                 for i in range(len(pipe.stages) - 1)]
    for key in edge_keys:
        monitoring.add_key(key, work_type='Mbits')
    adaptive = _make_adaptive_callback(pipe.stages[:-1], window_size,
                                       edge_keys=edge_keys)

    for lb in labels:
        label_queue.put(lb)

    def on_edge_bytes(i, edge_bytes):
        total_mbits = 0.0
        for key, nbytes in zip(edge_keys, edge_bytes):
            mbits = nbytes * 8 / 1e6
            total_mbits += mbits
            monitoring.iteration(key, work=mbits, safe=False)
        monitoring.iteration(MONITORING_KEY_SEND, work=total_mbits, safe=False)

    def on_result(i, out):
        handle_results(out)
        if adaptive is not None:
            adaptive(i, out)

    pipe.edge_bytes_callback = on_edge_bytes
    pipe.ubatch_callback = on_result
    inputs = [jnp.asarray(u, dtype=dtype if u.dtype.kind == 'f' else None)
              for u in ubatches]
    # --measure-rounds: round 0 pays the XLA compiles (the reference's
    # single-shot methodology, runtime.py:493-505 there); later rounds
    # measure the warm pipeline. Same data each round, so label-driven
    # accuracy is unchanged; per-round lines let callers record both.
    # --rebalance auto: between rounds, re-split the batch to the
    # microbatch size the MEASURED steady-state cadence says minimizes the
    # fill/drain-vs-overhead latency model (parallel/pipeline.py
    # plan_microbatches), instead of keeping the CLI --ubatch forever.
    rounds = max(1, args.measure_rounds)
    adaptive_mb = args.rebalance == "auto" and rounds > 1
    stats = {}
    for rnd in range(rounds):
        if rnd:
            for lb in labels:
                label_queue.put(lb)
        tik = time.monotonic()
        t_span0 = time.monotonic_ns()
        # request-tagged dispatch/retire spans (single-controller
        # analogue of the DCN feed's per-microbatch trace contexts)
        traces = ([telemetry.TraceContext(f"r{rnd}.mb{i}", "batch",
                                          parent="host.run")
                   for i in range(len(inputs))]
                  if telemetry.enabled() else None)
        _, stats = pipe.run(inputs, traces=traces)
        tok = time.monotonic()
        # round track: mb ids restart each measure round; the segmenting
        # consumers (report/flows) key on these intervals
        telemetry.record("runtime", f"round{rnd}", t_span0,
                         time.monotonic_ns())
        if rounds > 1:
            batch_total = sum(len(u) for u in inputs)
            steady = stats.get("steady_state_throughput_items_sec")
            print(f"round={rnd} latency_sec={tok - tik:.6f} "
                  f"throughput_items_sec={batch_total / (tok - tik):.3f}")
            if steady:
                # own line, steady-first: both the round= and latency_sec=
                # line formats are parsed by tooling/tests
                print(f"steady_state_throughput_items_sec={steady:.3f} "
                      f"round={rnd}")
        if adaptive_mb and rnd + 1 < rounds:
            # growth bound: the user sized --ubatch for the device's
            # memory; the planner may merge up to 4x that (activations
            # grow linearly with u) but never balloon to the whole batch
            inputs, labels = _adapt_microbatches(
                pipe, stats, inputs, labels,
                max_ubatch=4 * args.ubatch_size)
    _report(tik, tok, inputs)
    steady = stats.get("steady_state_throughput_items_sec")
    if steady:
        # warm cadence without the first (compile-tainted) microbatch —
        # what rebalance decisions and benches should chase, next to the
        # end-to-end number _report prints
        print(f"steady_state_throughput_items_sec={steady:.3f}")


def _adapt_microbatches(pipe, stats, inputs, labels,
                        max_ubatch: Optional[int] = None):
    """One adaptive-microbatching step between host-driver measure rounds:
    decompose this round's measured steady per-microbatch interval into
    per-item time vs per-microbatch fixed overhead, ask `plan_microbatches`
    for the latency-minimizing split, and re-slice the batch (inputs AND
    labels, same boundaries, so FIFO label/result pairing holds). The next
    round pays one re-compile for the new shape — that is what measure
    rounds are for."""
    import jax.numpy as jnp

    interval = stats.get("steady_mb_interval_s")
    if not interval or not inputs:
        return inputs, labels
    u_cur = max(len(u) for u in inputs)
    t_fixed = stats.get("host_dispatch_s_per_ubatch") or 0.0
    t_item = max(0.0, interval - t_fixed) / u_cur
    batch_total = sum(len(u) for u in inputs)
    u_new, m_new, t_pred = host_pipeline.plan_microbatches(
        batch_total, len(pipe.stages), t_item, t_fixed,
        max_ubatch=max(max_ubatch or 0, u_cur) or None)
    if u_new == u_cur:
        return inputs, labels
    logger.info("adaptive ubatch: %d -> %d items/microbatch (%d -> %d "
                "microbatches; modeled round latency %.4fs)", u_cur, u_new,
                len(inputs), m_new, t_pred)
    print(f"adaptive_ubatch={u_new} microbatches={m_new} "
          f"predicted_latency_sec={t_pred:.6f}")
    flat = jnp.concatenate(list(inputs), axis=0)
    new_inputs = [flat[i:i + u_new] for i in range(0, batch_total, u_new)]
    new_labels = labels
    if labels and all(lb is not None for lb in labels):
        lflat = np.concatenate([np.asarray(lb) for lb in labels], axis=0)
        new_labels = [lflat[i:i + u_new]
                      for i in range(0, batch_total, u_new)]
    # window follows the split: enough in-flight microbatches to cover the
    # pipeline depth, but never more than double buffering provides
    pipe.max_inflight = max(len(pipe.stages) + 1,
                            min(2 * len(pipe.stages), m_new))
    return new_inputs, new_labels


def run_pipeline_spmd(args, stage_layers, stage_quant, stage_ranks,
                      ubatches, labels) -> None:
    """SPMD pipeline: one XLA program, ppermute edges (block-aligned)."""
    import jax
    import jax.numpy as jnp

    entry = registry.get_model_entry(args.model_name)
    dtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32
    total = registry.get_model_layers(args.model_name)
    # conflict check on the RAW argument, before any (potentially multi-GB)
    # stage weights load
    if stage_ranks and list(stage_ranks) != list(range(len(stage_layers))) \
            and (args.spmd_dp > 1 or args.spmd_tp > 1 or args.spmd_sp > 1):
        raise RuntimeError("-r stage ranks cannot combine with "
                           "--spmd-dp/--spmd-tp/--spmd-sp mesh axes")
    if args.spmd_tp > 1 and args.spmd_sp > 1:
        raise RuntimeError("--spmd-tp and --spmd-sp are mutually exclusive "
                           "(Megatron TP assumes a full local sequence)")
    need = len(stage_layers) * args.spmd_dp * max(args.spmd_tp, args.spmd_sp)
    have = len(jax.devices())
    if need > have:
        raise RuntimeError(
            f"mesh needs {need} devices (stages x dp x tp|sp = "
            f"{len(stage_layers)} x {args.spmd_dp} x "
            f"{max(args.spmd_tp, args.spmd_sp)}) but only {have} available")
    stage_params = []
    for i, (l, r) in enumerate(stage_layers):
        # stacked block layout required: the SPMD driver pads and re-stacks
        # per-stage blocks across the 'stage' mesh axis
        _, params, _ = registry.module_shard_factory(
            args.model_name, args.model_file, l, r, stage=i, dtype=dtype,
            unroll=False)
        stage_params.append(params)
    n_stages = len(stage_layers)
    ranks = None
    if stage_ranks and list(stage_ranks) != list(range(n_stages)):
        devices = jax.devices()
        mapped = [r % len(devices) for r in stage_ranks]
        if len(set(mapped)) != n_stages:
            # hard error, not a silent identity fallback: the user asked
            # for an explicit stage placement the mesh cannot honor
            raise RuntimeError(
                f"-r stage ranks {list(stage_ranks)} map to non-distinct "
                f"devices {mapped} on {len(devices)} available devices; "
                "spmd mode needs one distinct device per stage (drop -r "
                "for the default identity order)")
        ranks = mapped
    mesh = spmd.make_pipeline_mesh(n_stages, dp=args.spmd_dp,
                                   tp=args.spmd_tp, sp=args.spmd_sp,
                                   stage_ranks=ranks)
    from pipeedge_tpu.ops import qcollectives
    qcollectives.reset_trace_tally()
    pipe = spmd.build_spmd_pipeline(entry.family.FAMILY, entry.config,
                                    stage_layers, stage_params, mesh,
                                    quant_bit=list(stage_quant) if stage_quant
                                    else 0, sp_kind=args.spmd_sp_kind)
    for lb in labels:
        label_queue.put(lb)
    inputs = jnp.asarray(np.stack(ubatches),
                         dtype=dtype if ubatches[0].dtype.kind == 'f' else None)
    pipe.run(inputs)  # compile + warmup
    tik = time.monotonic()
    outputs = np.asarray(pipe.run(inputs))
    tok = time.monotonic()
    for out in outputs:
        handle_results(out)
    _report(tik, tok, ubatches)
    if args.tp_quant_bits:
        # fold the traced quantized-collective sites into telemetry +
        # /metrics: each site inside the tick scan executes ~ticks x
        # blocks-per-stage times per run (bubble ticks included — they
        # move wire bits too); 2 runs (warmup + timed). stage=None: the
        # whole pipeline is ONE XLA program here, so the record is an
        # all-stage aggregate — per-stage attribution comes from the dcn
        # --stage-tp path, where each worker folds its own tally
        blocks_per_stage = max((r - l + 1) // 4 for l, r in stage_layers)
        ticks = len(ubatches) + n_stages - 1
        summary = qcollectives.record_collectives(
            executions=2 * ticks * max(1, blocks_per_stage))
        logger.info("quantized collectives (--tp-quant-bits %d): %s",
                    args.tp_quant_bits, summary)


# Host-side quantized wire codec: moved to the library
# (pipeedge_tpu/comm/wire.py) so the DCN decode mode shares it; aliased here
# for the runtime call sites and existing tests.
from pipeedge_tpu.comm.wire import (WireCorruptError,
                                    crc_enabled as _wire_crc_enabled,
                                    wire_decode as _wire_decode,
                                    wire_encode as _wire_encode,
                                    wire_encode_device as _wire_encode_device)


ENV_LEDGER_SNAPSHOT = "DCN_LEDGER_SNAPSHOT"  # acks between ledger
# snapshots (0 disables). Each snapshot compacts acknowledged microbatch
# payloads out of the ledger and advances the replay frontier, so a
# failover replays from the last snapshot's frontier — O(unacknowledged)
# work and memory — instead of rescanning (and holding) the whole round.
DEFAULT_LEDGER_SNAPSHOT = 8


class _MicrobatchLedger:
    """Bounded in-flight ledger for the data rank (failover mode): every
    microbatch is registered with its id before dispatch, acknowledged when
    its result frame lands, and REPLAYED (same id) after a failover if no
    acknowledgment arrived. Duplicate results — a replay overlapping a
    frame that was already in flight when the stage died, or a transient
    resend — are dropped by id, and delivery to `handle_results` is held
    until contiguous, so the result stream at the data rank is exactly-once
    and in microbatch order regardless of arrival order.

    Snapshots (`maybe_snapshot`, every `snapshot_every` acks) keep the
    failover replay O(in-flight) instead of O(round): acknowledged
    payloads are dropped (they can never be refed — an ack is final) and
    the replay frontier advances past the acked prefix, so `pending()`
    after a mid-round death scans and ships only the microbatches that
    genuinely need replaying from the last snapshot on."""

    def __init__(self, ubatches, labels, snapshot_every: Optional[int] = None):
        self._ubatches = list(ubatches)
        self._labels = (list(labels) if labels
                        else [None] * len(self._ubatches))
        self._snapshot_every = (snapshot_every if snapshot_every is not None
                                else int(os.getenv(
                                    ENV_LEDGER_SNAPSHOT,
                                    str(DEFAULT_LEDGER_SNAPSHOT))))
        self._acks_since_snapshot = 0
        self._frontier = 0        # lowest possibly-unacked microbatch id
        self.snapshots = 0        # snapshots taken (tests/metrics)
        # mbid -> epoch of the incarnation whose result was accepted: the
        # dedupe key carries the epoch, so forensics (and tests) can tell
        # a same-incarnation resend from a stale-incarnation replay
        self._acked: dict = {}
        self._held: dict = {}       # acked but not yet contiguous
        self._next_deliver = 0
        # per-source epoch floor (fence_rank): an ack produced by an
        # incarnation below the floor is stale and refused — the transport
        # already fences these at the reader; this is the ledger's own
        # belt-and-braces (a stale frame must NEVER ack a microbatch)
        self._epoch_floor: dict = {}
        self.stale_dropped = 0
        # request <-> microbatch mapping (docs/OBSERVABILITY.md request
        # tracing): the feed loop records each microbatch's trace/request
        # id here, so a postmortem bundle and trace_report --request can
        # resolve a request to its microbatches (and back) after the fact
        self._traces: dict = {}
        self._lock = make_lock("runtime.ledger")
        self.done = threading.Event()
        if not self._ubatches:
            self.done.set()

    def record_trace(self, mbid: int, rid: str) -> None:
        """Bind microbatch `mbid` to request id `rid` (feed time)."""
        with self._lock:
            self._traces[int(mbid)] = str(rid)

    def trace_of(self, mbid: int) -> Optional[str]:
        with self._lock:
            return self._traces.get(int(mbid))

    def forensics(self) -> dict:
        """The ledger slice of a failover postmortem bundle: progress,
        the replay set, and the request ids in flight when it was taken
        (ids only — payloads stay out of the bundle)."""
        with self._lock:
            pending = [i for i in range(self._frontier,
                                        len(self._ubatches))
                       if i not in self._acked]
            return {"microbatches": len(self._ubatches),
                    "acked": len(self._acked),
                    "pending_mbids": pending,
                    "frontier": self._frontier,
                    "snapshots": self.snapshots,
                    "stale_dropped": self.stale_dropped,
                    "next_deliver": self._next_deliver,
                    "traces": {str(k): v
                               for k, v in sorted(self._traces.items())}}

    @property
    def acked_count(self) -> int:
        with self._lock:
            return len(self._acked)

    def pending(self) -> List[Tuple[int, np.ndarray]]:
        """(microbatch id, ubatch) pairs not yet acknowledged — what the
        feed loop sends, and after a failover, exactly the replay set.
        The scan starts at the snapshot frontier: everything below it was
        acked (and compacted away) by the last snapshot."""
        with self._lock:
            return [(i, self._ubatches[i])
                    for i in range(self._frontier, len(self._ubatches))
                    if i not in self._acked]

    def maybe_snapshot(self) -> bool:
        """Count an ack toward the snapshot cadence; snapshot when due.
        Called by the results loop after every accepted ack (cheap: a
        counter bump between snapshots)."""
        if self._snapshot_every <= 0:
            return False
        with self._lock:
            self._acks_since_snapshot += 1
            if self._acks_since_snapshot < self._snapshot_every:
                return False
            self._snapshot_locked()
        _LEDGER_SNAPSHOTS.inc()
        return True

    def snapshot(self) -> None:
        """Compact now (see `maybe_snapshot` for the periodic form)."""
        with self._lock:
            self._snapshot_locked()
        _LEDGER_SNAPSHOTS.inc()

    def _snapshot_locked(self) -> None:
        # an acked payload is never refed (acks are final even across
        # failovers — replay covers only unacked ids), so drop it and
        # advance the frontier past the acked prefix: replay work and
        # ledger memory both become O(unacknowledged since snapshot)
        for i in range(self._frontier, len(self._ubatches)):
            if i in self._acked:
                self._ubatches[i] = None
        while self._frontier < len(self._ubatches) \
                and self._frontier in self._acked:
            self._frontier += 1
        self._acks_since_snapshot = 0
        self.snapshots += 1

    def acked_epochs(self) -> dict:
        """mbid -> producing incarnation's epoch, for every accepted ack."""
        with self._lock:
            return dict(self._acked)

    def fence_rank(self, src: int, min_epoch: int) -> None:
        """Refuse acks from `src` incarnations below `min_epoch` (mirrors
        the transport fence, `DistDcnContext.min_epoch_of`)."""
        with self._lock:
            self._epoch_floor[src] = max(self._epoch_floor.get(src, 0),
                                         int(min_epoch))

    def ack(self, mbid: int, out: np.ndarray, epoch: int = 0,
            src: Optional[int] = None) -> bool:
        """Acknowledge microbatch `mbid`'s result; False for a duplicate
        or a stale-incarnation ack (both dropped). Results are surfaced
        through `handle_results` in id order so the label queue and
        accuracy bookkeeping stay aligned."""
        deliver = []
        with self._lock:
            if src is not None and epoch < self._epoch_floor.get(src, 0):
                self.stale_dropped += 1
                return False
            if mbid in self._acked or not 0 <= mbid < len(self._ubatches):
                return False
            self._acked[mbid] = int(epoch)
            self._held[mbid] = out
            while self._next_deliver in self._held:
                i = self._next_deliver
                deliver.append((self._labels[i], self._held.pop(i)))
                self._next_deliver += 1
            complete = len(self._acked) == len(self._ubatches)
        for label, result in deliver:
            if label is not None:
                label_queue.put(label)
            handle_results(result)
        if complete:
            self.done.set()
        return True


def _collect_fleet_digests(ctx, args, stage_ranks):
    """Pull every stage rank's CUMULATIVE span digest over the command
    channel once (kilobytes; comm/dcn.py `collect_digest`). Collected
    ONCE per round boundary and shared by every consumer — the
    rebalancer and the peer-health scorer each difference the same
    cumulative snapshot against their own baselines, so two features
    never pay two serial fleet sweeps (up to N x 10 s each on exactly
    the degraded links the health plane targets). Returns
    `{rank: digest}`, or None when any rank is dead/unreachable (the
    whole window is unmeasurable — partial snapshots must not advance
    anyone's baseline)."""
    with dead_lock:
        gone = set(dead_ranks)
    out = {}
    for src in sorted(set(stage_ranks)):
        if src == args.rank:
            rec = telemetry.recorder()
            out[src] = rec.digest() if rec is not None else {}
        elif src in gone:
            logger.info("telemetry window: rank %d is dead; skipping "
                        "this round", src)
            return None
        else:
            try:
                out[src] = ctx.collect_digest(src, timeout=10.0)
            except Exception as exc:  # noqa: BLE001 - any peer hiccup
                logger.warning("telemetry window: digest collection from "
                               "rank %d failed (%s)", src, exc)
                return None
    return out


def _estimates_from_digests(cur_digests, sched, prev_digests: dict,
                            min_samples: int = 2):
    """One consumer's measured window: difference a fleet digest
    snapshot against `prev_digests` (the CALLER-owned baselines, which
    advance here — every rank's, atomically, so windows always cover one
    time span) and decompose into per-stage service estimates
    (telemetry/feedback.py). Returns the estimates dict, or None when
    the snapshot is absent or fails the self-test."""
    from pipeedge_tpu.telemetry import feedback

    if cur_digests is None:
        return None
    stage_layers = sched[0]
    windows = [feedback.diff_digests(cur, prev_digests.get(src, {}))
               for src, cur in cur_digests.items()]
    prev_digests.update(cur_digests)
    est = feedback.stage_estimates(feedback.merge_digests(windows))
    problems = feedback.check_estimates(est, len(stage_layers),
                                        min_samples=min_samples)
    if problems:
        logger.info("telemetry window failed the self-test (%s)",
                    "; ".join(problems))
        return None
    return est


def _consider_rebalance(ctx, args, policy, sched, prev_digests: dict,
                        rnd: int, cur_digests=None):
    """One closed-loop decision at a round boundary (data rank only):
    measure this round's window (from the boundary's shared digest
    snapshot `cur_digests`, collected by `_collect_fleet_digests`) and
    ask the policy (sched/rebalance.py) whether re-solving the partition
    with the MEASURED profile is worth a re-schedule. Returns the
    accepted Proposal or None; never raises — an unmeasurable round
    (dead peer, incomplete estimates) keeps the running partition."""
    stage_layers, _stage_quant, _stage_ranks = sched
    t0 = time.monotonic_ns()
    # cur_digests=None means the boundary's one shared sweep already
    # failed — do NOT sweep again (the failure was fleet-wide)
    est = _estimates_from_digests(cur_digests, sched, prev_digests)
    if est is None:
        logger.info("rebalance: no measurable window; keeping partition")
        return None
    proposal = policy.consider(list(stage_layers), est, rnd)
    now = time.monotonic_ns()
    telemetry.record("rebalance", "plan", t0, now)
    if proposal is None:
        return None
    # instant marker per ACCEPTED re-partition: trace_report's
    # `rebalance_events` (the zero-churn assertion) counts these
    telemetry.record("rebalance", "apply", now, now)
    _REBALANCE_EVENTS.inc()
    logger.warning("rebalance: round %d partition %s -> %s (predicted "
                   "bottleneck %.4fs -> %.4fs, gain %.1f%%)", rnd,
                   list(stage_layers), proposal.partition,
                   proposal.bottleneck_before_s,
                   proposal.bottleneck_after_s, 100 * proposal.gain)
    # machine-parseable line (bench_rebalance.py / CI grep this)
    print(f"rebalance_round={rnd} "
          f"partition={','.join(f'{l},{r}' for l, r in proposal.partition)} "
          f"predicted_gain={proposal.gain:.4f}")
    return proposal


def _consider_peer_health(ctx, args, hstate: dict, sched, next_sched,
                          world_size: int, rnd: int,
                          cur_digests=None) -> None:
    """One gray-failure decision at a round boundary (data rank only,
    docs/FAULT_TOLERANCE.md gray failures): fold this round's measured
    signals — per-stage service time vs the fleet median (the same
    digest windows the rebalancer reads), heartbeat RTT p99 vs the fleet
    median (comm/dcn.py `heartbeat_rtt_stats`), transport redial counts
    — into the EWMA health scorer, and act on its transitions:

    - suspect / floor-hold / recovery: recorded (health spans, flight
      ring) but nothing moves.
    - quarantine (`--on-peer-degraded quarantine`, confirmed over N
      windows, min-fleet floor verified by DRY-RUNNING the next round's
      failover plan with the victim benched): a PLANNED bench — the rank
      is alive and this round fully drained, so adding it to
      `quarantined_ranks` makes the next boundary's re-plan move its
      stage to a spare with no ledger replay.
    - probation readmit: the score recovered (heartbeat RTT is the main
      signal a benched rank still emits); un-benching lets the next
      round's own schedule restore the stage through the same re-plan
      path — and one bad probation window re-quarantines without
      re-confirmation.

    Never raises; an unmeasurable service window still folds RTT/retry
    signals so quarantined ranks keep moving toward (or away from)
    readmission."""
    from pipeedge_tpu import health as health_mod

    scorer = hstate["scorer"]
    _stage_layers, _q, stage_ranks = sched
    # cur_digests=None = the boundary's shared sweep failed: no service
    # signal this window, but RTT/retry signals still fold below
    est = _estimates_from_digests(cur_digests, sched,
                                  hstate["prev_digests"])

    # TRUE median (statistics.median: middle-two mean for even counts):
    # an upper median would make a 2-stage fleet's straggler its own
    # baseline (ratio 1.0 — detector blind)
    from statistics import median

    # relative signals: a fleet where everything is slow is balanced,
    # not gray — normalize against the fleet median. Absolute floors
    # guard the false-positive side: a stage a few ms over the median
    # (natural skew) or a sub-5 ms loopback RTT at 2x the median (pure
    # noise) reads as HEALTHY (ratio 1.0 — an actively decaying signal,
    # not a missing one). Env-tunable for unusual fleets.
    excess_floor_s = float(os.getenv("PIPEEDGE_HEALTH_MIN_EXCESS_S",
                                     "0.02"))
    rtt_floor_ms = float(os.getenv("PIPEEDGE_HEALTH_RTT_FLOOR_MS", "5"))
    service_ratio: dict = {}
    if est:
        svc = {stage_ranks[i]: e.service_s for i, e in est.items()
               if 0 <= i < len(stage_ranks)}
        med = median(svc.values()) if svc else 0.0
        if med > 0:
            service_ratio = {
                r: (s / med if s - med >= excess_floor_s else 1.0)
                for r, s in svc.items()}
    rtt = ctx.heartbeat_rtt_stats()
    rtt_ratio: dict = {}
    if rtt:
        med = median(v["p99_ms"] for v in rtt.values())
        for peer, v in rtt.items():
            _HB_RTT.set(v["p50_ms"], peer=str(peer), q="p50")
            _HB_RTT.set(v["p99_ms"], peer=str(peer), q="p99")
            if med > 0 and len(rtt) > 1:
                rtt_ratio[peer] = (v["p99_ms"] / med
                                   if v["p99_ms"] >= rtt_floor_ms
                                   else 1.0)
    retries_now = ctx.send_retry_counts()
    prev_r = hstate["prev_retries"]
    window_retries = {p: n - prev_r.get(p, 0)
                      for p, n in retries_now.items()}
    hstate["prev_retries"] = retries_now

    with dead_lock:
        dead_now = set(dead_ranks)
        bench_now = (set(benched_ranks) | set(quarantined_ranks)
                     | set(autoscaled_ranks))
    # score every rank carrying a stage this round PLUS every
    # quarantined rank (still beating — its RTT drives readmission)
    for peer in sorted((set(stage_ranks) | set(quarantined_ranks))
                       - dead_now - {args.rank}):
        sample = health_mod.HealthSample(
            service_ratio=service_ratio.get(peer),
            rtt_ratio=rtt_ratio.get(peer),
            send_retries=int(window_retries.get(peer, 0)))
        floor_ok = False
        if args.on_peer_degraded == "quarantine" \
                and scorer.state_of(peer) in (health_mod.STATE_SUSPECT,
                                              health_mod.STATE_PROBATION):
            # min-fleet floor: quarantine (or a probation RELAPSE —
            # also a quarantine decision) only if the NEXT round still
            # has a runnable plan with this rank ALSO benched — the same
            # failover cascade the boundary re-plan will actually run
            planned = _plan_failover(args, next_sched, world_size,
                                     dead_now,
                                     benched=bench_now | {peer})
            floor_ok = planned is not None
        t = scorer.observe(peer, sample, can_quarantine=floor_ok)
        if t is None:
            continue
        now = time.monotonic_ns()
        if t.to == health_mod.STATE_QUARANTINED:
            with dead_lock:
                quarantined_ranks.add(peer)
            telemetry.record("health", f"quarantine:r{peer}", now, now)
            flight.note("peer_degraded", rank=peer, to=t.to,
                        score=round(t.score, 4), reason=t.reason)
            flight.maybe_dump("gray", context={
                "rank": peer, "round": rnd, "score": t.score,
                "reason": t.reason,
                "health": scorer.snapshot()})
            logger.warning("peer health: QUARANTINING rank %d at round "
                           "%d (%s); its stage moves to a spare at the "
                           "next boundary", peer, rnd, t.reason)
            # machine-parseable line (tools/chaos_dcn.py + CI gate)
            print(f"quarantine_rank={peer} round={rnd} "
                  f"score={t.score:.4f}", flush=True)
        elif t.frm == health_mod.STATE_QUARANTINED \
                and t.to == health_mod.STATE_PROBATION:
            with dead_lock:
                quarantined_ranks.discard(peer)
            telemetry.record("health", f"readmit:r{peer}", now, now)
            flight.note("peer_readmitted", rank=peer,
                        score=round(t.score, 4))
            logger.warning("peer health: READMITTING rank %d on "
                           "probation at round %d (%s)", peer, rnd,
                           t.reason)
            print(f"readmit_rank={peer} round={rnd} "
                  f"score={t.score:.4f}", flush=True)
        elif t.frm == t.to:
            # floor hold (suspect stays suspect / probation relapse
            # held): checked BEFORE the suspect branch, which would
            # otherwise swallow a suspect-state hold as a second
            # `suspect` span and keep gray.held at zero
            telemetry.record("health", f"held:r{peer}", now, now)
            flight.note("peer_quarantine_held", rank=peer,
                        score=round(t.score, 4))
        elif t.to == health_mod.STATE_SUSPECT:
            telemetry.record("health", f"suspect:r{peer}", now, now)
            flight.note("peer_suspect", rank=peer,
                        score=round(t.score, 4), reason=t.reason)
        else:                 # suspect/probation -> healthy
            telemetry.record("health", f"recovered:r{peer}", now, now)
            flight.note("peer_recovered", rank=peer,
                        score=round(t.score, 4))


def _plan_failover(args, sched, world_size: int, dead_now: set,
                   benched: Optional[set] = None):
    """Re-schedule over the survivors (sched/failover.py cascade). The
    native scheduler re-solve is attempted only when profile files were
    given; spare substitution — which preserves the partition and thus
    bit-identical replay — is the fallback. None = no capacity: abort.
    `benched` ranks (rejoined, not healed) keep no stage but stay in the
    spare pool at lowest priority."""
    from pipeedge_tpu.sched import failover as failover_sched

    scheduler_fn = None
    if args.sched_models_file or args.sched_dev_types_file \
            or args.sched_dev_file:
        def scheduler_fn(n_survivors):
            return get_pipeline_sched(
                n_survivors, None, None, None, None, args.model_name,
                args.ubatch_size, args.sched_models_file,
                args.sched_dev_types_file, args.sched_dev_file,
                dtype=args.dtype)
    return failover_sched.plan_failover(*sched, world_size, dead_now,
                                        scheduler_fn=scheduler_fn,
                                        benched=benched)


def _consider_autoscale(ctx, args, a_state: dict, sched, schedules,
                        sched_idx: int, world_size: int, rnd: int,
                        cur_digests=None) -> None:
    """One capacity decision at a round boundary (data rank only): the
    pipeline-level half of the closed capacity loop (--autoscale-ranks;
    the decision engine is serving/autoscale.py's CapacityController —
    confirm/dwell hysteresis, flap damper, dry-run `held`, identical to
    the router's replica loop). Capacity unit = pipeline stages.

    Signal: the boundary's shared digest window (the same sweep the
    rebalancer and health scorer read) decomposed into per-stage
    service estimates — up pressure when the bottleneck stage's
    per-microbatch service time crosses `--autoscale-rank-high`
    (adding a stage lets the re-cut shed layers off the critical
    path), down pressure below `--autoscale-rank-low` (the pipeline is
    over-provisioned; merging stages trades idle bubbles for none).

    Actuation through EXISTING machinery only:
    - scale-up = planned rejoin: `plan_rejoin(sched, None, ...)`
      expands onto idle survivors — including capacity-benched
      spares — and is written over the remaining rounds, exactly like
      `_maybe_heal`'s re-expansion path.
    - scale-down = planned contraction: the span is re-solved over one
      FEWER stage and the victim (the rank carrying the fewest layers,
      never the data rank) is dropped from the placement and joins
      `autoscaled_ranks`, keeping it benched through later failover
      re-plans and available to scale-up's re-expansion — the
      contraction is built here first, so an un-runnable one renders
      as a visible `held` decision instead of an abort."""
    from pipeedge_tpu.sched import failover as failover_sched
    from pipeedge_tpu.sched import rebalance
    from pipeedge_tpu.serving import autoscale as autoscale_mod

    est = _estimates_from_digests(cur_digests, sched,
                                  a_state["prev_digests"])
    with dead_lock:
        dead_now = set(dead_ranks)
    # state BEFORE the lazy controller construction: the controller
    # probes size_fn() at __init__, and every closure below reads
    # a_state at call time
    a_state.update(sched=sched, schedules=schedules,
                   sched_idx=sched_idx, dead=dead_now, last_apply=None)

    if a_state.get("controller") is None:
        max_size = (min(args.autoscale_max, world_size)
                    if args.autoscale_max else world_size)

        def _classify(pol, sig):
            b = sig.get("bottleneck_s")
            if b is None:
                return 0     # unmeasurable window: streaks reset
            if b >= args.autoscale_rank_high:
                return 1
            if b <= args.autoscale_rank_low:
                return -1
            return 0

        def _plan(direction, cur, target):
            sched_now = a_state["sched"]
            dead_now = a_state["dead"]
            if direction == "up":
                planned = failover_sched.plan_rejoin(
                    sched_now, None, world_size, dead_now,
                    align=4 if args.stage_tp > 1 else 1)
                if planned is None:
                    return {"ok": False,
                            "reason": "no idle survivor to expand onto"}
                return {"ok": True, "planned": planned}
            # scale-down = partition CONTRACTION (the inverse of the up
            # path's re-expansion): merge the span over target stages
            # and drop the victim from the placement. Benching through
            # the failover cascade is NOT enough — on a full pipeline
            # substitute_spares hands the stage back to the benched
            # rank as the last-resort spare (a visible no-op).
            stage_layers, _q, stage_ranks = sched_now
            candidates = [(hi - lo + 1, i)
                          for i, (lo, hi) in enumerate(stage_layers)
                          if stage_ranks[i] != args.rank
                          and stage_ranks[i] not in dead_now]
            if not candidates:
                return {"ok": False,
                        "reason": "no benchable stage (data rank "
                                  "holds the only one)"}
            _, idx = min(candidates)
            victim = stage_ranks[idx]
            try:
                contracted, _ = rebalance.solve_partition(
                    [1.0] * stage_layers[-1][1], target,
                    align=4 if args.stage_tp > 1 else 1)
            except ValueError as exc:
                return {"ok": False,
                        "reason": f"contraction to {target} stage(s) "
                                  f"unsolvable: {exc}"}
            new_ranks = [r for r in stage_ranks if r != victim]
            if len(new_ranks) != target:
                return {"ok": False,
                        "reason": f"placement mismatch: {len(new_ranks)} "
                                  f"survivors for {target} stage(s)"}
            return {"ok": True, "victim": victim,
                    "planned": (list(contracted), [0] * target,
                                new_ranks)}

        def _apply(plan):
            scheds = a_state["schedules"]
            idx_now = a_state["sched_idx"]
            planned = plan["planned"]
            for j in range(idx_now + 1, len(scheds)):
                scheds[j] = (list(planned[0]), list(planned[1]),
                             list(planned[2]))
            if "victim" not in plan:                    # scale-up
                with dead_lock:
                    for r_new in planned[2]:
                        autoscaled_ranks.discard(r_new)
                a_state["last_apply"] = ("up", planned[2])
            else:                                       # scale-down
                victim = plan["victim"]
                with dead_lock:
                    autoscaled_ranks.add(victim)
                a_state["last_apply"] = ("down", victim)

        a_state["controller"] = autoscale_mod.CapacityController(
            autoscale_mod.CapacityPolicy(
                min_size=args.autoscale_min,
                max_size=max(max_size, args.autoscale_min),
                confirm=args.autoscale_confirm,
                cooldown_s=args.autoscale_cooldown),
            mode=args.autoscale_ranks,
            size_fn=lambda: len(a_state["sched"][0]),
            plan_fn=_plan, apply_fn=_apply,
            classify_fn=_classify, label="stages")

    stage_layers = sched[0]
    signals = {"size": len(stage_layers), "brownout_level": 0}
    if est:
        svc = [e.service_s for e in est.values()]
        bott = max(svc)
        signals["bottleneck_s"] = bott
        # classic steady-state pipeline bubble ratio: how much of the
        # fleet's stage-seconds are spent waiting on the bottleneck
        signals["bubble_frac"] = (1.0 - sum(svc) / (len(svc) * bott)
                                  if bott > 0 else 0.0)
    d = a_state["controller"].tick(signals)
    if d is None:
        return
    # machine-parseable decision line (tools/chaos_dcn.py / CI grep)
    print(f"{d.line()} round={rnd}", flush=True)
    applied = a_state["last_apply"]
    if applied is None:
        return
    kind, detail = applied
    if kind == "up":
        print(f"autoscale_rank direction=up round={rnd} "
              f"ranks={','.join(str(r) for r in detail)}", flush=True)
    else:
        print(f"autoscale_rank direction=down round={rnd} "
              f"victim={detail}", flush=True)


def run_pipeline_dcn(args, schedules, ubatches, labels) -> None:
    """Multi-process pipeline over the DCN transport: this process is ONE
    rank (reference `runtime.py RANK WORLDSIZE` semantics, run_pipeline_p2p
    418-511). Rank `--data-rank` resolves/broadcasts the schedule, streams
    microbatches to the first stage, and collects results from the last.

    `schedules` is a list of (stage_layers, stage_quant, stage_ranks)
    rounds: after each round completes (CMD_STOP), the data rank broadcasts
    the next round's CMD_SCHED and the live fleet rebuilds its stages — the
    re-scheduling path the reference designed (CMD_SCHED lands on sched_q,
    runtime.py:404-415) but never shipped (its runtime consumes exactly one
    schedule at startup). An EMPTY CMD_SCHED means "no more rounds": workers
    exit their schedule loop."""
    import jax.numpy as jnp

    from pipeedge_tpu.comm import chaos, dcn

    rank, world_size = args.rank, args.worldsize
    _declare_fleet_metric_labels(world_size, rank)
    # per-rank flight recorder: always-on event ring; postmortem bundles
    # fire on failover (data rank) — one per cooldown window
    flight.configure(rank=rank)
    data_rank = args.data_rank
    failover_mode = args.on_peer_death == "failover"
    addrs = dcn.parse_rank_addrs(args.dcn_addrs, world_size, args.port)
    dtype = jnp.bfloat16 if args.dtype == 'bfloat16' else jnp.float32

    with dcn.DistDcnContext(world_size, rank, addrs,
                            cmd_handler=handle_cmd,
                            accept_joins=args.on_peer_rejoin != "ignore"
                            ) as ctx:
        _register_dcn_monitor_hooks(ctx)
        chaos.maybe_install(ctx)   # deterministic fault injection, env-gated
        if ctx.send_retries > 0 and not failover_mode:
            # a resent frame can DUPLICATE or reorder a microbatch; only
            # the failover ledger dedupes by id. Without it, the FIFO
            # label/result pairing can silently misalign.
            logger.warning(
                "DCN_SEND_RETRIES=%d without --on-peer-death failover: "
                "resends are not deduplicated; result/label alignment is "
                "not guaranteed after a transient fault", ctx.send_retries)

        def on_peer_death(dead: int) -> None:
            if stop_info[0] is not None:
                return  # the fleet is already aborting for a known death
            # Grace window: connections also drop during the clean fleet
            # teardown (empty CMD_SCHED), which may still be in flight on
            # another socket — wait briefly for it before declaring a
            # failure. Mid-run or between rounds, connections never drop
            # cleanly, so anything else is a death.
            if fleet_shutdown.wait(timeout=2.0):
                return
            monitoring.flush()   # the beat CSVs are about to matter
            if failover_mode and dead != data_rank:
                with dead_lock:
                    announced = dead in dead_ranks
                    dead_ranks.add(dead)
                if announced:
                    return
                _record_failover_detect(dead)
                logger.error("rank %d: peer rank %d died; entering failover",
                             rank, dead)
                failover_event.set()
                # every rank may detect independently; the announcement is
                # idempotent at the receivers (dead_ranks is a set) and the
                # data rank alone orchestrates the recovery
                try:
                    ctx.cmd_broadcast(CMD_DEAD,
                                      [np.asarray(dead, np.int32)],
                                      best_effort=True)
                except OSError:  # pragma: no cover - best_effort guards
                    pass
                return
            # the DATA rank's death is never survivable — it alone holds
            # the ledger, the inputs, and the orchestration — so even in
            # failover mode it takes the abort path below
            _record_failover_detect(dead, failover=False)
            logger.error("rank %d: peer rank %d died; stopping the pipeline",
                         rank, dead)
            stop_info[0] = dead
            # broadcast BEFORE waking local waiters: the data rank's finally
            # block broadcasts a plain CMD_STOP once stop_event fires, and
            # the death-carrying stop must reach peers first
            try:
                ctx.cmd_broadcast(CMD_STOP, [np.asarray(dead, np.int32)],
                                  best_effort=True)
            except OSError:  # pragma: no cover - best_effort already guards
                pass
            stop_event.set()

        ctx.register_peer_death_handler(on_peer_death)

        # heal cascade state shared between the rejoin handler (reader-
        # thread dispatch) and the data rank's round loop
        round_state = {"rnd": 0}

        def on_peer_rejoin(src: int, epoch: int) -> None:
            """A peer passed the JOIN admission handshake: pull it out of
            the terminal dead set (it is live idle-spare capacity again),
            and — on the data rank — ack the admission (CMD_ADMIT) and arm
            the heal for the next round boundary."""
            with dead_lock:
                was_dead = src in dead_ranks
                dead_ranks.discard(src)
                # the rejoiner is live idle capacity, but its old stage
                # stays where the failover moved it until a heal says
                # otherwise (spare mode never says otherwise)
                if was_dead:
                    benched_ranks.add(src)
            now = time.monotonic_ns()
            telemetry.record("rejoin", "admit", now, now)
            _REJOINS.inc(peer=str(src))
            _heal_state["rejoin_ns"] = now
            if was_dead:
                _heal_state["pending"] = True
            logger.warning("rank %d: peer rank %d rejoined with epoch %d"
                           "%s", rank, src, epoch,
                           " (was confirmed dead)" if was_dead else "")
            if rank != data_rank:
                return
            # machine-parseable admission line (tools/chaos_dcn.py keys
            # its rejoin timestamp on it)
            print(f"rejoin_rank={src} epoch={epoch} "
                  f"was_dead={int(was_dead)}", flush=True)
            # epoch floor for the ledger: results signed by the fenced
            # incarnation must never ack a microbatch
            ledger = ledger_ref[0]
            if ledger is not None:
                ledger.fence_rank(src, ctx.min_epoch_of(src))
            try:
                ctx.cmd_send(src, CMD_ADMIT,
                             [np.asarray(round_state["rnd"], np.int32)],
                             timeout=10.0)
            except OSError as exc:
                logger.warning("CMD_ADMIT to rank %d failed (%s); it "
                               "will learn from the next CMD_SCHED",
                               src, exc)

        ledger_ref: List[Optional[_MicrobatchLedger]] = [None]
        ctx.register_peer_rejoin_handler(on_peer_rejoin)
        # liveness plane: beat every peer, watch every peer's beats, and
        # feed each received beat into the monitoring heartbeat windows
        # (the 'liveness' CSV is the post-mortem timeline of peer health)
        def liveness_beat(src: int) -> None:
            # raw context call: CSV row + window accounting WITHOUT the
            # facade's per-beat instant log lines — world_size beats per
            # interval would bury the very lines failover forensics greps
            _HEARTBEATS_RX.inc(src=str(src))
            with monitoring.get_locked_context(MONITORING_KEY_LIVENESS) \
                    as mctx:
                if mctx is not None:
                    mctx.iteration(key=MONITORING_KEY_LIVENESS, work=1,
                                   accuracy=src)

        ctx.register_heartbeat_hook(liveness_beat)

        def rtt_sample(src: int, rtt_ms: float) -> None:
            # per-probe feed for the monitoring snapshot / hb_rtt.csv
            # (work = rtt ms, accuracy = peer rank); the p50/p99 gauge
            # aggregation happens at round boundaries in
            # _consider_peer_health from the transport's bounded window
            with monitoring.get_locked_context(MONITORING_KEY_HB_RTT) \
                    as mctx:
                if mctx is not None:
                    mctx.iteration(key=MONITORING_KEY_HB_RTT,
                                   work=rtt_ms, accuracy=src)

        ctx.register_heartbeat_rtt_hook(rtt_sample)
        ctx.start_heartbeat(
            interval=args.heartbeat_interval if args.heartbeat_interval > 0
            else None,
            miss_threshold=args.heartbeat_miss if args.heartbeat_miss > 0
            else None)
        if ctx.epoch > 0:
            # this process IS a restarted incarnation (env DCN_EPOCH,
            # e.g. chaos restart@K:MS or an orchestrator relaunch): ask
            # the fleet to re-admit it before settling in to wait for a
            # schedule
            reached = ctx.announce_join()
            logger.warning("rank %d: restarted as epoch %d; JOIN "
                           "announced to rank(s) %s", rank, ctx.epoch,
                           reached)
        results_target = [0]
        if rank == data_rank:
            # span collection runs in the finally so round end, abort, AND
            # failover all leave a merged trace (best-effort, like
            # CMD_STOP): on the clean path it runs BEFORE the empty
            # CMD_SCHED below, while every worker is still serving frames
            # closed-loop rebalancer (--rebalance auto): re-partition the
            # NEXT rounds from this round's measured per-stage timings,
            # applied through the same CMD_SCHED broadcast failover uses
            rebalancer = None
            prev_digests: dict = {}
            if args.rebalance == "auto":
                from pipeedge_tpu.sched import rebalance as rebalance_sched
                rebalancer = rebalance_sched.RebalancePolicy(
                    threshold=args.rebalance_threshold,
                    cooldown=args.rebalance_cooldown,
                    confirm=args.rebalance_confirm,
                    align=4 if args.stage_tp > 1 else 1)
            # peer-health plane (gray-failure detection): active whenever
            # the fleet records spans — the scorer reads the same digest
            # windows the rebalancer does. `--on-peer-degraded
            # quarantine` forces telemetry on (main()); with `ignore` +
            # --trace-spans the scorer still runs for observability
            # (scores, suspect spans, flight events) but never benches.
            health_state = None
            if telemetry.enabled() and world_size > 1:
                from pipeedge_tpu import health as health_mod
                h_scorer = health_mod.PeerHealthScorer(
                    [r for r in range(world_size) if r != rank],
                    policy=health_mod.HealthPolicy(
                        suspect_threshold=args.degraded_threshold,
                        readmit_threshold=args.degraded_threshold / 2,
                        confirm=args.degraded_confirm,
                        readmit=args.degraded_readmit))
                health_mod.set_scorer(h_scorer)
                health_state = {"scorer": h_scorer, "prev_digests": {},
                                "prev_retries": {}}
            # closed capacity loop, pipeline half (--autoscale-ranks):
            # the controller is built lazily at the first boundary
            # (_consider_autoscale), from the same digest windows
            a_state = None
            if getattr(args, "autoscale_ranks", "off") != "off" \
                    and world_size > 1:
                a_state = {"prev_digests": {}, "controller": None}
            schedules = [tuple(s) for s in schedules]
            try:
                rnd = 0
                fo_t0 = None   # recovery span: detection stamp, if any
                for sched_idx in range(len(schedules)):
                    stage_layers, stage_quant, stage_ranks = \
                        schedules[sched_idx]
                    sched = (stage_layers, stage_quant, stage_ranks)
                    ledger = None
                    if failover_mode:
                        # clear BEFORE snapshotting: a death landing in
                        # between is caught by the snapshot (its rank is
                        # added to dead_ranks before the event is set),
                        # and a death landing after re-sets the event and
                        # fails the round over normally — never both missed
                        failover_event.clear()
                        with dead_lock:
                            dead_now = set(dead_ranks)
                            bench_now = (set(benched_ranks)
                                         | set(quarantined_ranks)
                                         | set(autoscaled_ranks))
                        if dead_now or bench_now:
                            # a LATER schedule round may still name a rank
                            # that died earlier (or rejoined un-healed, or
                            # was gray-quarantined); remap before
                            # broadcasting
                            if _heal_state["pre_failure"] is None:
                                _heal_state["pre_failure"] = sched
                            sched = _plan_failover(args, sched, world_size,
                                                   dead_now,
                                                   benched=bench_now)
                            if sched is None:
                                _abort_no_capacity(ctx, dead_now)
                        ledger = _MicrobatchLedger(ubatches, labels)
                        ledger_ref[0] = ledger
                    while True:
                        round_state["rnd"] = rnd
                        if rnd:
                            logger.info("re-schedule: broadcasting round %d "
                                        "(partition %s)", rnd, sched[0])
                        status = _dcn_round(args, ctx, rnd, *sched, ubatches,
                                            labels, dtype, results_target,
                                            ledger=ledger)
                        rnd += 1
                        if status != "failover":
                            if fo_t0 is not None:
                                # detection -> replay-round completion: the
                                # trace_report failover breakdown; consume
                                # this episode's stamps so the next episode
                                # starts from its own first detection
                                telemetry.record("failover", "recover",
                                                 fo_t0, time.monotonic_ns())
                                fo_t0 = None
                                del _failover_detect_ns[:]
                            # ONE digest sweep per boundary, shared by
                            # the rebalancer and the peer-health scorer
                            # (each differences it against its own
                            # baseline — the digests are cumulative)
                            boundary_digests = None
                            if (rebalancer is not None
                                    or health_state is not None
                                    or a_state is not None) \
                                    and sched_idx + 1 < len(schedules):
                                boundary_digests = _collect_fleet_digests(
                                    ctx, args, sched[2])
                            if rebalancer is not None \
                                    and sched_idx + 1 < len(schedules):
                                proposal = _consider_rebalance(
                                    ctx, args, rebalancer, sched,
                                    prev_digests, rnd - 1,
                                    cur_digests=boundary_digests)
                                if proposal is not None:
                                    # re-cut the REMAINING rounds; their
                                    # quant/rank specs stand, and a death
                                    # before they run still goes through
                                    # the per-round failover re-plan above
                                    for j in range(sched_idx + 1,
                                                   len(schedules)):
                                        _, q_j, r_j = schedules[j]
                                        schedules[j] = (
                                            [tuple(p) for p in
                                             proposal.partition], q_j, r_j)
                            if health_state is not None \
                                    and sched_idx + 1 < len(schedules):
                                # gray-failure decision at the boundary:
                                # fold this round's measured signals and
                                # quarantine/readmit before the next
                                # round's re-plan (the round is fully
                                # drained — a planned bench, no replay)
                                _consider_peer_health(
                                    ctx, args, health_state, sched,
                                    schedules[sched_idx + 1], world_size,
                                    rnd - 1,
                                    cur_digests=boundary_digests)
                            if args.on_peer_rejoin == "heal" \
                                    and _heal_state["pending"] \
                                    and sched_idx + 1 < len(schedules):
                                # heal-at-round-boundary: capacity came
                                # back mid-run; restore (or re-expand)
                                # before the next round's broadcast
                                _maybe_heal(args, sched, world_size, rnd,
                                            schedules, sched_idx)
                            if a_state is not None \
                                    and sched_idx + 1 < len(schedules):
                                # capacity decision LAST: it reads the
                                # same digest window, and its scale-up
                                # rewrite must land after any heal so
                                # the remaining rounds reflect both
                                _consider_autoscale(
                                    ctx, args, a_state, sched,
                                    schedules, sched_idx, world_size,
                                    rnd - 1,
                                    cur_digests=boundary_digests)
                            break
                        if fo_t0 is None:
                            # FIRST detection of this episode (appends are
                            # deduped per dead rank)
                            fo_t0 = (_failover_detect_ns[0]
                                     if _failover_detect_ns
                                     else time.monotonic_ns())
                        # failover postmortem bundle (flight recorder):
                        # the ledger's replay set + request map and the
                        # membership state at the moment the round failed
                        # over — written before the re-plan mutates them
                        with dead_lock:
                            fo_dead = sorted(dead_ranks)
                            fo_bench = sorted(benched_ranks)
                        flight.note("failover", dead_ranks=fo_dead,
                                    round=rnd)
                        flight.maybe_dump("failover", context={
                            "round": rnd,
                            "dead_ranks": fo_dead,
                            "benched_ranks": fo_bench,
                            "ledger": (ledger.forensics()
                                       if ledger is not None else None)})
                        # clear-then-snapshot, same ordering as above
                        failover_event.clear()
                        with dead_lock:
                            dead_now = set(dead_ranks)
                            bench_now = (set(benched_ranks)
                                         | set(quarantined_ranks)
                                         | set(autoscaled_ranks))
                        if _heal_state["pre_failure"] is None:
                            # the schedule running when the episode's
                            # death hit: what --on-peer-rejoin heal
                            # restores when its ranks come back
                            _heal_state["pre_failure"] = sched
                        replay = ledger.pending()
                        with telemetry.span("failover", "reschedule"):
                            planned = _plan_failover(args, sched, world_size,
                                                     dead_now,
                                                     benched=bench_now)
                        if planned is None:
                            _abort_no_capacity(ctx, dead_now)
                        logger.warning(
                            "failover: rank(s) %s dead (benched: %s); "
                            "re-scheduling over survivors and replaying "
                            "%d unacknowledged microbatch(es)",
                            sorted(dead_now), sorted(bench_now),
                            len(replay))
                        sched = planned
            finally:
                if getattr(args, "trace_spans", None):
                    _collect_write_spans(ctx, args)
            # no more rounds: an empty schedule releases the workers.
            # fleet_shutdown first, so peers closing in response are not
            # taken for deaths.
            fleet_shutdown.set()
            with dead_lock:
                gone = set(dead_ranks)
            ctx.cmd_broadcast(CMD_SCHED, [], exclude=gone)
        else:
            rnd = 0
            while True:
                # workers block until the schedule arrives (runtime.py:447-8),
                # polling so a peer death declared meanwhile aborts promptly
                deadline = time.monotonic() + args.sched_timeout
                while True:
                    try:
                        stop_base, tensors = sched_q.get(timeout=0.5)
                        break
                    except queue.Empty:
                        if stop_info[0] is not None:
                            raise RuntimeError(
                                f"rank {rank}: pipeline aborted: rank "
                                f"{stop_info[0]} died") from None
                        if time.monotonic() >= deadline:
                            raise RuntimeError(
                                f"rank {rank}: no CMD_SCHED within "
                                f"{args.sched_timeout}s; is the data rank up "
                                "and are --dcn-addrs consistent across "
                                "ranks?") from None
                if len(tensors) == 0:
                    logger.info("rank %d: empty CMD_SCHED; shutting down",
                                rank)
                    fleet_shutdown.set()
                    break
                stage_layers = [tuple(map(int, lr)) for lr in tensors[0]]
                stage_quant = [int(q) for q in tensors[1]]
                stage_ranks = [int(r) for r in tensors[2]]
                # the schedule carries the data rank's GLOBAL round index:
                # channel round-parity must match the fleet's, not this
                # worker's local count — a rejoined worker starts counting
                # mid-sequence (older peers without the tensor: fall back
                # to the local count, correct when nothing was missed)
                if len(tensors) > 3:
                    rnd = int(np.asarray(tensors[3]).reshape(-1)[0])
                _dcn_round(args, ctx, rnd, stage_layers, stage_quant,
                           stage_ranks, [], [], dtype, results_target,
                           stop_base=stop_base)
                rnd += 1


def _collect_write_spans(ctx, args) -> None:
    """Gather every live peer's span ring over the command channel (clock-
    aligned NTP-style, dcn.collect_spans), merge with the local ring, and
    write the Perfetto-loadable trace to `--trace-spans`. Best-effort like
    CMD_STOP: an unreachable or span-less peer is skipped, never fatal —
    this runs on abort paths where peers may already be gone."""
    from pipeedge_tpu.telemetry import chrome_trace

    rec = telemetry.recorder()
    if rec is None:
        return
    merged = rec.snapshot()
    ranks_seen = 1
    dead = ctx.dead_ranks()
    for dst in range(args.worldsize):
        if dst == args.rank or dst in dead:
            continue
        try:
            spans, offset = ctx.collect_spans(dst, timeout=5.0)
        except Exception as exc:  # noqa: BLE001 - skip unreachable peers
            logger.warning("trace-spans: collection from rank %d failed "
                           "(%s); the trace will omit it", dst, exc)
            continue
        merged.extend(telemetry.align_spans(spans, offset))
        ranks_seen += 1
    chrome_trace.dump_trace(merged, args.trace_spans)
    logger.info("trace-spans: %d span(s) from %d rank(s) -> %s (load in "
                "ui.perfetto.dev; report: python tools/trace_report.py %s)",
                len(merged), ranks_seen, args.trace_spans, args.trace_spans)


def _maybe_heal(args, sched, world_size: int, rnd: int,
                schedules, sched_idx: int) -> None:
    """One heal decision at a round boundary (`--on-peer-rejoin heal`,
    data rank only): if the capacity the episode lost is restorable —
    every rank the pre-failure schedule names is alive again, or idle
    ranks allow a re-expansion (sched/failover.py `plan_rejoin`) — clear
    the bench so the next round runs the fleet at full capacity, and
    close the episode's time-to-full-capacity clock. A restore needs no
    schedule rewrite (each remaining round's own schedule replans clean
    once the bench is empty); a genuine RE-EXPANSION is written over the
    remaining rounds, since no original schedule expresses it. The heal
    line reports the schedule the next round will ACTUALLY run. A
    rejoiner that cannot restore capacity yet simply stays a spare and
    the heal stays pending for a later boundary."""
    from pipeedge_tpu.sched import failover as failover_sched

    with dead_lock:
        dead_now = set(dead_ranks)
    pre = _heal_state["pre_failure"]
    healed = failover_sched.plan_rejoin(sched, pre, world_size, dead_now,
                                        align=4 if args.stage_tp > 1 else 1)
    if healed is None:
        logger.info("heal: capacity not restorable yet (dead=%s); the "
                    "rejoined rank stays a spare", sorted(dead_now))
        return
    restored = pre is not None and healed == (list(pre[0]), list(pre[1]),
                                              list(pre[2]))
    if restored:
        # the next round's own (possibly rebalance-re-cut) schedule runs
        # clean once the bench is empty: report THAT, not the plan
        layers, _quant, ranks = schedules[sched_idx + 1]
    else:
        for j in range(sched_idx + 1, len(schedules)):
            schedules[j] = (list(healed[0]), list(healed[1]),
                            list(healed[2]))
        layers, _quant, ranks = healed
    now = time.monotonic_ns()
    t0 = _heal_state["detect_ns"] or _heal_state["rejoin_ns"] or now
    telemetry.record("rejoin", "heal", t0, now)
    ttfc = (now - t0) / 1e9
    _TTFC.set(ttfc)
    with dead_lock:
        benched_ranks.clear()
    _heal_state["pending"] = False
    _heal_state["pre_failure"] = None
    _heal_state["detect_ns"] = None
    logger.warning("heal: partition %s to full capacity for round "
                   "%d: layers=%s ranks=%s (%.3fs after detection)",
                   "restored" if restored else "re-expanded",
                   rnd, list(layers), list(ranks), ttfc)
    # machine-parseable heal line (tools/chaos_dcn.py and the CI restart
    # smoke key their healed timestamp and final partition on it)
    print(f"heal_round={rnd} "
          f"partition={','.join(f'{l},{r}' for l, r in layers)} "
          f"ranks={','.join(str(r) for r in ranks)} "
          f"time_to_full_capacity_s={ttfc:.3f}", flush=True)


def _abort_no_capacity(ctx, dead_now: set) -> None:
    """Failover found no schedule the survivors can run: fall back to the
    abort semantics, naming the dead rank fleet-wide (death-carrying
    CMD_STOP) so every worker raises instead of waiting for a schedule."""
    dead = sorted(dead_now)[0]
    stop_info[0] = dead
    monitoring.flush()
    try:
        ctx.cmd_broadcast(CMD_STOP, [np.asarray(dead, np.int32)],
                          best_effort=True)
    except OSError:  # pragma: no cover - best_effort already guards
        pass
    stop_event.set()
    raise RuntimeError(
        f"pipeline aborted: rank {dead} died and no spare capacity "
        "remains to fail over (set --on-peer-death abort to skip the "
        "re-schedule attempt)")


def _make_tp_stage(args, l, r, stage, dtype, restored):
    """Build a stage whose blocks are Megatron-TP-sharded over this rank's
    local devices (--stage-tp N): hierarchical parallelism the reference
    cannot express — pipeline over DCN across hosts, tensor parallelism over
    ICI within each host (SURVEY.md §2.4 'composes with the pipeline').

    Returns `(fn, params)` with the work_cb calling convention
    `fn(params, payload)`; the TP block params live pre-sharded in the
    closure, so `params` is empty."""
    import jax
    from jax.sharding import Mesh

    from pipeedge_tpu.parallel import tensor as tp

    n_tp = args.stage_tp
    local = jax.local_devices()
    if len(local) < n_tp:
        raise RuntimeError(f"--stage-tp {n_tp}: only {len(local)} local "
                           "devices on this rank")
    entry = registry.get_model_entry(args.model_name)
    cfg = entry.config
    if cfg.num_attention_heads % n_tp or cfg.intermediate_size % n_tp \
            or cfg.kv_heads % n_tp:
        raise RuntimeError(
            f"--stage-tp {n_tp} must divide attention heads "
            f"({cfg.num_attention_heads}), kv heads ({cfg.kv_heads}), "
            f"and intermediate size ({cfg.intermediate_size})")
    if (l - 1) % 4 or r % 4:
        raise RuntimeError(f"--stage-tp requires block-aligned stages; "
                           f"[{l}, {r}] cuts mid-block")
    _, params, shard_cfg = registry.module_shard_factory(
        args.model_name, args.model_file, l, r, stage=stage, dtype=dtype,
        params=restored, unroll=True)
    mesh = Mesh(np.asarray(local[:n_tp]), ("tp",))
    block_fn = tp.make_tp_block_fn(cfg, mesh)
    # shard block-by-block, dropping each unsharded block as it is placed,
    # so peak memory is the stage + one block rather than two full stages
    blocks = list(params["blocks"])
    params["blocks"] = None
    sharded_blocks = []
    for i, bp in enumerate(blocks):
        sharded_blocks.append(tp.shard_block_params(cfg, bp, mesh))
        blocks[i] = None
    sharded_blocks = tuple(sharded_blocks)
    family = entry.family
    embed_fn = jax.jit(lambda p, x: family.embed(p, x, cfg))
    final_fn = jax.jit(lambda p, x: family.finalize(p, x, cfg))
    embed_p = params.get("embeddings")
    final_p = params.get("final")
    logger.info("stage %d: %d block(s) TP-sharded over %d local devices",
                stage, len(sharded_blocks), n_tp)

    def stage_fn(_params, x):
        if shard_cfg.is_first:
            x = embed_fn(embed_p, x)
        for bp in sharded_blocks:
            x = block_fn(bp, x)
        if shard_cfg.is_last:
            x = final_fn(final_p, x)
        return x

    return stage_fn, {}


def _handle_corrupt_results(ctx, src: int, channel: int, exc) -> None:
    """BELT-AND-BRACES handler: with --wire-crc the transport reader
    verifies and recovers corrupt frames before they ever reach a
    consumer, so this only fires on a config mismatch (producer armed
    CRC, this receiver's PIPEEDGE_WIRE_CRC off). Count it, note it, and
    request a latest-frame resend (no seq is known here). In failover
    mode the ledger dedupes and re-orders the replayed frame by
    microbatch id; without a ledger FIFO label pairing may shift by one
    — the same caveat DCN_SEND_RETRIES carries outside failover mode."""
    from pipeedge_tpu.comm import dcn
    dcn.FRAMES_CORRUPT.inc(peer=str(src))
    flight.note("frame_corrupt", peer=src, error=str(exc))
    logger.error("results: corrupt frame from rank %d (%s); requesting "
                 "resend", src, exc)
    try:
        ctx.request_resend(src, channel)
    except OSError as rexc:
        logger.error("resend request to rank %d failed: %s", src, rexc)


def _dcn_round(args, ctx, rnd, stage_layers, stage_quant, stage_ranks,
               ubatches, labels, dtype, results_target,
               ledger: Optional[_MicrobatchLedger] = None,
               stop_base: Optional[int] = None) -> Optional[str]:
    """One schedule round on a live DCN fleet: (data rank) broadcast the
    schedule, build this rank's stage if it is in the schedule, stream the
    batch, stop; (worker) build, run until this round's CMD_STOP.

    With a `ledger` (failover mode at the data rank) every frame carries a
    leading microbatch-id tensor, only unacknowledged microbatches are fed,
    and a mid-round stage death ends the round with status "failover"
    (survivor results drained) instead of raising — the caller re-schedules
    and replays. Returns "ok" on completion, "failover" on a survivable
    death, None on worker ranks."""
    import jax.numpy as jnp

    from pipeedge_tpu.comm import dcn

    rank, data_rank = args.rank, args.data_rank
    failover_mode = args.on_peer_death == "failover"
    # frame integrity (--wire-crc / PIPEEDGE_WIRE_CRC): v2 frames carry a
    # checksum trailer, verified before decode; a corrupt frame requests
    # one bounded resend over the control channel. NaN guard
    # (PIPEEDGE_NAN_GUARD=1): activations checked at stage boundaries.
    wire_crc = getattr(args, "wire_crc", False) or _wire_crc_enabled()
    guard_on = nan_guard.nan_guard_enabled()
    # cross-round frame isolation (see dcn.CHANNEL_ROUND_PARITY)
    parity = dcn.CHANNEL_ROUND_PARITY * (rnd % 2)
    # an ABORTING death is terminal for the whole run — stop_info is never
    # reset, so a death notification landing between rounds cannot be
    # erased (failover-mode deaths live in dead_ranks instead)
    if stop_info[0] is not None:
        raise RuntimeError(f"rank {rank}: pipeline aborted: rank "
                           f"{stop_info[0]} died")
    # fresh round state BEFORE the schedule goes out: once peers have the
    # schedule they may finish the round (CMD_STOP) at any time
    t_round0 = time.monotonic_ns()
    stop_event.clear()
    if rank == data_rank:
        # schedule resolved by the caller; broadcast it (CMD_SCHED,
        # reference runtime.py:441-445), skipping confirmed-dead ranks so
        # a failover schedule reaches every survivor without stalling
        with dead_lock:
            gone = set(dead_ranks)
        ctx.cmd_broadcast(CMD_SCHED, [
            np.asarray(stage_layers, np.int32),
            np.asarray(stage_quant, np.int32),
            np.asarray(stage_ranks, np.int32),
            # the global round index: workers derive channel parity and
            # their stop baseline from it, which is what lets a REJOINED
            # worker (who missed earlier rounds) fall into the sequence
            np.asarray(rnd, np.int32)], exclude=gone)

    try:
        my_stages = [i for i, r in enumerate(stage_ranks) if r == rank]
        stage = None
        if my_stages:
            assert len(my_stages) == 1, \
                "one stage per rank (reference p2p semantics)"
            i = my_stages[0]
            l, r = stage_layers[i]
            restored = None
            if args.stage_ckpt:
                # per-stage Orbax restore: this rank reads exactly its
                # own shard from disk (utils/checkpoint.py); validated
                # against the runtime schedule via the manifest
                from pipeedge_tpu.utils import checkpoint as ckpt_utils
                ckpt_utils.check_stage_compatible(
                    args.stage_ckpt, args.model_name, i, (l, r))
                restored = ckpt_utils.load_stage_checkpoint(
                    args.stage_ckpt, i)
            if args.stage_tp > 1:
                if args.tp_quant_bits:
                    # per-round collective accounting: the tally records
                    # traced sites; this round's fold (in the finally
                    # below) must not re-count a previous round's build
                    from pipeedge_tpu.ops import qcollectives
                    qcollectives.reset_trace_tally()
                fn, params = _make_tp_stage(args, l, r, i, dtype, restored)
            else:
                fn, params, _ = registry.module_shard_factory(
                    args.model_name, args.model_file, l, r, stage=i,
                    dtype=dtype, params=restored)
            out_bit = stage_quant[i] if i < len(stage_layers) - 1 else 0
            is_first, is_last = i == 0, i == len(stage_layers) - 1
            if args.stage_tp <= 1:
                # colocated hand-offs INTO this rank land on its compute
                # device (device-to-device move in dcn._put_on_device; a
                # same-device buffer passes through untouched). TP stages
                # keep the default: their jit places inbound host arrays
                # per its own in_shardings, and a forced single-device
                # commit would fight the mesh.
                import jax
                ctx.set_local_device(jax.local_devices()[0])
            # adaptive policy (env ADAPTIVE_QUANT): this rank adapts its
            # own output edge on its own measured 'send' window, exactly
            # the reference's per-rank hook (runtime.py:121-216). The
            # bitwidth travels on the wire, so the consumer needs no
            # coordination.
            edge = None if is_last else _EdgeQuantState(out_bit)
            adaptive = None if edge is None else _make_adaptive_callback(
                [edge], get_window_size())
            ubatch_idx = [0]
            mb_seq = [0]   # dispatch-order fallback mb id (non-failover
            # frames carry no microbatch id on the wire)

            # head stage is fed over the wire from the data rank
            # (self-connection over loopback when colocated) on the FEED
            # channel; the last stage's results ride the RESULTS channel.
            # Distinct channels keep a colocated schedule's feed, edge,
            # and result streams demultiplexed — and keep feed bytes out
            # of the adaptive policies' edge telemetry.
            rank_src = stage_ranks[i - 1] if not is_first else data_rank
            rank_dst = stage_ranks[i + 1] if not is_last else data_rank

            # per-edge bitwidth handshake (control channel): ask the
            # consuming rank what it accepts BEFORE streaming. The frame
            # header still carries the actual bitwidth; `negotiate`
            # below also re-caps any bitwidth the adaptive policy later
            # selects, so the stream never leaves the agreed capability.
            # On timeout keep the proposal (any consumer in this tree
            # can decode any supported bitwidth from the header alone).
            agreed_bits: dict = {0: 0}

            def negotiate(proposed: int, timeout: float = 5.0) -> int:
                agreed = agreed_bits.get(proposed)
                if agreed is None:
                    try:
                        agreed = ctx.negotiate_edge_bits(rank_dst, proposed,
                                                         timeout=timeout)
                        if agreed != proposed:
                            logger.info("edge rank %d->%d: bitwidth "
                                        "negotiated %d -> %d", rank,
                                        rank_dst, proposed, agreed)
                    except queue.Empty:
                        logger.warning(
                            "edge rank %d->%d: bitwidth handshake timed "
                            "out; keeping bit=%d", rank, rank_dst, proposed)
                        agreed = proposed
                    agreed_bits[proposed] = agreed
                _EDGE_BITS.set(agreed, edge=f"{rank}->{rank_dst}")
                return agreed

            if edge is not None and edge.quant_bit:
                edge.quant_bit = negotiate(edge.quant_bit,
                                           timeout=min(30.0,
                                                       args.sched_timeout))

            # transport-tier handshake for this stage's OUTPUT edge
            # (docs/DCN_WIRE.md selection matrix): colocated consumers
            # take device buffers straight off this process's queues —
            # readback then skips the D2H finalize entirely — remote
            # consumers declare zero-copy vs legacy socket. Timeout or
            # an unreachable peer keeps the (always-correct) socket path.
            edge_tier = [None]
            try:
                edge_tier[0] = ctx.negotiate_edge_path(
                    rank_dst, timeout=min(10.0, args.sched_timeout))
                _EDGE_PATH.set(dcn.PATH_CODES[edge_tier[0]],
                               edge=f"{rank}->{rank_dst}")
            except (queue.Empty, OSError) as exc:
                logger.warning("edge rank %d->%d: transport-path "
                               "handshake failed (%s); keeping the "
                               "socket path", rank, rank_dst, exc)

            # Overlapped work contract (DcnPipelineStage dispatch/readback
            # split): dispatch decodes the inbound frame ON device, runs
            # the shard step, and quantizes the output edge ON device
            # (wire v2) — returning with only async D2H copies of the
            # packed payload in flight. Readback (the send thread) drains
            # those copies while THIS thread dispatches the next
            # microbatch: compute, device->host copy, and socket send
            # overlap instead of serializing.
            def dispatch_cb(tensors):
                mbid = None
                if failover_mode:
                    # failover frames lead with the microbatch id: strip it
                    # host-side here, re-attach in readback — the id never
                    # enters the jitted stage step
                    mbid, tensors = tensors[0], tensors[1:]
                if is_first:
                    payload = jnp.asarray(tensors[0], dtype=dtype
                                          if tensors[0].dtype.kind == 'f'
                                          else None)
                else:
                    try:
                        payload = _wire_decode(tensors, dtype)
                    except WireCorruptError as exc:
                        # belt-and-braces: the transport reader verifies
                        # CRC-flagged frames before enqueueing, so this
                        # only fires on a config mismatch (producer
                        # armed, this receiver's PIPEEDGE_WIRE_CRC off).
                        # Drop + request a latest-frame resend; the
                        # replay re-enters this stage's recv loop.
                        dcn.FRAMES_CORRUPT.inc(peer=str(rank_src))
                        flight.note("frame_corrupt", peer=rank_src,
                                    error=str(exc))
                        logger.error("stage %d: corrupt frame from rank "
                                     "%d (%s); requesting resend", i,
                                     rank_src, exc)
                        try:
                            ctx.request_resend(rank_src,
                                               dcn.CHANNEL_DATA + parity)
                        except OSError as rexc:
                            logger.error("resend request to rank %d "
                                         "failed: %s", rank_src, rexc)
                        return dcn.DcnPipelineStage.SKIP
                # mbid is the host-side wire tensor stripped above,
                # never a device array: the asarray cannot sync
                mb = (int(np.asarray(mbid).reshape(-1)[0])  # pipelint: disable=PL303
                      if mbid is not None else mb_seq[0])
                mb_seq[0] += 1
                if guard_on:
                    # opt-in NaN/Inf guard at the stage INPUT boundary: a
                    # poisoned microbatch dies loudly here (named error +
                    # postmortem bundle) instead of propagating garbage.
                    # The check is a host sync — exactly why it is opt-in.
                    payload = nan_guard.check_finite(  # pipelint: disable=PL303
                        payload, where=f"stage{i}/input", mb=mb)
                # compute span: host dispatch of the jitted shard step
                # (async under jit — device completion lands in the stage
                # readback span, where the wire payload materializes)
                with telemetry.span("compute", f"stage{i}", stage=i, mb=mb):
                    out = fn(params, payload)
                    pending = _wire_encode_device(
                        out, edge.quant_bit if edge is not None else 0,
                        crc=wire_crc)
                first = out[0] if isinstance(out, tuple) else out
                # keep the raw device output alive through the hand-off
                # queue ONLY when the adaptive policy will read it — at
                # depth N it would otherwise pin N extra microbatches of
                # unquantized activations in device memory
                return (pending, out if adaptive is not None else None,
                        int(first.shape[0]), mbid)

            def readback_cb(item):
                pending, out, n_items, mbid = item
                if edge_tier[0] == dcn.PATH_LOCAL:
                    # colocated consumer: hand the DEVICE buffers off
                    # as-is — no D2H readback, no serialize; the frame
                    # metadata rides the local queue (send_tensors'
                    # negotiated local path)
                    wire = list(pending.parts)
                else:
                    wire = pending.finalize()   # completes the async copies
                # beat-to-beat measurement (no iteration_start: dispatch
                # runs on another thread): in steady state the interval
                # between retiring microbatches IS the per-ubatch time.
                # The round build reset the key's beat baseline, so the
                # first beat never swallows the inter-round gap.
                monitoring.iteration(MONITORING_KEY_MODEL, work=n_items,
                                     accuracy=r - l + 1, safe=False)
                if adaptive is not None:
                    adaptive(ubatch_idx[0], out)
                    ubatch_idx[0] += 1
                    # re-cap an adaptive move to what the consumer agreed
                    # to accept (the handshake's promise); answers are
                    # cached, so steady-state windows cost no extra RTT
                    if edge.quant_bit:
                        edge.quant_bit = negotiate(edge.quant_bit)
                if mbid is not None:
                    # NOT ascontiguousarray: it would promote the 0-d id
                    # to 1-d (recv-side arrays are already contiguous)
                    wire = [np.asarray(mbid)] + list(wire)
                return wire

            stage = dcn.DcnPipelineStage(
                ctx, rank_src, rank_dst,
                dispatch_cb=dispatch_cb, readback_cb=readback_cb,
                # failover frames lead with the global microbatch id: tag
                # the stage spans with it so replays trace correctly
                mb_of=((lambda ts: int(np.asarray(ts[0]).reshape(-1)[0]))
                       if failover_mode else None),
                # stage-tagged spans: per-stage busy tracks on the merged
                # trace AND the digest windows the rebalancer consumes
                stage=i,
                depth=args.stage_depth or None,
                recv_channel=(dcn.CHANNEL_FEED if is_first
                              else dcn.CHANNEL_DATA) + parity,
                send_channel=(dcn.CHANNEL_RESULTS if is_last
                              else dcn.CHANNEL_DATA) + parity)
            # fresh beat baseline per round: the beat-to-beat 'shard'
            # measurement must not record the inter-round gap (model
            # build, restore, handshake) as its first iteration
            monitoring.iteration_reset(MONITORING_KEY_MODEL)
            stage.start()
        else:
            logger.info("rank %d not in schedule; idling", rank)

        if rank == data_rank:
            if ledger is None:
                for lb in labels:
                    label_queue.put(lb)
                feed_items = None
            else:
                # only unacknowledged microbatches are (re)fed; labels are
                # delivered by the ledger in microbatch order
                feed_items = ledger.pending()
            first_rank = stage_ranks[0]
            last_rank = stage_ranks[-1]

            # transport tier for the FEED edge (data rank -> head stage):
            # when the head stage is colocated — the common `-r 0,...`
            # layout puts stage 0 on the data rank itself — raw inputs
            # hand off in-process instead of riding a loopback socket
            # round trip per microbatch
            try:
                feed_tier = ctx.negotiate_edge_path(
                    first_rank, timeout=min(10.0, args.sched_timeout))
                _EDGE_PATH.set(dcn.PATH_CODES[feed_tier],
                               edge=f"{rank}->{first_rank}:feed")
            except (queue.Empty, OSError) as exc:
                logger.warning("feed edge rank %d->%d: transport-path "
                               "handshake failed (%s); keeping the "
                               "socket path", rank, first_rank, exc)

            def death_hits_schedule() -> bool:
                # a dead IDLE spare is recorded but must not tear down a
                # healthy round (the rebuild + replay cost is real); only
                # a death among this round's stage ranks fails it over.
                # A SCHEDULED rank sitting in benched_ranks is lost too:
                # restart@K:MS can re-exec the victim fast enough that
                # its JOIN is admitted (dead -> benched) BEFORE this
                # loop's next 0.5s poll observes the death — the fresh
                # incarnation holds no stage state and the in-flight
                # microbatches died with the old one, so waiting on the
                # original schedule would ride out the full sched
                # timeout (the test_chaos_restart_rejoins_and_heals
                # flake). Every call site pairs this check with
                # failover_event, so a benched rank only fails a round
                # during an open death episode — never a healthy run.
                with dead_lock:
                    lost = set(dead_ranks) | set(benched_ranks)
                    return bool(lost & set(stage_ranks))

            def results_loop():
                # wire Mbits/time are measured by the transport recv
                # hooks (_register_dcn_monitor_hooks) on the reader
                # thread; this loop only consumes decoded results
                if ledger is not None:
                    # failover mode: keep acking until the ledger is full
                    # or the round is torn down — including the drain
                    # window after a death, when survivors' in-flight
                    # results are still arriving
                    while not stop_event.is_set() \
                            and not ledger.done.is_set():
                        try:
                            # traced variant: the producing incarnation's
                            # epoch keys the ledger's epoch-aware dedupe
                            # (stale incarnations are fenced at the
                            # reader; this is the ledger's own guard),
                            # and the trace context the feed minted rides
                            # the whole loop back — the retire span
                            # closes the request's fleet-wide timeline
                            tensors, epoch, tctx = ctx.recv_tensors_traced(
                                last_rank, timeout=0.5,
                                channel=dcn.CHANNEL_RESULTS + parity)
                        except queue.Empty:
                            continue
                        except ConnectionError:
                            return
                        mbid = int(np.asarray(tensors[0]).reshape(-1)[0])
                        rid = (tctx.rid if tctx is not None
                               else ledger.trace_of(mbid))
                        try:
                            with telemetry.span("results", "deliver",
                                                mb=mbid, rid=rid):
                                out = _wire_decode(tensors[1:], dtype)
                                if guard_on:
                                    out = nan_guard.check_finite(
                                        out, where="results", mb=mbid,
                                        rid=rid)
                                # the ledger retains the DECODED result,
                                # not the wire views — and a pooled recv
                                # buffer is recycled only when nothing
                                # references it (dcn._RecvBufferPool), so
                                # even a retained view could never be
                                # overwritten
                                if not ledger.ack(mbid, np.asarray(out),
                                                  epoch=epoch,
                                                  src=last_rank):
                                    logger.info("failover: duplicate "
                                                "result for microbatch "
                                                "%d dropped", mbid)
                                else:
                                    # periodic snapshot: keeps the replay
                                    # a mid-round death would trigger
                                    # bounded to the unacked window
                                    ledger.maybe_snapshot()
                        except WireCorruptError as exc:
                            # the resent frame re-enters this loop and
                            # acks by id — exactly-once holds
                            _handle_corrupt_results(
                                ctx, last_rank,
                                dcn.CHANNEL_RESULTS + parity, exc)
                    return
                for mbid in range(len(ubatches)):
                    if stop_event.is_set():
                        return
                    try:
                        tensors, _, tctx = ctx.recv_tensors_traced(
                            last_rank, timeout=args.sched_timeout,
                            channel=dcn.CHANNEL_RESULTS + parity)
                    except (queue.Empty, ConnectionError):
                        # timeout, or the last stage died: the peer-death
                        # handler aborts the run; just stop consuming
                        return
                    try:
                        with telemetry.span("results", "deliver", mb=mbid,
                                            rid=tctx.rid if tctx else None):
                            out = _wire_decode(tensors, dtype)
                            if guard_on:
                                out = nan_guard.check_finite(
                                    out, where="results", mb=mbid)
                            handle_results(np.asarray(out))
                    except WireCorruptError as exc:
                        # the replayed frame is consumed by a later
                        # iteration of this loop (count stays whole)
                        _handle_corrupt_results(
                            ctx, last_rank, dcn.CHANNEL_RESULTS + parity,
                            exc)

            results_thread = threading.Thread(target=results_loop,
                                              daemon=True)
            results_thread.start()
            def feed_loop():
                # feeding runs on its own thread: a send backpressured by a
                # stalled pipeline can block in the kernel indefinitely, and
                # the main thread must stay free to abort (peer death) and
                # broadcast CMD_STOP. On send failure the transport's
                # peer-death handler aborts the run; just stop feeding.
                # request dimension of the batch world: each microbatch
                # is a "request" with a fleet-unique id — the trace
                # context rides every hop's frame, the ledger records the
                # rid<->mbid mapping, and trace_report --request replays
                # the admit(feed)->stages->retire timeline across ranks.
                # Minted only when span recording is on: untraced rounds
                # send byte-identical v2 frames.
                def trace_for(mbid):
                    if not telemetry.enabled():
                        return None
                    tctx = telemetry.TraceContext(
                        f"r{rnd}.mb{mbid}", "batch",
                        parent=f"feed.rank{rank}")
                    if ledger is not None:
                        ledger.record_trace(mbid, tctx.rid)
                    return tctx

                try:
                    if ledger is not None:
                        for mbid, u in feed_items:
                            if stop_event.is_set() or (
                                    failover_event.is_set()
                                    and death_hits_schedule()):
                                return
                            tctx = trace_for(mbid)
                            with telemetry.span("feed", f"mb{mbid}",
                                                mb=mbid,
                                                rid=tctx.rid
                                                if tctx else None):
                                ctx.send_tensors(
                                    first_rank,
                                    [np.asarray(mbid, np.int64),
                                     np.asarray(u)],
                                    channel=dcn.CHANNEL_FEED + parity,
                                    trace=tctx)
                        return
                    for mbid, u in enumerate(ubatches):
                        if stop_event.is_set():
                            return
                        tctx = trace_for(mbid)
                        with telemetry.span("feed", f"mb{mbid}", mb=mbid,
                                            rid=tctx.rid if tctx
                                            else None):
                            ctx.send_tensors(first_rank, [np.asarray(u)],
                                             channel=dcn.CHANNEL_FEED
                                             + parity, trace=tctx)
                except OSError as exc:
                    logger.error("feeding stage rank %d failed (%s)",
                                 first_rank, exc)

            failed_over = False
            try:
                tik = time.monotonic()
                batch_total = sum(len(u) for u in ubatches)
                # results_counter is cumulative across rounds
                results_target[0] += batch_total
                target = results_target[0]
                feed_thread = threading.Thread(target=feed_loop, daemon=True)
                feed_thread.start()
                # poll so a peer-death stop aborts the wait immediately
                # instead of riding out the full --sched-timeout
                deadline = time.monotonic() + args.sched_timeout
                complete = False
                # stop_info guards the window where a death notification
                # lands just before this round cleared stop_event
                while not complete and time.monotonic() < deadline \
                        and not stop_event.is_set() \
                        and stop_info[0] is None:
                    if ledger is not None and failover_event.is_set() \
                            and death_hits_schedule():
                        break
                    if ledger is not None:
                        complete = ledger.done.wait(timeout=0.5)
                    else:
                        complete = results_counter.wait_gte(target,
                                                            timeout=0.5)
                if ledger is not None:
                    if not complete and failover_event.is_set() \
                            and death_hits_schedule():
                        # drain the survivors: in-flight results keep
                        # landing for a moment after the death; wait until
                        # the ack stream goes quiet before tearing down
                        quiet_at = ledger.acked_count
                        drain_deadline = time.monotonic() + 5.0
                        while time.monotonic() < drain_deadline:
                            time.sleep(0.4)
                            now_acked = ledger.acked_count
                            if now_acked == quiet_at:
                                break
                            quiet_at = now_acked
                        failed_over = not ledger.done.is_set()
                    complete = ledger.done.is_set()
                else:
                    # last results can land concurrently with an abort
                    complete = complete or results_counter.wait_gte(
                        target, timeout=0)
                tok = time.monotonic()
            finally:
                # CMD_STOP must go out even on failure, or the workers
                # hang until their own timeouts
                ctx.cmd_broadcast(CMD_STOP)
                stop_event.set()
            results_thread.join(timeout=10)
            feed_thread.join(timeout=10)
            if failed_over:
                monitoring.flush()
                return "failover"
            if not complete:
                if ledger is not None and failover_event.is_set() \
                        and death_hits_schedule():
                    monitoring.flush()
                    return "failover"
                # results_counter is cumulative; report this round's share
                delivered = (ledger.acked_count if ledger is not None else
                             results_counter.value - (target - batch_total))
                if stop_info[0] is not None:
                    raise RuntimeError(
                        f"pipeline aborted: rank {stop_info[0]} died "
                        f"mid-run ({delivered}/{batch_total} "
                        "results delivered)")
                raise RuntimeError(
                    f"pipeline delivered {delivered}/"
                    f"{batch_total} results within {args.sched_timeout}s")
            _report(tik, tok, ubatches)
            return "ok"
        else:
            # wait on the stop COUNT, not the event: this round ends at
            # the first CMD_STOP after its schedule arrived (stop_base =
            # stops counted when the CMD_SCHED landed, paired in
            # handle_cmd) — a stop that lands while this worker is still
            # tearing down the previous round is counted, not lost, and a
            # REJOINED worker who missed earlier rounds' stops needs no
            # absolute history. Poll so a LOCALLY detected death (own
            # send failed; own broadcast skips self, so stop_counter
            # never moves) also aborts promptly.
            target = (stop_base + 1) if stop_base is not None else rnd + 1
            deadline = time.monotonic() + args.sched_timeout
            stopped = False
            while not stopped and stop_info[0] is None \
                    and time.monotonic() < deadline:
                stopped = stop_counter.wait_gte(target, timeout=0.5)
            if stop_info[0] is not None:
                raise RuntimeError(
                    f"rank {rank}: pipeline aborted: rank "
                    f"{stop_info[0]} died mid-run")
            if not stopped:
                raise RuntimeError(
                    f"rank {rank}: no CMD_STOP within "
                    f"{args.sched_timeout}s; aborting")
    finally:
        # the round track frames every other span of this round on the
        # merged timeline (trace_report's window)
        telemetry.record("runtime", f"round{rnd}", t_round0,
                         time.monotonic_ns())
        if stage is not None:
            stage.stop()
            if args.stage_tp > 1 and args.tp_quant_bits:
                # fold this stage's quantized-collective wire footprint,
                # STAGE-TAGGED (the per-stage bits-moved attribution the
                # trace report's collectives section promises): one
                # shared block trace per stage, executed once per block
                # per dispatched microbatch
                from pipeedge_tpu.ops import qcollectives
                summary = qcollectives.record_collectives(
                    executions=mb_seq[0] * max(1, (r - l + 1) // 4),
                    stage=i)
                qcollectives.reset_trace_tally()
                logger.info("rank %d stage %d quantized collectives "
                            "(--tp-quant-bits %d): %s", rank, i,
                            args.tp_quant_bits, summary)


def _report(tik, tok, ubatches):
    batch_size = sum(len(u) for u in ubatches)
    latency = tok - tik
    throughput = batch_size / latency if latency > 0 else 0
    logger.info("Latency: %f seconds", latency)
    logger.info("Throughput: %f items/sec", throughput)
    print(f"latency_sec={latency:.6f} throughput_items_sec={throughput:.3f}")


def main():
    parser = argparse.ArgumentParser(
        description="Pipeline-parallel inference runtime (TPU-native)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("rank", type=int, help="must be 0 (single controller)")
    parser.add_argument("worldsize", type=int,
                        help="number of pipeline stages (devices)")
    parser.add_argument("-c", "--comm", type=str, default="host",
                        choices=["host", "spmd", "dcn", "p2p", "rpc"],
                        help="pipeline driver; dcn = multi-process TCP "
                             "transport (one rank per process, reference "
                             "p2p semantics); p2p/rpc are host aliases")
    parser.add_argument("-m", "--model-name", type=str,
                        default="google/vit-base-patch16-224",
                        choices=registry.get_model_names())
    parser.add_argument("-M", "--model-file", type=str,
                        help="model weights file (.npz)")
    parser.add_argument("--stage-ckpt", type=str, default=None, metavar="DIR",
                        help="per-stage Orbax checkpoint root (from "
                             "tools/convert_checkpoint.py); each dcn rank "
                             "restores only its own stage shard")
    parser.add_argument("-b", "--batch-size", default=64, type=int)
    parser.add_argument("-u", "--ubatch-size", default=8, type=int)
    parser.add_argument("-t", "--dtype", default="float32",
                        choices=["float32", "bfloat16"])
    # scheduling (reference runtime.py:657-687)
    parser.add_argument("-pt", "--partition", type=str,
                        help="comma-delimited layer pairs, e.g. '1,24,25,48';"
                             " ';'-separated values define live re-schedule "
                             "rounds (dcn only)")
    parser.add_argument("-q", "--quant", type=str,
                        help="comma-delimited per-stage output quant bitwidths"
                             " (';'-separated per re-schedule round)")
    parser.add_argument("-r", "--rank-order", type=str, default=None,
                        help="comma-delimited stage-to-device mapping")
    parser.add_argument("-D", "--data-rank", type=int, default=0,
                        help="rank that drives data/results (dcn mode); "
                             "single-controller drivers always use the host")
    parser.add_argument("--dcn-addrs", type=str, default=None,
                        help="comma-delimited host:port listener address per "
                             "rank (dcn mode); default 127.0.0.1:PORT+rank")
    parser.add_argument("-P", "--port", type=int, default=29600,
                        help="base listener port for dcn mode defaults")
    parser.add_argument("--spmd-dp", type=int, default=1,
                        help="data-parallel mesh axis for the spmd driver "
                             "(devices needed = stages x dp x (tp or sp))")
    parser.add_argument("--spmd-tp", type=int, default=1,
                        help="Megatron tensor-parallel mesh axis for the "
                             "spmd driver: blocks stage-sharded AND "
                             "tp-sharded in one XLA program")
    parser.add_argument("--spmd-sp", type=int, default=1,
                        help="sequence-parallel mesh axis for the spmd "
                             "driver: activations sequence-sharded, exact "
                             "ring attention per block (long-context "
                             "pipelines); exclusive with --spmd-tp")
    parser.add_argument("--spmd-sp-kind", default="ring",
                        choices=["ring", "ulysses"],
                        help="sp attention core: K/V ring rotation or "
                             "Ulysses all-to-all head resharding")
    parser.add_argument("--stage-tp", type=int, default=1,
                        help="shard each dcn stage's blocks Megatron-style "
                             "over N local devices (block-aligned stages): "
                             "pipeline across hosts over DCN, tensor "
                             "parallelism within each host")
    parser.add_argument("--tp-quant-bits", type=int, default=0,
                        choices=[0, 8, 4],
                        help="bitwidth of intra-stage TP/SP collectives "
                             "(EQuARX-style quantized allreduce/all-gather "
                             "over ICI, ops/qcollectives.py): 0 = exact "
                             "full-width psum/all_gather; 8/4 = block-"
                             "scaled int8/int4 ring collectives with an "
                             "f32 accumulator. Gates every tensor.py psum "
                             "site (--spmd-tp, --stage-tp) and the "
                             "sequence-parallel gather (--spmd-sp); see "
                             "docs/QUANT_COLLECTIVES.md")
    parser.add_argument("--stage-depth", type=int, default=0,
                        help="dcn stage pipelining depth: microbatches "
                             "buffered per hand-off queue, letting the next "
                             "microbatch's compute overlap the previous "
                             "one's device->host readback and socket send "
                             "(0 = env DCN_STAGE_DEPTH or 2; 1 restores the "
                             "serialized pre-overlap behavior)")
    parser.add_argument("--sched-timeout", type=float, default=300,
                        help="seconds a worker waits for the schedule / "
                             "results / stop (dcn mode)")
    parser.add_argument("--rebalance", default="off",
                        choices=["off", "auto"],
                        help="closed-loop rebalancing from live telemetry "
                             "(docs/REBALANCE.md). dcn mode: the data rank "
                             "re-solves the layer partition each round from "
                             "measured per-stage timings (span digests over "
                             "the command channel) and applies it at the "
                             "next round boundary via CMD_SCHED — pass the "
                             "flag to every rank. host mode with "
                             "--measure-rounds > 1: adapt the microbatch "
                             "size to the measured steady-state stage time "
                             "vs fill/drain overhead")
    parser.add_argument("--rebalance-threshold", type=float, default=0.10,
                        help="minimum predicted relative bottleneck gain "
                             "before a re-partition is applied (hysteresis: "
                             "a balanced fleet never churns)")
    parser.add_argument("--rebalance-cooldown", type=int, default=1,
                        help="full rounds to wait after a rebalance before "
                             "considering another (no oscillation while "
                             "the previous re-plan is still being measured)")
    parser.add_argument("--rebalance-confirm", type=int, default=1,
                        help="extra consecutive windows that must blame the "
                             "SAME bottleneck stage before a re-partition "
                             "is applied (filters round-to-round drift; a "
                             "real straggler persists; 0 = act on the "
                             "first actionable window)")
    parser.add_argument("--rounds", type=int, default=1,
                        help="dcn mode: run the schedule this many rounds "
                             "(same batch each round) — the boundaries "
                             "--rebalance auto re-plans at; equivalent to "
                             "repeating the schedule with ';'")
    parser.add_argument("--on-peer-death", default="abort",
                        choices=["abort", "failover"],
                        help="dcn mode reaction to a stage rank dying "
                             "mid-run: abort the fleet (default, the "
                             "pre-failover semantics) or re-schedule over "
                             "the survivors and replay unacknowledged "
                             "microbatches (must be uniform across the "
                             "fleet; results are exactly-once by "
                             "microbatch id)")
    parser.add_argument("--on-peer-rejoin", default="spare",
                        choices=["ignore", "spare", "heal"],
                        help="dcn mode reaction to a confirmed-dead rank "
                             "passing the JOIN admission handshake (a "
                             "restarted incarnation with a higher "
                             "DCN_EPOCH): ignore refuses re-admission "
                             "(deaths stay terminal), spare re-admits it "
                             "as live idle capacity for FUTURE failovers, "
                             "heal additionally restores the pre-failure "
                             "partition (or re-expands onto the restored "
                             "rank) at the next round boundary — "
                             "docs/FAULT_TOLERANCE.md")
    parser.add_argument("--on-peer-degraded", default="ignore",
                        choices=["ignore", "quarantine"],
                        help="dcn mode reaction to a GRAY-failing peer — "
                             "alive and beating, but its EWMA health "
                             "score (relative stage service time, "
                             "heartbeat RTT, send retries) confirmed a "
                             "straggler: ignore scores and reports only; "
                             "quarantine benches the rank at the next "
                             "round boundary (a planned drain — its "
                             "stage moves to a spare via the failover "
                             "re-plan, no replay) and readmits it "
                             "through probation when the score recovers. "
                             "Forces span recording on (the scorer reads "
                             "the rebalancer's digest windows); pass the "
                             "flag to every rank — "
                             "docs/FAULT_TOLERANCE.md gray failures")
    parser.add_argument("--degraded-threshold", type=float, default=0.4,
                        help="EWMA degradation score at which a rank "
                             "turns suspect (readmit threshold is half "
                             "this: the hysteresis band)")
    parser.add_argument("--degraded-confirm", type=int, default=2,
                        help="consecutive bad windows AFTER the suspect "
                             "entry before quarantine (false-positive "
                             "protection; the entry window never "
                             "convicts alone)")
    parser.add_argument("--degraded-readmit", type=int, default=2,
                        help="consecutive recovered windows before a "
                             "quarantined rank readmits on probation")
    parser.add_argument("--autoscale-ranks", default="off",
                        choices=["off", "advise", "auto"],
                        help="dcn mode closed-loop capacity control over "
                             "the pipeline partition (the rank-level "
                             "half of serving/autoscale.py): scale-up "
                             "expands onto idle survivors via the "
                             "plan_rejoin cascade at a round boundary, "
                             "scale-down benches the least-needed rank "
                             "through the failover re-plan (dry-run "
                             "verified — an un-runnable contraction "
                             "renders as `held`). advise logs decisions "
                             "without acting; auto acts. Data rank "
                             "drives; forces span recording on "
                             "(signals come from the rebalancer's "
                             "digest windows)")
    parser.add_argument("--autoscale-min", type=int, default=2,
                        help="stage-count floor the capacity controller "
                             "never contracts below")
    parser.add_argument("--autoscale-max", type=int, default=0,
                        help="stage-count ceiling (0 = world size)")
    parser.add_argument("--autoscale-confirm", type=int, default=2,
                        help="consecutive same-direction measured "
                             "windows before a capacity decision")
    parser.add_argument("--autoscale-cooldown", type=float, default=0.0,
                        help="seconds between capacity decisions "
                             "(reversals double it — the flap damper)")
    parser.add_argument("--autoscale-rank-high", type=float, default=0.75,
                        help="bottleneck stage service seconds per "
                             "microbatch that count as up pressure")
    parser.add_argument("--autoscale-rank-low", type=float, default=0.05,
                        help="bottleneck service seconds below which "
                             "the pipeline counts as over-provisioned")
    parser.add_argument("--wire-crc", action="store_true",
                        help="frame integrity: checksum every wire-v2 "
                             "frame (CRC32C when the wheel is present, "
                             "zlib CRC32 otherwise; algorithm rides the "
                             "frame), verify on receive, and recover a "
                             "corrupt frame with one bounded resend "
                             "over the control channel (cap = max(1, "
                             "DCN_SEND_RETRIES)). Equivalent to env "
                             "PIPEEDGE_WIRE_CRC=1; pass to every rank")
    parser.add_argument("--heartbeat-interval", type=float, default=0.0,
                        help="dcn liveness plane: seconds between heartbeat "
                             "frames to every peer (0 = env "
                             "DCN_HEARTBEAT_INTERVAL or disabled); catches "
                             "HUNG ranks whose sockets stay open")
    parser.add_argument("--heartbeat-miss", type=int, default=0,
                        help="missed-beat threshold before a silent peer "
                             "is declared dead (0 = env DCN_HEARTBEAT_MISS "
                             "or 3)")
    parser.add_argument("--save-results", type=str, default=None,
                        metavar="NPZ",
                        help="save every delivered result microbatch (in "
                             "delivery order) to this .npz — lets chaos "
                             "runs be compared bit-for-bit against "
                             "no-fault runs")
    parser.add_argument("--platform", type=str, default="auto",
                        choices=["auto", "cpu"],
                        help="force the JAX CPU backend (testing multi-"
                             "process dcn pipelines without TPU chips)")
    parser.add_argument("--trace", type=str, default=None, metavar="DIR",
                        help="capture a JAX profiler trace of the run into "
                             "DIR (view with tensorboard/perfetto)")
    parser.add_argument("--trace-spans", type=str, default=None,
                        metavar="OUT",
                        help="record runtime spans (dispatch/compute/"
                             "readback/wire/feed/results/failover) and "
                             "write a merged Perfetto-loadable trace JSON "
                             "to OUT. In dcn mode the data rank gathers "
                             "every rank's spans over the command channel "
                             "with NTP-style clock alignment (pass the "
                             "flag to every rank); analyze with "
                             "tools/trace_report.py")
    parser.add_argument("--measure-rounds", type=int, default=1,
                        help="host driver: run the ubatch stream this many "
                             "times, printing a latency line per round "
                             "(round 0 includes the XLA compiles; later "
                             "rounds measure the warm pipeline)")
    parser.add_argument("-sm", "--sched-models-file", default=None, type=str)
    parser.add_argument("-sdt", "--sched-dev-types-file", default=None, type=str)
    parser.add_argument("-sd", "--sched-dev-file", default=None, type=str)
    parser.add_argument("-H", "--hosts", type=str,
                        help="comma-delimited hosts/chips for schedule mapping")
    # dataset (reference runtime.py:688-705)
    parser.add_argument("--dataset-name", type=str, default="synthetic",
                        choices=["synthetic", "ImageNet", "CoLA"])
    parser.add_argument("--dataset-root", type=str)
    parser.add_argument("--dataset-split", default='val', type=str)
    parser.add_argument("--dataset-indices-tsv", type=str,
                        help="TSV file with dataset indices to use")
    parser.add_argument("--dataset-shuffle", action="store_true")
    args = parser.parse_args()

    if args.platform == "cpu":
        from pipeedge_tpu.utils import force_host_cpu_devices
        force_host_cpu_devices(max(1, args.worldsize))

    if args.stage_ckpt and args.comm != "dcn":
        parser.error("--stage-ckpt is a dcn-mode option (per-rank restore); "
                     "single-controller drivers load via -M/--model-file")

    if args.rank != 0 and args.comm != "dcn":
        logger.warning("Single-controller runtime: only rank 0 runs; "
                       "rank %d exits immediately (all devices are driven "
                       "from rank 0). Use --comm dcn for one-process-per-"
                       "rank operation.", args.rank)
        return

    hosts = args.hosts.split(',') if args.hosts else None
    indices = None
    if args.dataset_indices_tsv:
        with open(args.dataset_indices_tsv) as f:
            indices = [int(line.split('\t')[0]) for line in f if line.strip()]

    # ';'-separated -pt/-q/-r values define multiple schedule ROUNDS: the
    # dcn fleet re-schedules live at each run boundary (CMD_SCHED). A single
    # value applies to every round.
    pt_rounds = args.partition.split(';') if args.partition else [None]
    q_rounds = args.quant.split(';') if args.quant else [None]
    r_rounds = args.rank_order.split(';') if args.rank_order else [None]
    n_rounds = max(len(pt_rounds), len(q_rounds), len(r_rounds))
    if n_rounds > 1 and args.comm != "dcn":
        parser.error("';'-separated re-schedule rounds require --comm dcn")
    if args.rounds < 1:
        parser.error("--rounds must be >= 1")
    if args.rounds > 1:
        if args.comm != "dcn":
            parser.error("--rounds requires --comm dcn (use "
                         "--measure-rounds for the host driver)")
        if n_rounds > 1:
            parser.error("--rounds cannot combine with ';'-separated "
                         "re-schedule rounds (pick one)")
    if args.rebalance == "auto":
        if args.comm == "spmd":
            parser.error("--rebalance auto applies to the dcn driver "
                         "(partition re-solve) and the host driver "
                         "(adaptive microbatching), not spmd")
        if len(set(pt_rounds)) > 1:
            # the rebalancer assumes rounds repeat the same workload; it
            # would silently overwrite deliberately distinct partitions
            parser.error("--rebalance auto cannot combine with distinct "
                         "';'-separated partitions")
        if args.stage_ckpt:
            # the per-stage checkpoint manifest pins the partition; a
            # re-cut would fail every rank's compatibility check on the
            # next round's restore
            parser.error("--rebalance auto cannot combine with "
                         "--stage-ckpt (the checkpoint manifest pins the "
                         "partition)")
        # a single round leaves no boundary to re-plan at: refuse the
        # silent no-op (matches the validation style of the combinations
        # above)
        if args.comm == "dcn" and args.rounds == 1 and n_rounds == 1:
            parser.error("--rebalance auto needs round boundaries to "
                         "re-plan at: pass --rounds N (or ';'-separated "
                         "schedule rounds)")
        if args.comm != "dcn" and args.measure_rounds <= 1:
            parser.error("--rebalance auto on the host driver adapts the "
                         "microbatch size BETWEEN measure rounds: pass "
                         "--measure-rounds N > 1")
    if args.on_peer_degraded == "quarantine":
        if args.comm != "dcn":
            parser.error("--on-peer-degraded quarantine applies to the "
                         "dcn driver (per-process ranks)")
        # quarantine acts at round boundaries, like --rebalance auto:
        # refuse the silent no-op of a single-round run
        if args.rounds == 1 and n_rounds == 1:
            parser.error("--on-peer-degraded quarantine acts at round "
                         "boundaries: pass --rounds N (or ';'-separated "
                         "schedule rounds)")
    if args.autoscale_ranks != "off":
        if args.comm != "dcn":
            parser.error("--autoscale-ranks applies to the dcn driver "
                         "(per-process ranks)")
        if args.rounds == 1 and n_rounds == 1:
            parser.error("--autoscale-ranks acts at round boundaries: "
                         "pass --rounds N (or ';'-separated schedule "
                         "rounds)")
        if args.autoscale_ranks == "auto" \
                and args.on_peer_death != "failover":
            parser.error("--autoscale-ranks auto needs --on-peer-death "
                         "failover: a planned bench rides the failover "
                         "re-plan cascade (advise mode only observes)")
        if args.autoscale_min < 1:
            parser.error("--autoscale-min must be >= 1")
        if args.autoscale_confirm < 1:
            parser.error("--autoscale-confirm must be >= 1")
    if args.wire_crc:
        # one process-wide switch (env), so the transport's resend cache
        # and chaos corrupt@K see the same setting the codec does
        from pipeedge_tpu.comm.wire import ENV_WIRE_CRC
        os.environ[ENV_WIRE_CRC] = "1"
    if args.tp_quant_bits:
        has_tp_sites = (args.stage_tp > 1
                        or (args.comm == "spmd"
                            and (args.spmd_tp > 1 or args.spmd_sp > 1)))
        if not has_tp_sites:
            parser.error("--tp-quant-bits gates intra-stage TP/SP "
                         "collectives, but no TP axis is active: pass "
                         "--spmd-tp/--spmd-sp > 1 (--comm spmd) or "
                         "--stage-tp > 1 (--comm dcn)")
        # one global trace-time flag (layers.set_fast_numerics idiom):
        # set BEFORE any driver traces a TP block body, and inherited by
        # dcn worker processes through their own arg parse
        from pipeedge_tpu.parallel import tensor as _tensor_flags
        _tensor_flags.set_tp_quant_bits(args.tp_quant_bits)
    if args.stage_tp > 1 and args.comm != "dcn":
        parser.error("--stage-tp requires --comm dcn (per-rank local TP; "
                     "use the spmd driver's mesh axes for single-controller "
                     "tp)")
    if args.stage_tp > 1:
        # fail at parse time, not mid-round after the schedule broadcast
        # (a late failure on one rank strands the rest of the fleet until
        # the peer-death abort)
        cfg = registry.get_model_config(args.model_name)
        if cfg.num_attention_heads % args.stage_tp \
                or cfg.intermediate_size % args.stage_tp \
                or cfg.kv_heads % args.stage_tp:
            parser.error(
                f"--stage-tp {args.stage_tp} must divide attention heads "
                f"({cfg.num_attention_heads}), kv heads ({cfg.kv_heads}), "
                f"and intermediate size ({cfg.intermediate_size}) of "
                f"{args.model_name}")
        for spec in pt_rounds:
            if not spec:
                continue
            nums = [int(x) for x in spec.split(',')]
            for l, r in zip(nums[::2], nums[1::2]):
                if (l - 1) % 4 or r % 4:
                    parser.error(f"--stage-tp requires block-aligned "
                                 f"stages; [{l}, {r}] cuts mid-block")
    for opt, specs in (("-pt", pt_rounds), ("-q", q_rounds),
                       ("-r", r_rounds)):
        if 1 < len(specs) != n_rounds:
            parser.error(f"{opt}: {len(specs)} ';'-rounds given but "
                         f"{n_rounds} rounds defined; give 1 or {n_rounds}")

    def _round_spec(specs, i):
        return specs[i] if len(specs) > 1 else specs[0]

    is_dcn_worker = args.comm == "dcn" and args.rank != args.data_rank
    if is_dcn_worker:
        # schedule arrives via CMD_SCHED; only the data rank loads data
        schedules = []
        stage_layers, stage_quant, stage_ranks = [], [], []
        ubatches, labels = [], []
    else:
        schedules = []
        for i in range(n_rounds):
            partition = None
            pt_spec = _round_spec(pt_rounds, i)
            if pt_spec:
                nums = [int(x) for x in pt_spec.split(',')]
                assert len(nums) % 2 == 0
                partition = list(zip(nums[::2], nums[1::2]))
            q_spec = _round_spec(q_rounds, i)
            quant = [int(x) for x in q_spec.split(',')] if q_spec else None
            r_spec = _round_spec(r_rounds, i)
            rank_order = [int(x) for x in r_spec.split(',')] \
                if r_spec else None
            schedules.append(get_pipeline_sched(
                args.worldsize, hosts, partition, quant, rank_order,
                args.model_name, args.ubatch_size, args.sched_models_file,
                args.sched_dev_types_file, args.sched_dev_file,
                dtype=args.dtype))
        # --rounds N: the single resolved schedule runs N times (the round
        # boundaries --rebalance auto re-plans at)
        schedules = schedules * max(1, args.rounds)
        stage_layers, stage_quant, stage_ranks = schedules[0]

        dataset = load_dataset(
            {'name': args.dataset_name, 'root': args.dataset_root,
             'split': args.dataset_split, 'indices': indices,
             'shuffle': args.dataset_shuffle},
            args.model_name, args.batch_size, args.ubatch_size)
        ubatches, labels = [], []
        for inputs, lbls in data_utils.batch_dataset(dataset, args.ubatch_size):
            ubatches.append(inputs)
            labels.append(lbls)

    window_size = get_window_size()
    monitoring.init(MONITORING_KEY_MODEL, window_size, work_type='items',
                    acc_type='layers')
    monitoring.add_key(MONITORING_KEY_OUTPUT, work_type='classifications',
                       acc_type='correct')
    monitoring.add_key(MONITORING_KEY_SEND, work_type='Mbits')
    monitoring.add_key(MONITORING_KEY_RECV, work_type='Mbits')
    monitoring.add_key(MONITORING_KEY_QUANT_ENCODE, acc_type='bits')
    monitoring.add_key(MONITORING_KEY_QUANT_DECODE, acc_type='bits')
    monitoring.add_key(MONITORING_KEY_LIVENESS, work_type='beats',
                       acc_type='rank')
    monitoring.add_key(MONITORING_KEY_HB_RTT, work_type='ms',
                       acc_type='rank')

    global _results_sink
    if args.save_results and not is_dcn_worker:
        _results_sink = []

    if args.trace_spans or (args.comm == "dcn"
                            and (args.rebalance == "auto"
                                 or args.on_peer_degraded == "quarantine"
                                 or args.autoscale_ranks != "off")):
        # every rank records; in dcn mode the data rank merges the fleet
        # (workers serve their rings over _MSG_SPANS), single-controller
        # drivers write their own single-rank timeline below. The
        # rebalancer's digests come from the same recorder (workers answer
        # _MSG_SPANS digest requests inline), so --rebalance auto records
        # even without a trace destination — and the peer-health scorer
        # (--on-peer-degraded quarantine) reads the same digest windows.
        telemetry.configure(rank=args.rank if args.comm == "dcn" else 0)

    try:
        comm = args.comm
        if comm in ("p2p", "rpc"):
            comm = "host"
        if comm == "spmd":
            try:
                spmd.partition_to_blocks(stage_layers)
            except ValueError as exc:
                logger.warning("%s; falling back to host driver", exc)
                comm = "host"
        from pipeedge_tpu.utils import tracing
        trace_dir = args.trace
        if trace_dir and comm == "dcn":
            # per-rank session dirs: same-host ranks would otherwise clobber
            # each other's hostname-keyed profile files
            trace_dir = os.path.join(trace_dir, f"rank{args.rank}")
        with tracing.trace(trace_dir):
            if comm == "dcn":
                # waits for its own results/stop internally (multi-process)
                run_pipeline_dcn(args, schedules, ubatches, labels)
            elif comm == "spmd":
                run_pipeline_spmd(args, stage_layers, stage_quant,
                                  stage_ranks, ubatches, labels)
            else:
                run_pipeline_host(args, stage_layers, stage_quant, stage_ranks,
                                  ubatches, labels)
        if comm != "dcn":
            assert results_counter.wait_gte(
                sum(len(u) for u in ubatches), timeout=300)
            if args.trace_spans and telemetry.recorder() is not None:
                # single-controller drivers: one rank, no collection pass
                from pipeedge_tpu.telemetry import chrome_trace
                spans = telemetry.recorder().snapshot()
                chrome_trace.dump_trace(spans, args.trace_spans)
                logger.info("trace-spans: %d span(s) -> %s", len(spans),
                            args.trace_spans)
        if _results_sink is not None:
            np.savez(args.save_results,
                     *[np.asarray(o) for o in _results_sink])
            logger.info("saved %d result microbatch(es) to %s",
                        len(_results_sink), args.save_results)
    finally:
        monitoring.finish()


if __name__ == "__main__":
    logging.basicConfig(
        level=logging.INFO,
        handlers=[logging.StreamHandler(sys.stdout),
                  logging.FileHandler("runtime.log", mode='a')])
    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()  # JAX_PLATFORMS=cpu must mean cpu even though the
    # TPU plugin overrides the env var (same guard as every other CLI);
    # --platform cpu additionally forces the virtual device count
    main()
