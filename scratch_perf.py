import time, json
import jax, jax.numpy as jnp, numpy as np
from pipeedge_tpu.models import registry
from pipeedge_tpu.models.shard import make_shard_fn

name = "google/vit-large-patch16-224"
entry = registry.get_model_entry(name)
cfg = entry.config
sc = registry.make_shard_config(name, 1, registry.get_model_layers(name))

def bench(batch, n_ubatch, dtype):
    params = entry.family.init_params(cfg, sc, dtype=dtype)
    fn = make_shard_fn(entry.family.FAMILY, cfg, sc)
    rng = np.random.default_rng(0)
    xs = jax.device_put(jnp.asarray(rng.normal(size=(n_ubatch, batch, 3, 224, 224)), dtype=dtype))
    params = jax.device_put(params)
    @jax.jit
    def run_all(p, xs):
        def step(c, x):
            return c + jnp.sum(fn(p, x).astype(jnp.float32)), None
        t, _ = jax.lax.scan(step, jnp.float32(0), xs)
        return t
    float(run_all(params, xs))
    best = float("inf")
    for _ in range(3):
        t0 = time.monotonic(); float(run_all(params, xs)); best = min(best, time.monotonic()-t0)
    return n_ubatch*batch/best

for batch, n_ub, dt, label in [
    (8, 32, jnp.bfloat16, "b8 bf16 (bench)"),
    (16, 16, jnp.bfloat16, "b16 bf16"),
    (32, 8, jnp.bfloat16, "b32 bf16"),
    (64, 4, jnp.bfloat16, "b64 bf16"),
    (128, 2, jnp.bfloat16, "b128 bf16"),
    (8, 32, jnp.float32, "b8 f32"),
]:
    print(label, round(bench(batch, n_ub, dt), 1), "img/s", flush=True)
