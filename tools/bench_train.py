"""Training-step benchmark: ViT-Large pipeline train step on this chip.

Prints ONE JSON line: images/sec trained, steady step ms, achieved
TFLOP/s and MFU (fwd+bwd ~= 3x forward FLOPs, 2*MAC convention), both
peak denominators — the same overhead-aware methodology as bench.py
(steps CHAIN through the (params, opt_state) carry, so N steps + one
fence amortize the tunnel round trip).

The reference cannot run this benchmark at all: it is inference-only
(@torch.no_grad on every shard forward). Training here is jax.grad
through the one-program SPMD pipeline (parallel/train.py).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model-name", default="google/vit-large-patch16-224")
    p.add_argument("-b", "--batch", default=8, type=int)
    p.add_argument("-u", "--ubatches", default=4, type=int)
    p.add_argument("--steps", default=8, type=int)
    p.add_argument("--mixed-precision", action="store_true",
                   help="f32 master weights + per-step bf16 compute cast "
                        "(parallel/train.py) instead of pure-bf16 params")
    args = p.parse_args()

    from pipeedge_tpu.utils import apply_env_platform, require_live_backend
    apply_env_platform()
    require_live_backend("vit_large_train_images_per_sec", unit="images/sec")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipeedge_tpu.benchkit.headline import (
        NOMINAL_BF16_PEAK, calibrate_peak_flops as _calibrate_peak_flops,
        model_flops_per_image as _model_flops_per_image)
    from pipeedge_tpu.models import ShardConfig, registry
    from pipeedge_tpu.parallel import spmd, train

    cfg = registry.get_model_config(args.model_name)
    total = registry.get_model_layers(args.model_name)
    entry = registry.get_model_entry(args.model_name)
    family_mod = entry.family
    param_dtype = jnp.float32 if args.mixed_precision else jnp.bfloat16
    stage_params = [family_mod.init_params(
        cfg, ShardConfig(1, total, is_first=True, is_last=True),
        dtype=param_dtype)]
    mesh = spmd.make_pipeline_mesh(1)
    # remat: per-block checkpointing — without it the backward's saved
    # tick activations need ~40 GB HBM on ViT-L (measured OOM vs 15.75G)
    pipe = spmd.build_spmd_pipeline(family_mod.FAMILY, cfg, [(1, total)],
                                    stage_params, mesh, remat=True)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(
        size=(args.ubatches, args.batch, 3, cfg.image_size, cfg.image_size)),
        param_dtype)   # mixed mode casts to bf16 inside the step
    y = jnp.asarray(rng.integers(0, max(cfg.num_labels, 1),
                                 size=(args.ubatches, args.batch)), jnp.int32)

    on_tpu = jax.devices()[0].platform != "cpu"
    peak = _calibrate_peak_flops() if on_tpu else None   # 32x 8192^3
    #                       matmuls — pointless (and minutes) on CPU
    step, opt_state = train.make_train_step(
        pipe, optax.sgd(1e-3), x, mixed_precision=args.mixed_precision)
    params = pipe.params
    params, opt_state, loss = step(params, opt_state, x, y)   # compile
    float(loss)                                               # fence
    reps = args.steps
    tik = time.monotonic()
    for _ in range(reps):
        params, opt_state, loss = step(params, opt_state, x, y)
    final_loss = float(loss)                                  # fence
    dt = (time.monotonic() - tik) / reps
    images = args.ubatches * args.batch
    # fwd+bwd: dL/dx costs one fwd-sized pass, dL/dw another
    flops = 3 * _model_flops_per_image(cfg) * images
    achieved = flops / dt
    device_kind = jax.devices()[0].device_kind
    nominal = NOMINAL_BF16_PEAK.get(device_kind)   # bench.py's table
    print(json.dumps({
        "metric": "vit_large_train_images_per_sec",
        "value": round(images / dt, 1),
        "unit": "images/sec",
        "vs_baseline": None,    # the reference cannot train at all
        "step_ms": round(dt * 1e3, 2),
        "images_per_step": images,
        "final_loss": round(final_loss, 4),
        "achieved_tflops": round(achieved / 1e12, 1),
        "mfu_calibrated": round(achieved / peak, 3) if peak else None,
        # both key spellings, matching bench.py's record exactly
        "calibrated_peak_tflops": round(peak / 1e12, 1) if peak else None,
        "peak_calibrated_tflops": round(peak / 1e12, 1) if peak else None,
        "mfu_nominal": round(achieved / nominal, 3) if nominal else None,
        "peak_nominal_tflops": round(nominal / 1e12, 1) if nominal else None,
        "dtype": ("f32-master/bf16-compute" if args.mixed_precision
                  else "bfloat16"),
        "mixed_precision": args.mixed_precision,
        "device_kind": device_kind,
    }))


if __name__ == "__main__":
    main()
