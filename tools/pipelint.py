"""pipelint: the repo's invariant-aware static-analysis gate.

Runs the `pipeedge_tpu/analysis/` AST rule engine over the given paths
and gates on zero non-baselined findings (docs/STATIC_ANALYSIS.md has the
rule catalog and the triage workflow).

Usage:
    python -m tools.pipelint pipeedge_tpu tools runtime.py
    python -m tools.pipelint --list-rules
    python -m tools.pipelint --json report.json pipeedge_tpu
    python -m tools.pipelint --write-baseline pipeedge_tpu tools runtime.py

Exit codes: 0 clean (everything suppressed/baselined with justification),
1 non-baselined findings, 2 engine error (syntax error in a linted file,
malformed or unjustified baseline).

The baseline (default tools/pipelint_baseline.json) grandfathers findings
by fingerprint; every entry must carry a non-empty justification — the
loader fails the run otherwise. `--write-baseline` regenerates the file
from the current findings with EMPTY justifications for new entries
(preserving existing ones), so a freshly-grandfathered finding cannot
pass CI until a human explains it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu.analysis import lint  # noqa: E402

DEFAULT_BASELINE = os.path.join("tools", "pipelint_baseline.json")


def _list_rules() -> None:
    for rule in lint.default_rules():
        print(f"{rule.id} {rule.name} [{rule.severity}]")
        print(f"    {rule.rationale}")
        if rule.fix_hint:
            print(f"    fix: {rule.fix_hint}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pipelint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files/directories to lint")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default %(default)s; ignored "
                    "when missing)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--json", metavar="OUT",
                    help="write the one-JSON-line report here ('-' for "
                    "stdout)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                    "(new entries get empty justifications to fill in)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        _list_rules()
        return 0
    if not args.paths:
        ap.error("no paths given (try: pipeedge_tpu tools runtime.py)")

    try:
        findings, errors, n_files = lint.run_lint(args.paths)
    except lint.LintError as exc:
        print(f"pipelint: {exc}", file=sys.stderr)
        return 2
    if errors:
        for e in errors:
            print(f"pipelint: {e}", file=sys.stderr)
        return 2

    baseline = lint.Baseline()
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = lint.Baseline.load(args.baseline)
        except lint.LintError as exc:
            print(f"pipelint: {exc}", file=sys.stderr)
            return 2

    if args.write_baseline:
        keep = {e["fingerprint"]: str(e.get("justification", ""))
                for e in baseline.entries}
        with open(args.baseline, "w", encoding="utf-8") as fh:
            fh.write(lint.Baseline.render(findings, keep))
        print(f"pipelint: wrote {len(findings)} entries to "
              f"{args.baseline} "
              f"({sum(1 for f in findings if not keep.get(f.fingerprint))} "
              "need justifications)")
        return 0

    new, baselined, stale = baseline.split(findings)

    # With --json - the report owns stdout; human lines move to stderr.
    human = sys.stderr if args.json == "-" else sys.stdout
    for f in new:
        print(f.format(), file=human)
    if stale:
        print(f"pipelint: note: {len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'} no longer "
              "match any finding (prune with --write-baseline):",
              file=sys.stderr)
        for e in stale:
            print(f"  {e['fingerprint']} {e['rule']} {e['path']} "
                  f"[{e.get('symbol', '')}]", file=sys.stderr)

    counts = {}
    for f in new:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    report = {
        "files": n_files,
        "rules": len(lint.default_rules()),
        "findings": [f.to_dict() for f in new],
        "counts_by_rule": counts,
        "baselined": len(baselined),
        "stale_baseline": [e["fingerprint"] for e in stale],
        "ok": not new,
    }
    if args.json:
        line = json.dumps(report, separators=(",", ":")) + "\n"
        if args.json == "-":
            sys.stdout.write(line)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(line)

    tag = "clean" if not new else f"{len(new)} finding(s)"
    print(f"pipelint: {n_files} files, {tag}, {len(baselined)} baselined",
          file=human)
    return 0 if not new else 1


if __name__ == "__main__":
    sys.exit(main())
