"""Generate the committed real-sized HF-torch parity fixture.

VERDICT r2 item 5 (real-weights accuracy): pretrained checkpoints are not
downloadable in this zero-egress environment (docs/REAL_WEIGHTS.md logs
the attempt), so this fixture anchors the parity claim at FULL model size
instead: HF torch's own float32 logits for ViT-Base on a fixed input,
with weights built by the same seeded recipe `save_model_weights.py
--random` uses (torch.manual_seed(0) + HF init). The committed artifact
is small (the logits, not the 330 MB weights); the test regenerates the
weights from the seed recipe, runs them through THIS framework's npz
conversion + shard pipeline, and must reproduce torch's recorded logits
(tests/test_weights.py::test_full_size_parity_vs_committed_torch_logits).

The moment real weights are obtainable, the identical path yields label
accuracy: swap --random for the pretrained fetch, keep everything else.

Usage: python tools/make_parity_fixture.py  (writes tests/fixtures/)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

MODEL = "google/vit-base-patch16-224"
INPUT_SEED = 1234
FIXTURE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures",
    "vitb_random_torch_logits.npz")


def build_torch_model():
    import torch
    from save_model_weights import _hf_model

    from pipeedge_tpu.models import registry
    cfg = registry.get_model_entry(MODEL).config
    model = _hf_model(MODEL, cfg, random_init=True)  # torch.manual_seed(0)
    return model.eval(), cfg


def fixture_input(cfg):
    rng = np.random.default_rng(INPUT_SEED)
    return rng.normal(size=(2, cfg.num_channels, cfg.image_size,
                            cfg.image_size)).astype(np.float32)


def main():
    import torch
    model, cfg = build_torch_model()
    x = fixture_input(cfg)
    with torch.no_grad():
        logits = model(torch.from_numpy(x)).logits.numpy()
    os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
    # weight checksum so a failing test can distinguish "HF init recipe
    # drifted" from "the framework's conversion/forward drifted"
    sd = model.state_dict()
    probe = np.concatenate([
        sd["vit.encoder.layer.0.attention.attention.query.weight"]
        .numpy().ravel()[:64],
        sd["classifier.weight"].numpy().ravel()[:64]])
    np.savez(FIXTURE, logits=logits, input_seed=INPUT_SEED,
             weight_probe=probe.astype(np.float32))
    print(f"wrote {FIXTURE}: logits {logits.shape}, "
          f"probe sum {probe.sum():.6f}")


if __name__ == "__main__":
    main()
