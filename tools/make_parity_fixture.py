"""Generate the committed HF-torch parity fixtures (one per model family).

VERDICT r2 item 5 / r3 item 7 (real-weights accuracy): pretrained
checkpoints are not downloadable in this zero-egress environment
(docs/REAL_WEIGHTS.md logs the attempt), so these fixtures anchor the
parity claim per family instead: HF torch's own float32 logits on a fixed
input, with weights built by the same seeded recipe `save_model_weights.py
--random` uses (torch.manual_seed(0) + HF init). The committed artifacts
are small (logits + a weight probe, not the weights); the anchor tests
(tests/test_weights_parity.py) regenerate the weights from the seed
recipe, run them through THIS framework's npz conversion + shard pipeline,
and must reproduce torch's recorded logits — catching drift in either the
HF init recipe (weight_probe check) or this framework's conversion/forward
for EVERY family, not just ViT. Reference capability anchored: per-model
weight loading (reference vit.py:121-159, bert.py:164-219, deit.py:131-156,
and the gpt2/llama families beyond it).

The moment real weights are obtainable, the identical path yields label
accuracy: swap --random for the pretrained fetch, keep everything else.

Usage: python tools/make_parity_fixture.py [model ...]   (default: all)
Writes tests/fixtures/<slug>_random_torch_logits.npz.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

INPUT_SEED = 1234
_FIXDIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures")

# One anchor per model family. probe_keys: state-dict slices recorded so a
# failing test can distinguish "HF init recipe drifted" from "this
# framework's conversion/forward drifted". logits_attr: which HF output
# carries the reference-parity logits (DeiT: the reference classifier is
# the CLS head only, reference deit.py:224-227). tail_positions bounds the
# committed artifact for big-vocab causal models (last positions only).
SPECS = {
    "google/vit-base-patch16-224": dict(
        slug="vitb", kind="image", logits_attr="logits",
        probe_keys=["vit.encoder.layer.0.attention.attention.query.weight",
                    "classifier.weight"]),
    "facebook/deit-base-distilled-patch16-224": dict(
        slug="deitb", kind="image", logits_attr="cls_logits",
        probe_keys=["deit.encoder.layer.0.attention.attention.query.weight",
                    "cls_classifier.weight"]),
    "textattack/bert-base-uncased-CoLA": dict(
        slug="bert_cola", kind="ids", seq=32, logits_attr="logits",
        probe_keys=["bert.encoder.layer.0.attention.self.query.weight",
                    "classifier.weight"]),
    "gpt2": dict(
        slug="gpt2", kind="ids", seq=16, logits_attr="logits",
        tail_positions=2,
        probe_keys=["transformer.h.0.attn.c_attn.weight", "lm_head.weight"]),
    "pipeedge/test-tiny-llama": dict(
        slug="tiny_llama", kind="ids", seq=16, logits_attr="logits",
        probe_keys=["model.layers.0.self_attn.q_proj.weight",
                    "lm_head.weight"]),
}
# Back-compat aliases (round-2 single-model tool API)
MODEL = "google/vit-base-patch16-224"
FIXTURE = os.path.join(_FIXDIR, "vitb_random_torch_logits.npz")


def fixture_path(model_name: str) -> str:
    return os.path.join(_FIXDIR,
                        f"{SPECS[model_name]['slug']}_random_torch_logits.npz")


def build_torch_model(model_name: str = MODEL):
    from save_model_weights import _hf_model

    from pipeedge_tpu.models import registry
    cfg = registry.get_model_entry(model_name).config
    model = _hf_model(model_name, cfg, random_init=True)  # torch.manual_seed(0)
    return model.eval(), cfg


def fixture_input(cfg, model_name: str = MODEL) -> np.ndarray:
    """The fixed fixture input: seeded image batch or token ids."""
    spec = SPECS[model_name]
    rng = np.random.default_rng(INPUT_SEED)
    if spec["kind"] == "image":
        return rng.normal(size=(2, cfg.num_channels, cfg.image_size,
                                cfg.image_size)).astype(np.float32)
    return rng.integers(0, cfg.vocab_size,
                        size=(2, spec["seq"])).astype(np.int64)


def weight_probe(model, model_name: str) -> np.ndarray:
    sd = model.state_dict()
    return np.concatenate([
        sd[key].numpy().ravel()[:64] for key in SPECS[model_name]["probe_keys"]
    ]).astype(np.float32)


def make_fixture(model_name: str) -> str:
    import torch
    spec = SPECS[model_name]
    model, cfg = build_torch_model(model_name)
    x = fixture_input(cfg, model_name)
    with torch.no_grad():
        out = model(torch.from_numpy(x))
    logits = getattr(out, spec["logits_attr"]).numpy()
    tail = spec.get("tail_positions")
    if tail:
        logits = logits[:, -tail:]
    path = fixture_path(model_name)
    os.makedirs(_FIXDIR, exist_ok=True)
    np.savez(path, logits=logits, input_seed=INPUT_SEED,
             weight_probe=weight_probe(model, model_name))
    print(f"wrote {path}: logits {logits.shape}")
    return path


def main():
    names = sys.argv[1:] or list(SPECS)
    for name in names:
        make_fixture(name)


if __name__ == "__main__":
    main()
