"""Pipelined KV-cache text generation demo (GPT-2 family).

Greedy-decodes synthetic (or file-provided) token prompts through a
block-aligned pipeline partition, printing tokens/sec. Weights load from the
registry's npz (random fallback under zero egress) — the decoding path is
weight-agnostic; pair with `save_model_weights.py` for real checkpoints.

Example:
    python tools/generate.py -m gpt2 -pt 1,24,25,48 -b 8 --new-tokens 32
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def prompt_ids(args, cfg):
    """Synthetic prompt token ids [B, prompt_len] (seeded, rank-consistent)."""
    return np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(args.batch_size, args.prompt_len))


def print_summary(args, dt, result, label):
    print(f"generated {args.batch_size}x{args.new_tokens} tokens in "
          f"{dt:.3f}s = {args.batch_size * args.new_tokens / dt:.1f} tok/s "
          f"({label})")
    print("sample continuation ids:", result[0, args.prompt_len:].tolist())


def run_dcn(args, cfg, total, partition, max_len, dtype):
    """Pipelined decoding across OS processes over TCP (DCN): stage i runs
    on rank i; every rank launches the same command with its own --rank, so
    the step count is known fleet-wide and no control plane is needed. Per
    step, the token's hidden state hops rank-to-rank on CHANNEL_DATA and
    the last rank returns the next-token logits to rank 0 on
    CHANNEL_RESULTS (the same edge discipline as runtime.py's DCN driver).

    Adaptive edge quantization (env ADAPTIVE_QUANT=HEURISTIC|HEURISTIC2|
    CONTROLLER + SEND_CONSTRAINT, reference runtime.py:121-216): each
    non-last rank adapts its OWN output edge's bitwidth on its own measured
    'send' telemetry window, exactly like the runtime driver's DCN mode —
    `--edge-bits` is then the starting bitwidth, and the consumer needs no
    coordination because the bitwidth rides the wire header (comm/wire.py).
    """
    import jax
    import jax.numpy as jnp

    from pipeedge_tpu.comm import dcn, wire
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode

    world = len(partition)
    rank = args.rank
    if not 0 <= rank < world:
        raise SystemExit(f"--rank {rank} outside the {world}-stage partition")
    decode.validate_partition(partition, total)
    decode.validate_capacity(cfg, max_len, args.prompt_len, args.new_tokens)
    addrs = dcn.parse_rank_addrs(args.dcn_addrs, world, 29600)
    l, r = partition[rank]
    _, params, sc = registry.module_shard_factory(
        args.model_name, args.model_file, l, r, stage=rank, dtype=dtype,
        unroll=False)
    family = registry.get_model_entry(args.model_name).family.FAMILY
    prefill_fn, decode_fn = decode.make_stage_fns(family, cfg, sc)
    params = dict(params)
    params["blocks"] = decode.stage_blocks(params)
    pick = decode.make_token_picker(args.temperature, args.top_k)
    prompt = args.prompt_len
    ids = prompt_ids(args, cfg)

    # mutable output-edge bitwidth + adaptive policy (non-last ranks own
    # exactly one edge; the runtime driver's _EdgeQuantState/-callback are
    # reused so policy behavior is identical across both DCN applications)
    edge = adaptive = None
    monitoring_mod = None
    if world > 1 and not sc.is_last:
        import runtime as runtime_mod
        edge = runtime_mod._EdgeQuantState(args.edge_bits)
        if os.getenv(runtime_mod.ENV_ADAPTIVE_QUANT):
            import logging

            import monitoring as monitoring_mod
            logging.basicConfig(level=logging.INFO)
            window = runtime_mod.get_window_size()
            monitoring_mod.init(runtime_mod.MONITORING_KEY_SEND, window,
                                work_type="Mbits")
            monitoring_mod.add_key(runtime_mod.MONITORING_KEY_RECV,
                                   work_type="Mbits")
            adaptive = runtime_mod._make_adaptive_callback([edge], window)
    step_beat = [0]

    with dcn.DistDcnContext(world, rank, addrs) as ctx:
        if adaptive is not None:
            import runtime as runtime_mod
            runtime_mod._register_dcn_monitor_hooks(ctx)

        def run_once(new_tokens):
            """One full fleet-lockstep generation (prefill + steps). Every
            rank executes the same step count, so no control plane is
            needed; returns rank 0's tokens."""
            cache = decode.init_cache(cfg, (r - l + 1) // 4,
                                      args.batch_size, max_len, dtype)
            rng = jax.random.PRNGKey(args.seed)
            tokens = []

            def stage_step(data, pos, fn):
                nonlocal cache
                if not sc.is_first:
                    data = wire.wire_decode(ctx.recv_tensors(rank - 1),
                                            dtype)
                # bucketed attend window: pos is fleet-lockstep, so every
                # rank independently picks the same static bucket
                out, cache = fn(params, data, cache) if pos is None else \
                    fn(params, data, cache, pos,
                       read_len=decode.attend_bucket(pos + 1, max_len,
                                                     args.attend_floor))
                if not sc.is_last:
                    ctx.send_tensors(rank + 1, wire.wire_encode(
                        out, edge.quant_bit if edge is not None else 0))
                    if adaptive is not None:
                        adaptive(step_beat[0], out)
                        step_beat[0] += 1
                elif world > 1:
                    # last position's logits back to rank 0
                    last = out[:, -1] if pos is None else out[:, 0]
                    ctx.send_tensors(0, [np.asarray(last)],
                                     channel=dcn.CHANNEL_RESULTS)
                return out

            def next_token(out, pos):
                nonlocal rng
                if world > 1:
                    logits = jnp.asarray(
                        ctx.recv_tensors(world - 1,
                                         channel=dcn.CHANNEL_RESULTS)[0])
                else:
                    logits = out[:, prompt - 1] if pos is None else out[:, 0]
                rng, sub = jax.random.split(rng)
                return pick(logits.astype(jnp.float32), sub)

            out = stage_step(
                jnp.asarray(ids, jnp.int32) if sc.is_first else None,
                None, prefill_fn)
            if rank == 0:
                tokens.append(next_token(out, None))
            for step in range(1, new_tokens):
                pos = prompt + step - 1
                data = tokens[-1][:, None] if sc.is_first else None
                out = stage_step(data, pos, decode_fn)
                if rank == 0:
                    tokens.append(next_token(out, pos))
            return tokens

        # compile programs fleet-wide with the FULL token budget, so every
        # attend bucket the timed run crosses is already built (a 2-token
        # warmup would leave bucket compiles inside the timed region)
        run_once(args.new_tokens)
        tik = time.monotonic()
        tokens = run_once(args.new_tokens)
        if rank == 0:
            dt = time.monotonic() - tik
            result = np.concatenate(
                [ids, np.stack([np.asarray(t) for t in tokens], axis=1)],
                axis=1)
            print_summary(args, dt, result, f"{world} DCN ranks")
    if monitoring_mod is not None:
        monitoring_mod.finish()


def run_spmd_wave(args, cfg, partition, stage_params, max_len, dtype):
    """`--spmd-wave`: the whole continuous-batching wave schedule compiled
    into shard_map programs over a ('stage',) mesh (n_stages request
    slots, ppermute edges, zero host round-trips per tick)."""
    import jax
    from jax.sharding import Mesh

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel.spmd_decode import SpmdDecodePipeline

    n_stages = len(partition)
    if len(jax.devices()) < n_stages:
        raise SystemExit(f"--spmd-wave needs {n_stages} devices (one per "
                         f"stage), only {len(jax.devices())} visible")
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]), ("stage",))
    wave = SpmdDecodePipeline(registry.get_model_entry(
        args.model_name).family.FAMILY, cfg, partition, stage_params,
        mesh, max_len=max_len, dtype=dtype, edge_bits=args.edge_bits)
    # same prompt convention as solo/--concurrent runs (one prompt_ids()
    # prompt per request slot, per-slot sampling seeds seed+r), so wave
    # throughput and continuations are comparable across demo modes
    wave_ids = np.stack([prompt_ids(args, cfg)] * n_stages)
    kw = dict(temperature=args.temperature, top_k=args.top_k,
              seeds=[args.seed + r for r in range(n_stages)])
    # warm with the SAME token budget: new_tokens sizes the compiled
    # wave programs, so a shorter warmup would compile the wrong ones
    np.asarray(wave.generate(wave_ids, args.new_tokens, **kw))
    tik = time.monotonic()
    out = np.asarray(wave.generate(wave_ids, args.new_tokens, **kw))
    dt = time.monotonic() - tik
    n_tok = n_stages * args.batch_size * args.new_tokens
    print(f"generated {n_stages}x{args.batch_size}x{args.new_tokens} "
          f"tokens in {dt:.3f}s = {n_tok / dt:.1f} tok/s "
          f"({n_stages} stages, SPMD wave decode)")
    print("sample continuation ids:",
          out[0, 0, args.prompt_len:].tolist())


def main():
    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    import jax.numpy as jnp
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode

    parser = argparse.ArgumentParser(
        description="Pipelined KV-cache greedy generation",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-m", "--model-name", default="gpt2",
                        choices=[n for n in registry.get_model_names()
                                 if registry.get_model_config(n).model_type
                                 in ("gpt2", "llama")])
    parser.add_argument("-M", "--model-file", default=None)
    parser.add_argument("-pt", "--partition", default=None,
                        help="comma-separated layer ranges, e.g. 1,24,25,48 "
                             "(default: single stage)")
    parser.add_argument("-b", "--batch-size", default=4, type=int)
    parser.add_argument("--prompt-len", default=16, type=int)
    parser.add_argument("--new-tokens", default=32, type=int)
    parser.add_argument("--max-len", default=None, type=int,
                        help="cache capacity (default: prompt+new tokens)")
    parser.add_argument("-t", "--dtype", default="float32",
                        choices=["float32", "bfloat16"])
    parser.add_argument("--kv-bits", default=0, type=int, choices=[0, 8],
                        help="int8-quantize the KV cache (halves decode "
                             "HBM traffic; 0 = full precision)")
    parser.add_argument("--attend-floor", default=64, type=int,
                        help="smallest bucketed attend window: decode "
                             "steps attend over the least power-of-2 "
                             "window >= the live cache length instead of "
                             "max_len (one compiled variant per bucket)")
    parser.add_argument("--tp", default=1, type=int,
                        help="Megatron tensor-parallel degree per stage "
                             "(head-sharded KV cache, shard_map)")
    parser.add_argument("--sp", default=1, type=int,
                        help="sequence-parallel PREFILL degree (causal ring "
                             "attention over the prompt; decode steps stay "
                             "single-device)")
    parser.add_argument("--ep", default=1, type=int,
                        help="expert-parallel degree for MoE models "
                             "(experts shard over an 'ep' mesh per stage); "
                             "combine with --tp for the tp x ep serving "
                             "mesh (attention tp-sharded, experts "
                             "ep-sharded)")
    parser.add_argument("--draft-model", default=None,
                        help="speculative decoding: this (smaller, same-"
                             "vocabulary) model proposes --gamma tokens "
                             "per round, one target span forward verifies "
                             "them; output is token-identical to plain "
                             "greedy decoding of the main model")
    parser.add_argument("--gamma", default=4, type=int,
                        help="draft lookahead per speculative round")
    parser.add_argument("--temperature", default=0.0, type=float,
                        help="sampling temperature (0 = greedy)")
    parser.add_argument("--top-k", default=0, type=int,
                        help="sample only from the k most likely tokens "
                             "(0 = full distribution)")
    parser.add_argument("--seed", default=0, type=int,
                        help="sampling PRNG seed")
    parser.add_argument("--beams", default=0, type=int,
                        help="beam-search width (0 = greedy/sampling; "
                             "local pipeline mode only)")
    parser.add_argument("--prefill-ubatch", default=None, type=int,
                        help="pipeline the prompt pass across stages in "
                             "batch chunks of this size")
    parser.add_argument("--shared-prefix", default=0, type=int,
                        help="prompt caching: treat the first N prompt "
                             "tokens as a prefix shared by every batch "
                             "row — prefilled ONCE (precompute_prefix) "
                             "and reused; the per-row suffixes run as "
                             "one span at the prefix offset")
    parser.add_argument("--concurrent", default=0, type=int,
                        help="continuous batching: decode this many "
                             "concurrent requests (each of -b sequences) "
                             "wave-scheduled across the pipeline stages; "
                             "tokens match solo runs per request")
    parser.add_argument("--spmd-wave", action="store_true",
                        help="compile the whole wave schedule into one "
                             "shard_map program per phase (n_stages "
                             "request slots over a ('stage',) mesh, "
                             "ppermute edges, zero host round-trips per "
                             "tick); greedy or --temperature sampling")
    parser.add_argument("--monitor", action="store_true",
                        help="record per-step heartbeats to decode.csv "
                             "(overwrites an existing decode.csv in cwd)")
    parser.add_argument("--rank", default=0, type=int,
                        help="this process's rank in a DCN fleet")
    parser.add_argument("-sm", "--sched-models-file", default=None)
    parser.add_argument("-sdt", "--sched-dev-types-file", default=None)
    parser.add_argument("-sd", "--sched-dev-file", default=None)
    parser.add_argument("--edge-bits", default=0, type=int,
                        choices=[0, 2, 4, 6, 8, 16],
                        help="quantize stage edges (QuantPipe activation "
                             "compression): DCN wire frames with "
                             "--dcn-addrs, or the [B, S, D] prefill "
                             "ppermute hops with --spmd-wave")
    parser.add_argument("--dcn-addrs", default=None, type=str,
                        help="comma-separated host:port per rank: run the "
                             "pipeline across OS processes over TCP (stage "
                             "i on rank i; launch the same command on every "
                             "rank with its own --rank)")
    args = parser.parse_args()
    if args.new_tokens < 1:
        parser.error("--new-tokens must be >= 1")

    cfg = registry.get_model_config(args.model_name)
    total = registry.get_model_layers(args.model_name)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if args.partition:
        nums = [int(x) for x in args.partition.split(",")]
        if len(nums) % 2:
            parser.error(f"-pt needs an even count of layer bounds: {nums}")
        partition = list(zip(nums[::2], nums[1::2]))
    elif args.sched_models_file:
        # profile-driven partitioning: the native DP scheduler cuts at
        # sublayer granularity (its cost model is per quarter-block);
        # decoding needs block-aligned stages, so round the cuts to the
        # nearest block boundary
        from pipeedge_tpu.sched.scheduler import sched_pipeline
        # dtype must match the profile records' (dtype, batch_size) key
        # (native/sched_pipeline_main.cpp:135) — chip profiles are bfloat16
        sched = sched_pipeline(args.model_name, 2, 2, args.batch_size,
                               dtype=args.dtype,
                               models_file=args.sched_models_file,
                               dev_types_file=args.sched_dev_types_file,
                               dev_file=args.sched_dev_file)
        if not sched:
            raise SystemExit("No viable schedule found")
        raw = [tuple(int(v) for v in layers)
               for stage in sched for layers in stage.values()]
        partition = decode.round_partition_to_blocks(raw, total)
        if partition != raw:
            print(f"scheduler partition {raw} rounded to block-aligned "
                  f"{partition}")
    else:
        partition = [(1, total)]
    max_len = args.max_len or args.prompt_len + args.new_tokens
    if args.draft_model and args.max_len is None:
        max_len += args.gamma   # verify spans write past the last token
    if args.beams and args.temperature > 0:
        parser.error("--beams and --temperature are mutually exclusive")
    if args.beams and args.monitor:
        parser.error("--monitor records per-step heartbeats only for "
                     "greedy/sampled generation, not --beams")
    if args.beams and args.prefill_ubatch:
        parser.error("--prefill-ubatch applies to greedy/sampled "
                     "generation, not --beams")
    if args.edge_bits and args.dcn_addrs is None and not args.spmd_wave:
        parser.error("--edge-bits applies to DCN stage edges or the SPMD "
                     "wave prefill hops; pass --dcn-addrs or --spmd-wave")
    if args.shared_prefix and (
            args.beams or args.spmd_wave
            or args.prefill_ubatch or args.dcn_addrs is not None):
        # checked BEFORE mode dispatch: every one of these modes branches
        # away earlier than the prefix path, which would otherwise
        # silently ignore --shared-prefix (--draft-model and --concurrent
        # compose: the speculative decoder and the batcher both take
        # prefix handles)
        parser.error("--shared-prefix composes with plain, speculative, "
                     "or --concurrent greedy/sampled generation only "
                     "(not --beams/--spmd-wave/--prefill-ubatch/"
                     "--dcn-addrs)")
    if args.shared_prefix and args.sp > 1 and args.shared_prefix % args.sp:
        parser.error(f"--shared-prefix {args.shared_prefix} must divide "
                     f"by --sp {args.sp} (the prefix is what the sp "
                     "prefill runs on)")
    if args.spmd_wave and (
            args.concurrent or args.beams or args.monitor
            or args.prefill_ubatch
            or args.tp > 1 or args.sp > 1 or args.ep > 1 or args.kv_bits
            or args.dcn_addrs is not None):
        parser.error("--spmd-wave does not compose with --concurrent/"
                     "--beams/--monitor/--prefill-ubatch/--tp/--sp/--ep/"
                     "--kv-bits/--dcn-addrs")
    if args.dcn_addrs is not None:
        if args.tp > 1 or args.sp > 1 or args.ep > 1 or args.kv_bits \
                or args.monitor or args.beams or args.prefill_ubatch:
            parser.error("--dcn-addrs does not compose with --tp/--sp/--ep/"
                         "--kv-bits/--monitor/--beams/--prefill-ubatch in "
                         "this demo")
        run_dcn(args, cfg, total, partition, max_len, dtype)
        return
    stage_params = []
    for i, (l, r) in enumerate(partition):
        _, params, _ = registry.module_shard_factory(
            args.model_name, args.model_file, l, r, stage=i, dtype=dtype,
            unroll=False)  # DecodePipeline wants the stacked block layout
        stage_params.append(params)
    if args.spmd_wave:
        run_spmd_wave(args, cfg, partition, stage_params, max_len, dtype)
        return
    mesh = sp_mesh = ep_mesh = tp_ep_mesh = None
    if args.tp > 1 or args.sp > 1 or args.ep > 1:
        import jax
        from jax.sharding import Mesh
        tp_with_ep = args.tp > 1 and args.ep > 1    # MoE serving: tp x ep
        need = args.tp * args.ep if tp_with_ep else max(args.tp, args.sp,
                                                        args.ep)
        if len(jax.devices()) < need:
            parser.error(f"--tp/--sp/--ep {need} needs {need} devices, "
                         f"only {len(jax.devices())} visible")
        if args.sp > 1 and (args.tp > 1 or args.ep > 1):
            parser.error("--sp is mutually exclusive with --tp/--ep in "
                         "this demo")
        if args.sp > 1 and args.prompt_len % args.sp:
            parser.error(f"--prompt-len {args.prompt_len} must divide by "
                         f"--sp {args.sp}")
        if tp_with_ep:
            tp_ep_mesh = Mesh(np.array(jax.devices()[:need]).reshape(
                args.tp, args.ep), ("tp", "ep"))
        elif args.tp > 1:
            mesh = Mesh(np.array(jax.devices()[:args.tp]), ("tp",))
        elif args.sp > 1:
            sp_mesh = Mesh(np.array(jax.devices()[:args.sp]), ("sp",))
        else:
            ep_mesh = Mesh(np.array(jax.devices()[:args.ep]), ("ep",))
    # shared construction path with tools/serve.py (model lookup /
    # capacity clamp live in one place); params pre-loaded above because
    # the spmd-wave branch needs them directly
    pipe = decode.build_decode_pipeline(
        args.model_name, partition, max_len=max_len, dtype=dtype,
        cache_bits=args.kv_bits, attend_floor=args.attend_floor,
        stage_params=stage_params, mesh=mesh, sp_mesh=sp_mesh,
        ep_mesh=ep_mesh, tp_ep_mesh=tp_ep_mesh)

    heartbeat = None
    if args.monitor:
        import jax
        import monitoring
        monitoring.init("decode", window_size=16, work_type="tokens")

        def heartbeat(step, tokens):
            # per-step heartbeat -> decode.csv. JAX dispatch is async, so
            # fence on the step's tokens to time real emission, not host
            # dispatch. The first beat establishes the time base
            # (runtime.py's safe=False pattern), so decode.csv carries
            # new_tokens - 1 intervals.
            jax.block_until_ready(tokens)
            monitoring.iteration("decode", work=int(tokens.shape[0]),
                                 safe=False)

    ids = prompt_ids(args, cfg)
    p_len = args.shared_prefix
    if p_len:
        # ONE prefix setup for both the plain and speculative modes:
        # validate, make every batch row share the prefix, and prepend
        # it back onto generate()'s prefix-omitting output
        if not 0 < p_len < args.prompt_len:
            parser.error(f"--shared-prefix must be in (0, "
                         f"{args.prompt_len})")
        ids[:, :p_len] = ids[0, :p_len]
        with_prefix = lambda out: np.concatenate([ids[:, :p_len], out],
                                                 axis=1)
    if args.draft_model:
        if (args.temperature > 0 or args.top_k or args.beams
                or args.concurrent or args.monitor or args.spmd_wave
                or args.prefill_ubatch or args.dcn_addrs is not None
                or args.kv_bits):
            parser.error("--draft-model is greedy-exact speculative "
                         "decoding; it does not compose with sampling/"
                         "--beams/--concurrent/--monitor/--spmd-wave/"
                         "--prefill-ubatch/--dcn-addrs, nor --kv-bits "
                         "(int8 span verification is not bit-identical "
                         "to serial int8 steps)")
        from pipeedge_tpu.parallel.speculative import SpeculativeDecoder
        d_total = registry.get_model_layers(args.draft_model)
        _, d_params, _ = registry.module_shard_factory(
            args.draft_model, None, 1, d_total, dtype=dtype, unroll=False)
        d_pipe = decode.DecodePipeline(
            registry.get_model_entry(args.draft_model).family.FAMILY,
            registry.get_model_config(args.draft_model), [(1, d_total)],
            [d_params], max_len=max_len, dtype=dtype,
            attend_floor=args.attend_floor)
        spec = SpeculativeDecoder(pipe, d_pipe, gamma=args.gamma)
        label = (f"{len(partition)} stages, speculative gamma="
                 f"{args.gamma} draft={args.draft_model}")
        if p_len:
            handle = spec.precompute_prefix(ids[:1, :p_len])
            gen = lambda n: with_prefix(np.asarray(spec.generate(
                ids[:, p_len:], n, prefix=handle)))
            label += f", shared prefix {p_len}"
        else:
            gen = lambda n: np.asarray(spec.generate(ids, n))
        gen(min(2, args.new_tokens))          # compile programs
        tik = time.monotonic()
        out = gen(args.new_tokens)
        dt = time.monotonic() - tik
        rate = spec.last_acceptance_rate
        print_summary(args, dt, out, label + " acceptance="
                      + (f"{rate:.2f}" if rate is not None else "n/a"))
        return
    if args.concurrent:
        if args.beams or args.monitor or args.prefill_ubatch:
            parser.error("--concurrent composes with greedy/sampled "
                         "generation only (not --beams/--monitor/"
                         "--prefill-ubatch)")
        from pipeedge_tpu.parallel.batcher import ContinuousBatcher
        handle = pipe.precompute_prefix(ids[:1, :p_len]) if p_len else None
        req_ids = ids[:, p_len:] if p_len else ids

        def run_batch():
            batcher = ContinuousBatcher(pipe)
            for req in range(args.concurrent):
                batcher.submit(req, req_ids, args.new_tokens,
                               temperature=args.temperature,
                               top_k=args.top_k, seed=args.seed + req,
                               prefix=handle)
            return batcher, batcher.run()

        run_batch()                      # compile programs
        tik = time.monotonic()
        batcher, results = run_batch()
        dt = time.monotonic() - tik
        n_tok = args.concurrent * args.batch_size * args.new_tokens
        shared = f", shared prefix {p_len}" if p_len else ""
        print(f"generated {args.concurrent}x{args.batch_size}x"
              f"{args.new_tokens} tokens in {dt:.3f}s = {n_tok / dt:.1f} "
              f"tok/s ({len(partition)} stages, continuous batching"
              f"{shared}; {batcher.stats['ticks']} ticks, "
              f"{batcher.stats['stage_steps']} stage-steps)")
        out0 = with_prefix(results[0]) if p_len else results[0]
        print("sample continuation ids:",
              out0[0, args.prompt_len:].tolist())
        return
    if args.beams:
        run = lambda n, cb=None: np.asarray(
            pipe.generate_beam(ids, n, beams=args.beams))
        label = f"{len(partition)} stages, beam {args.beams}"
    elif p_len:
        handle = pipe.precompute_prefix(ids[:1, :p_len])
        sample_kw = dict(temperature=args.temperature, top_k=args.top_k,
                         seed=args.seed)
        run = lambda n, cb=None: with_prefix(np.asarray(pipe.generate(
            ids[:, p_len:], n, step_callback=cb, prefix=handle,
            **sample_kw)))
        label = (f"{len(partition)} stages, shared prefix {p_len} "
                 "(prefilled once)")
    else:
        sample_kw = dict(temperature=args.temperature, top_k=args.top_k,
                         seed=args.seed, prefill_ubatch=args.prefill_ubatch)
        run = lambda n, cb=None: np.asarray(
            pipe.generate(ids, n, step_callback=cb, **sample_kw))
        label = f"{len(partition)} stages"
    run(min(2, args.new_tokens))   # compile programs
    tik = time.monotonic()
    out = run(args.new_tokens, heartbeat)
    dt = time.monotonic() - tik
    if args.monitor:
        import monitoring
        monitoring.finish()
    print_summary(args, dt, out, label)


if __name__ == "__main__":
    main()
