"""Pipelined KV-cache text generation demo (GPT-2 family).

Greedy-decodes synthetic (or file-provided) token prompts through a
block-aligned pipeline partition, printing tokens/sec. Weights load from the
registry's npz (random fallback under zero egress) — the decoding path is
weight-agnostic; pair with `save_model_weights.py` for real checkpoints.

Example:
    python tools/generate.py -m gpt2 -pt 1,24,25,48 -b 8 --new-tokens 32
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    import jax.numpy as jnp
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode

    parser = argparse.ArgumentParser(
        description="Pipelined KV-cache greedy generation",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-m", "--model-name", default="gpt2",
                        choices=[n for n in registry.get_model_names()
                                 if registry.get_model_config(n).model_type
                                 == "gpt2"])
    parser.add_argument("-M", "--model-file", default=None)
    parser.add_argument("-pt", "--partition", default=None,
                        help="comma-separated layer ranges, e.g. 1,24,25,48 "
                             "(default: single stage)")
    parser.add_argument("-b", "--batch-size", default=4, type=int)
    parser.add_argument("--prompt-len", default=16, type=int)
    parser.add_argument("--new-tokens", default=32, type=int)
    parser.add_argument("--max-len", default=None, type=int,
                        help="cache capacity (default: prompt+new tokens)")
    parser.add_argument("-t", "--dtype", default="float32",
                        choices=["float32", "bfloat16"])
    parser.add_argument("--kv-bits", default=0, type=int, choices=[0, 8],
                        help="int8-quantize the KV cache (halves decode "
                             "HBM traffic; 0 = full precision)")
    parser.add_argument("--tp", default=1, type=int,
                        help="Megatron tensor-parallel degree per stage "
                             "(head-sharded KV cache, shard_map)")
    parser.add_argument("--temperature", default=0.0, type=float,
                        help="sampling temperature (0 = greedy)")
    parser.add_argument("--top-k", default=0, type=int,
                        help="sample only from the k most likely tokens "
                             "(0 = full distribution)")
    parser.add_argument("--seed", default=0, type=int,
                        help="sampling PRNG seed")
    parser.add_argument("--monitor", action="store_true",
                        help="record per-step heartbeats to decode.csv "
                             "(overwrites an existing decode.csv in cwd)")
    args = parser.parse_args()

    cfg = registry.get_model_config(args.model_name)
    total = registry.get_model_layers(args.model_name)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if args.partition:
        nums = [int(x) for x in args.partition.split(",")]
        if len(nums) % 2:
            parser.error(f"-pt needs an even count of layer bounds: {nums}")
        partition = list(zip(nums[::2], nums[1::2]))
    else:
        partition = [(1, total)]
    stage_params = []
    for i, (l, r) in enumerate(partition):
        _, params, _ = registry.module_shard_factory(
            args.model_name, args.model_file, l, r, stage=i, dtype=dtype,
            unroll=False)  # DecodePipeline wants the stacked block layout
        stage_params.append(params)
    max_len = args.max_len or args.prompt_len + args.new_tokens
    mesh = None
    if args.tp > 1:
        import jax
        from jax.sharding import Mesh
        if len(jax.devices()) < args.tp:
            parser.error(f"--tp {args.tp} needs {args.tp} devices, only "
                         f"{len(jax.devices())} visible")
        mesh = Mesh(np.array(jax.devices()[:args.tp]), ("tp",))
    pipe = decode.DecodePipeline(registry.get_model_entry(
        args.model_name).family.FAMILY, cfg, partition, stage_params,
        max_len=max_len, dtype=dtype, cache_bits=args.kv_bits, mesh=mesh)

    heartbeat = None
    if args.monitor:
        import jax
        import monitoring
        monitoring.init("decode", window_size=16, work_type="tokens")

        def heartbeat(step, tokens):
            # per-step heartbeat -> decode.csv. JAX dispatch is async, so
            # fence on the step's tokens to time real emission, not host
            # dispatch. The first beat establishes the time base
            # (runtime.py's safe=False pattern), so decode.csv carries
            # new_tokens - 1 intervals.
            jax.block_until_ready(tokens)
            monitoring.iteration("decode", work=int(tokens.shape[0]),
                                 safe=False)

    sample_kw = dict(temperature=args.temperature, top_k=args.top_k,
                     seed=args.seed)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(args.batch_size, args.prompt_len))
    out = np.asarray(pipe.generate(ids, 2, **sample_kw))  # compile programs
    tik = time.monotonic()
    out = np.asarray(pipe.generate(ids, args.new_tokens,
                                   step_callback=heartbeat, **sample_kw))
    dt = time.monotonic() - tik
    if args.monitor:
        import monitoring
        monitoring.finish()
    print(f"generated {args.batch_size}x{args.new_tokens} tokens in "
          f"{dt:.3f}s = {args.batch_size * args.new_tokens / dt:.1f} tok/s "
          f"({len(partition)} stages)")
    print("sample continuation ids:", out[0, args.prompt_len:].tolist())


if __name__ == "__main__":
    main()
