"""Build bert_input.npz: evaluation sentences + labels for the BERT models.

Parity with /root/reference/tools/bert_save_input.py:8-18: 512 GLUE CoLA
training sentences with index 0 replaced by a width-forcing 512-token string.
Falls back to synthetic sentences when the datasets cache is unavailable
(zero egress).
"""
import logging

import numpy as np

logger = logging.getLogger(__name__)


def build(n: int = 512):
    try:
        import datasets
        ds_train = datasets.load_dataset('glue', name='cola', split='train')
        bert_input = ds_train[:n]['sentence']
        bert_label = ds_train[:n]['label']
    except Exception as exc:
        logger.warning("GLUE CoLA unavailable (%s); generating synthetic "
                       "sentences", exc)
        rng = np.random.default_rng(0)
        words = ["the", "model", "runs", "fast", "on", "tpu", "chips",
                 "with", "pipeline", "stages"]
        bert_input = [" ".join(rng.choice(words, size=rng.integers(4, 16)))
                      for _ in range(n)]
        bert_label = rng.integers(0, 2, size=n).tolist()
    # index 0 forces the tokenizer to produce width-512 input_ids
    bert_input[0] = 'hello ' * 512
    bert_label[0] = 0
    np.savez('bert_input.npz', input=bert_input, label=bert_label)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    build()
