#!/usr/bin/env bash
# Generate the committed TPU chip profiles (profiles/README.md recipe) in one
# serialized chip session: per-layer profiles for ViT-B and ViT-L, the
# scheduler YAML conversions, and a bench.py run. Run from the repo root on a
# machine with the real chip. The chip is single-tenant — never run two chip
# processes at once, and never SIGKILL a running one (stale-lease wedge).
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p profiles/tpu

run() { echo "=== $*" >&2; stdbuf -oL -eL "$@"; }

# Profile into temp files and move into place ONLY on success: the
# profiler MERGES into an existing file and (reference semantics)
# refuses to re-profile a layer already present, so refresh runs need a
# fresh output — but deleting the committed fixtures up front would
# strand the tree with tracked files gone if an early step fails.
rm -f profiles/tpu/.tmp_vitb.yml profiles/tpu/.tmp_vitl.yml
run python profiler.py -m google/vit-base-patch16-224 -b 8 -t bfloat16 \
    -o profiles/tpu/.tmp_vitb.yml
mv profiles/tpu/.tmp_vitb.yml profiles/tpu/profiler_results_vitb.yml
run python profiler.py -m google/vit-large-patch16-224 -b 8 -t bfloat16 \
    -o profiles/tpu/.tmp_vitl.yml
mv profiles/tpu/.tmp_vitl.yml profiles/tpu/profiler_results_vitl.yml

# -f: refresh runs overwrite the previous session's entries
run python profiler_results_to_models.py -f \
    -i profiles/tpu/profiler_results_vitb.yml -o profiles/tpu/models.yml
run python profiler_results_to_models.py -f \
    -i profiles/tpu/profiler_results_vitl.yml -o profiles/tpu/models.yml
# -dtm 16384: v5e HBM MB; -dtb 100000: ~100 Gbps per-link planning number
# for the scheduler's min(src,dst) bandwidth model.
run python profiler_results_to_device_types.py tpu-v5e -f \
    -i profiles/tpu/profiler_results_vitb.yml -o profiles/tpu/device_types.yml \
    -dtm 16384 -dtb 100000
run python profiler_results_to_device_types.py tpu-v5e -f \
    -i profiles/tpu/profiler_results_vitl.yml -o profiles/tpu/device_types.yml \
    -dtm 16384 -dtb 100000
python -c "import yaml; yaml.safe_dump(
    {'tpu-v5e': ['tpu0', 'tpu1', 'tpu2', 'tpu3']},
    open('profiles/tpu/devices.yml', 'w'))"

run python bench.py
run python bench_decode.py
run python tools/bench_train.py
