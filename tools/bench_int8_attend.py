"""Isolated int8 decode-attention microbench: XLA vs kernel v1 vs v2.

The decode-step attend over an int8 KV cache is a per-(batch, head)
matvec — no MXU mapping fills the array (M=1 queries), so the op is
HBM-bandwidth-bound and the only lever is bytes moved. The XLA path
dequantizes the window to a bf16/f32 copy before attending (int8 read +
fp write + fp read); the Pallas kernels read int8 once and dequantize
in VMEM. Round 4 measured kernel v1 (per-cell grid) at parity-to-slower
(docs/DECODE.md honest negative); round 5 adds v2 (batch-as-sublane:
grid over KV blocks, all cells per instance — ops/decode_attention.py).

This harness times all three routes interleaved (chained reps, one
scalar fence — the docs/PERF.md tunnel discipline) at decode-dominant
shapes, and calibrates the chip's effective HBM bandwidth with a big
jnp.copy so each route's bytes/roofline is explicit in the record.
Prints ONE JSON line.
"""
import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-b", "--batch", default=16, type=int)
    p.add_argument("--heads", default=16, type=int)
    p.add_argument("--head-dim", default=64, type=int)
    p.add_argument("--widths", default="256,1024",
                   help="attend window widths; 1024 is the production "
                        "VMEM-cap regime (4096 busts the v1 kernel's "
                        "scoped-vmem stack on v5e — measured, capped)")
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--chain", default=16, type=int)
    p.add_argument("--rounds", default=3, type=int)
    args = p.parse_args()

    from pipeedge_tpu.utils import apply_env_platform, require_live_backend
    apply_env_platform()
    require_live_backend("int8_attend_best_route_ms", unit="ms")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pipeedge_tpu.ops.decode_attention import (
        int8_decode_attention, int8_decode_attention_supported)
    from pipeedge_tpu.parallel import decode as dec

    b, h, d = args.batch, args.heads, args.head_dim
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    interpret = not int8_decode_attention_supported()
    rng = np.random.default_rng(0)

    # effective HBM bandwidth via the PAIRED-DELTA estimator: time a
    # chain of N and of 2N dependent copies and divide the difference —
    # the fixed dispatch/tunnel round trip (~65 ms here) cancels, which
    # a single fenced chain cannot achieve at these op sizes
    big = jax.device_put(jnp.asarray(
        rng.normal(size=(64 << 20) // 4), jnp.float32))
    cp = jax.jit(lambda x: x * jnp.float32(1.000001))
    float(jnp.sum(cp(big)))               # compile + warm

    def chain_copies(k):
        tik = time.monotonic()
        y = big
        for _ in range(k):
            y = cp(y)
        float(jnp.sum(y))
        return time.monotonic() - tik

    # long chains: each leg must dwarf the tunnel's RTT jitter or the
    # delta can go negative (one session measured -6600 GB/s at n=16)
    n_bw = 64
    deltas = [chain_copies(2 * n_bw) - chain_copies(n_bw)
              for _ in range(3)]
    med = statistics.median(deltas)
    bw = 2 * n_bw * big.nbytes / med if med > 0 else None

    results = {}
    for width in (int(w) for w in args.widths.split(",")):
        pos = width - 2
        kq = jnp.asarray(rng.integers(-128, 127, size=(b, width, h, d)),
                         jnp.int8)
        vq = jnp.asarray(rng.integers(-128, 127, size=(b, width, h, d)),
                         jnp.int8)
        ks = jnp.asarray(rng.random(size=(b, width, h)) * 0.02, jnp.float32)
        kz = jnp.asarray(rng.random(size=(b, width, h)) - 0.5, jnp.float32)
        vs, vz = ks + 0.001, kz * 0.5
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), dtype)
        k_new = jnp.asarray(rng.normal(size=(b, 1, h, d)), dtype)
        v_new = jnp.asarray(rng.normal(size=(b, 1, h, d)), dtype)

        # cache tensors enter as ARGUMENTS (a closure would bake the
        # multi-MB int8 windows into the HLO as constants; the tunneled
        # compile endpoint rejects oversized programs)
        operands = (kq, ks, kz, vq, vs, vz, k_new, v_new)

        def xla_route(q, pos, kq, ks, kz, vq, vs, vz, k_new, v_new):
            # the production XLA path's math: dequantize window, fresh
            # row substitution, masked attend (decode._attend)
            k = dec._dequantize_rows(kq, ks, kz, dtype)
            v = dec._dequantize_rows(vq, vs, vz, dtype)
            k = jax.lax.dynamic_update_slice(k, k_new, (0, pos, 0, 0))
            v = jax.lax.dynamic_update_slice(v, v_new, (0, pos, 0, 0))
            keep = (jnp.arange(width) <= pos)[None, :]
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                                preferred_element_type=jnp.float32)
            scores = scores / jnp.sqrt(jnp.float32(d))
            scores = jnp.where(keep[:, None, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                              preferred_element_type=jnp.float32) \
                .astype(dtype).reshape(b, 1, h * d)

        def kernel_route(variant):
            def run(q, pos, *t):
                return jnp.sum(int8_decode_attention(
                    q, *t, pos, interpret=interpret,
                    variant=variant).astype(jnp.float32))

            return jax.jit(run)

        routes = {
            "xla": jax.jit(lambda q, pos, *t: jnp.sum(
                xla_route(q, pos, *t).astype(jnp.float32))),
            "kernel_v1": kernel_route(1),
            "kernel_v2": kernel_route(2),
        }
        for fn in routes.values():
            float(fn(q, pos, *operands))  # compile + warm (incl. fence)
        def timed_chain(fn, k):
            tik = time.monotonic()
            out = None
            for _ in range(k):
                out = fn(q, pos, *operands)
            float(out)
            return time.monotonic() - tik

        times = {k: [] for k in routes}
        for _ in range(args.rounds):      # interleaved rounds
            for name, fn in routes.items():
                # paired-delta estimator: (t(2N) - t(N)) / N cancels the
                # fixed dispatch/tunnel round trip that would otherwise
                # dominate these sub-ms ops (docs/PERF.md discipline).
                # A negative delta means RTT jitter swamped the sample —
                # record it as INVALID (None), never clamp to a fake 0
                # that could win the comparison
                delta = timed_chain(fn, 2 * args.chain) \
                    - timed_chain(fn, args.chain)
                times[name].append(delta / args.chain
                                   if delta > 0 else None)
        int8_bytes = 2 * b * width * h * d          # K + V int8 reads
        fp_bytes = int8_bytes * jnp.dtype(dtype).itemsize
        results[str(width)] = {}
        for name, ts in times.items():
            valid = [t for t in ts if t is not None]
            results[str(width)][name] = {
                "ms": (round(statistics.median(valid) * 1e3, 3)
                       if valid else None),
                "invalid_samples": len(ts) - len(valid),
            }
        results[str(width)]["roofline_ms"] = {
            # pure-traffic lower bounds at the measured copy bandwidth
            # (None when the bandwidth calibration was jitter-swamped)
            "kernel_int8_read": (round(int8_bytes / bw * 1e3, 3)
                                 if bw else None),
            "xla_int8_read_fp_write_fp_read": (round(
                (int8_bytes + 2 * fp_bytes) / bw * 1e3, 3)
                if bw else None),
        }

    widest = str(max(int(w) for w in args.widths.split(",")))
    candidates = [(v["ms"], k) for k, v in results[widest].items()
                  if k != "roofline_ms" and v["ms"] is not None]
    best = min(candidates) if candidates else (None, "no-valid-sample")
    print(json.dumps({
        "metric": "int8_attend_best_route_ms",
        "value": best[0],
        "unit": "ms",
        "vs_baseline": None,
        "best_route": best[1],
        "widths": results,
        "copy_bandwidth_gbs": round(bw / 1e9, 1) if bw else None,
        "config": {"batch": b, "heads": h, "head_dim": d,
                   "dtype": args.dtype, "chain": args.chain,
                   "rounds": args.rounds, "interpret": interpret},
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
