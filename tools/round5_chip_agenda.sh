#!/usr/bin/env bash
# Round-5 serialized chip agenda: the verdict's measured A/Bs in one
# single-tenant chip session (docs/PERF.md lease protocol — never run
# two chip processes at once, never signal a running one).
#   1. bench.py            — headline + pinned calibration spread +
#                            fast-numerics buy-back (verdict #1, #7)
#   2. bench_mfu_buckets   — f32/pad/head_dim bucket sizing (verdict #1)
#   3. bench_int8_attend   — XLA vs kernel v1 vs v2 + roofline (verdict #3)
#   4. bench_speculative   — host-sync vs device-sync rounds (verdict #2)
#   5. bench_train x2      — pure-bf16 vs mixed-precision (verdict #6)
#   6. bench_decode        — decode record refresh
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p docs/evidence
LOG=docs/evidence/round5_agenda.log
run() { echo "=== $(date -u +%H:%M:%S) $*" | tee -a "$LOG" >&2;
        stdbuf -oL -eL "$@" 2>&1 | tee -a "$LOG"; }

run python bench.py
run python tools/bench_mfu_buckets.py
run python tools/bench_int8_attend.py
run python tools/bench_speculative.py -m gpt2 -b 8 --prompt-len 64 \
    --new-tokens 64 --gammas 2,4
run python tools/bench_train.py
run python tools/bench_train.py --mixed-precision
run python bench_decode.py
echo "=== agenda done $(date -u +%H:%M:%S)" | tee -a "$LOG"
