"""Chaos harness for the DCN fleet: inject one deterministic fault, record
detection and recovery latency.

Launches a loopback fleet of `runtime.py` ranks (one OS process each, like
tests/test_dcn_runtime.py), arms `DCN_CHAOS` (pipeedge_tpu/comm/chaos.py)
in the victim rank's environment only, and timestamps every rank's output
lines to measure the fault-tolerance layer end to end:

- detect_s:  victim fault observed (process death / chaos log line) ->
             the data rank's death line ("entering failover" / "died")
- recover_s: detection -> run completion (`latency_sec=` from the data
             rank) — failover mode only; in abort mode the fleet stops
- replayed:  microbatches replayed after the failover re-schedule
- rejoin_s / heal_s / time_to_full_capacity_s: the healing timeline of a
             restart fault (detect -> rejoin admission -> partition
             healed at a round boundary); null when no rejoin happened

Emits one JSON line (plus pass-through logs with --verbose). Examples:

  # kill the last stage at its 3rd send; spare rank 2 takes over
  python tools/chaos_dcn.py --world 3 --victim 1 --chaos kill@3

  # no spare capacity: the fleet must abort naming the dead rank
  python tools/chaos_dcn.py --world 2 --victim 1 --chaos kill@2 \
      --expect abort

  # hang (SIGSTOP) a stage: only the heartbeat liveness plane can see it
  python tools/chaos_dcn.py --world 3 --victim 1 --chaos hang@3 \
      --heartbeat-interval 0.5

  # kill + restart after 2s: the rank rejoins (epoch 1) and the healed
  # fleet's final round runs the pre-failure partition again
  python tools/chaos_dcn.py --world 4 --victim 1 --chaos restart@3:2000 \
      --rounds 3 --on-peer-rejoin heal --expect heal

  # gray failure: an 80 ms per-send straggler never misses a beat; the
  # peer-health plane must quarantine it at a round boundary
  python tools/chaos_dcn.py --world 4 --victim 1 --chaos slow@2:80 \
      --rounds 4 --on-peer-degraded quarantine --expect quarantine

  # disaggregated-serving ship edge (--target serve-disagg): kill the
  # prefill worker at its 2nd KV ship, mid shared-prefix burst — gates:
  # zero lost/errored requests (re-dispatch or colocated fallback),
  # zero leaked pages, and the worker respawned + readmitted (epoch+1
  # JOIN), with recovery_s in the record
  python tools/chaos_dcn.py --target serve-disagg --chaos kill@2 \
      --expect disagg

  # routed decode-replica fleet (--target router-fleet): SIGKILL one
  # replica of a 2-replica routed fleet mid shared-prefix burst —
  # gates: zero lost/errored requests (router failover + stream
  # replay), pipeedge_router_failovers_total >= 1, the respawned
  # replica readmitted (epoch+1, healthy), zero leaked pages
  python tools/chaos_dcn.py --target router-fleet --expect router
"""
import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class _TimedReader:
    """Drain a process's stdout, stamping each line's arrival time."""

    def __init__(self, proc):
        self.proc = proc
        self.lines = []          # (monotonic, line)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for line in self.proc.stdout:
            self.lines.append((time.monotonic(), line.rstrip("\n")))

    def first(self, needle):
        for t, line in self.lines:
            if needle in line:
                return t, line
        return None

    def last(self, needle):
        hit = None
        for t, line in self.lines:
            if needle in line:
                hit = (t, line)
        return hit

    def join(self):
        self._thread.join(timeout=5)


def run_serve_disagg(args):
    """The disaggregated-serving chaos experiment: a `--disaggregate
    process` server under a shared-prefix burst while `--chaos` is armed
    on prefill worker rank 1's ship edge (PIPEEDGE_PREFILL_CHAOS). The
    fault-tolerance contract under test (docs/FAULT_TOLERANCE.md):
    every request completes (lease re-dispatch or colocated fallback —
    zero lost, zero errors), page accounting closes with zero leaks,
    and a killed worker is respawned + readmitted (DCN_EPOCH+1 JOIN).
    Emits one JSON line with the fault-window goodput and recovery_s."""
    import json as json_mod
    import urllib.request

    sys.path.insert(0, REPO)
    from tools import loadgen

    port = _free_ports(1)[0]
    url = f"http://127.0.0.1:{port}"
    env = dict(os.environ, PYTHONPATH=REPO,
               PIPEEDGE_PREFILL_CHAOS=args.chaos,
               PIPEEDGE_PREFILL_CHAOS_RANK="1")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("DCN_CONNECT_TIMEOUT", "30")
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve.py"),
           "-m", args.model_name, "-pt", args.partition,
           "--max-len", "64", "-t", "float32", "--port", str(port),
           "--kv-pages", str(args.kv_pages),
           "--kv-page-size", str(args.kv_page_size),
           "--disaggregate", "process",
           "--prefill-ranks", str(args.prefill_ranks),
           "--prefill-lease-timeout", "5",
           "--prefill-heartbeat-interval", "0.5"]
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, env=env, text=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    reader = _TimedReader(proc)

    def healthz(timeout=10.0):
        with urllib.request.urlopen(f"{url}/healthz",
                                    timeout=timeout) as resp:
            return json_mod.loads(resp.read())

    record = {"target": "serve-disagg", "chaos": args.chaos,
              "prefill_ranks": args.prefill_ranks,
              "expect": args.expect}
    try:
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError("serve.py died during startup")
            try:
                healthz(timeout=5)
                break
            except OSError:
                time.sleep(0.5)
        else:
            raise RuntimeError("serve.py never became healthy")
        # warmup compiles the burst's exact shapes so the fault window
        # measures the protocol, not XLA
        shared_max = loadgen.spec_max_len(args.shared_spec)
        for n in {shared_max, 6}:
            req = urllib.request.Request(
                f"{url}/generate",
                data=json_mod.dumps({"ids": [[7] * n],
                                     "new_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                resp.read()
        # the burst: the armed chaos fires at the victim's Kth ship
        # send. A concurrent watcher stamps the readmission AS IT
        # HAPPENS — a worker that respawns mid-burst must not have its
        # recovery_s aliased to the remaining burst duration
        recovered_at = [None]
        watch_stop = threading.Event()

        def watch_readmission():
            seen_down = False
            while not watch_stop.is_set() and recovered_at[0] is None:
                try:
                    live = healthz(timeout=5)["serving"]["kv"][
                        "prefill"]["live"]
                except OSError:
                    watch_stop.wait(0.3)
                    continue
                if len(live) < args.prefill_ranks:
                    seen_down = True
                elif seen_down:
                    recovered_at[0] = time.monotonic()
                    return
                watch_stop.wait(0.2)

        watcher = threading.Thread(target=watch_readmission,
                                   daemon=True, name="readmit-watch")
        watcher.start()
        report = loadgen.run_load(
            f"{url}/generate", args.duration, args.qps,
            mix={"interactive": 1.0}, new_tokens=4,
            prompt_len=args.shared_spec, seed=7, arrival="poisson")
        died = reader.first("died")   # supervisor's death line
        recover_deadline = time.monotonic() + 60
        while recovered_at[0] is None \
                and time.monotonic() < recover_deadline:
            time.sleep(0.3)
        watch_stop.set()
        watcher.join(timeout=10)
        kv = healthz()["serving"]["kv"]
        prefill = kv["prefill"]
        record.update({
            "requests": report["requests"],
            "lost": report["client_dropped"],
            "errors": report["totals"]["error"],
            "shed": report["totals"]["shed"],
            "fault_window_goodput_rps": round(sum(
                c["goodput_rps"] for c in report["classes"].values()), 3),
            "leases": prefill["leases"],
            "colocated": prefill.get("colocated"),
            "zombies_dropped": prefill["zombies_dropped_total"],
            "ship_corrupt": prefill["ship_corrupt_total"],
            "pages_leaked": kv["leaked"],
            "live_ranks": prefill["live"],
            "worker_epochs": {r: w["epoch"] for r, w in
                              prefill.get("workers", {}).items()},
            "recovery_s": (round(recovered_at[0] - died[0], 3)
                           if recovered_at[0] and died else None),
            "readmitted": recovered_at[0] is not None,
            "total_s": round(time.monotonic() - t0, 3),
        })
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        reader.join()
    print(json.dumps(record))
    if args.verbose:
        for t, line in reader.lines:
            print(f"[serve +{t - t0:7.3f}] {line}", file=sys.stderr)
    # the disagg gate: nothing lost, nothing errored, the fault path
    # engaged (re-dispatch/fallback/colocated), zero leaked pages, and
    # the victim readmitted after its respawn
    engaged = (record["leases"]["redispatched"]
               + record["leases"]["fallback"]
               + sum((record["colocated"] or {}).values())) > 0
    ok = (record["errors"] == 0 and record["lost"] == 0
          and record["pages_leaked"] == 0 and engaged
          and record["readmitted"])
    return 0 if ok else 1


def run_router_fleet(args):
    """The routed decode-replica chaos experiment: a `--role router`
    front-end over N supervised replicas under a shared-prefix burst,
    with one replica SIGKILLed mid-burst. The robustness contract under
    test (docs/FAULT_TOLERANCE.md replica lifecycle): every request
    completes (router failover re-routes, streams replay with
    suppression — zero lost, zero errors), the failover counter moved,
    the killed replica respawns + is readmitted (epoch+1, healthy), and
    no replica leaks a page. Emits one JSON line with the fault-window
    goodput, failover count, and readmission latency."""
    import json as json_mod
    import urllib.request

    sys.path.insert(0, REPO)
    from tools import loadgen

    port = _free_ports(1)[0]
    url = f"http://127.0.0.1:{port}"
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve.py"),
           "--role", "router", "--replicas", str(args.replicas),
           "-m", args.model_name, "-pt", args.partition,
           "--max-len", "64", "-t", "float32", "--port", str(port),
           "--kv-pages", str(args.kv_pages),
           "--kv-page-size", str(args.kv_page_size),
           "--router-poll-interval", "0.2"]
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, env=env, text=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    reader = _TimedReader(proc)

    def get_json(path, timeout=10.0):
        with urllib.request.urlopen(f"{url}{path}",
                                    timeout=timeout) as resp:
            return json_mod.loads(resp.read())

    def metric(name):
        with urllib.request.urlopen(f"{url}/metrics",
                                    timeout=10) as resp:
            for line in resp.read().decode().splitlines():
                if line.startswith(name + " "):
                    return float(line.split()[-1])
        return 0.0

    record = {"target": "router-fleet", "replicas": args.replicas,
              "expect": args.expect}
    try:
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError("router died during startup")
            try:
                h = get_json("/healthz", timeout=5)
                if h.get("ok") and all(
                        r["state"] == "healthy"
                        for r in h["fleet"].values()):
                    break
            except OSError:
                pass
            time.sleep(0.5)
        else:
            raise RuntimeError("router fleet never became healthy")
        epochs0 = {n: r["epoch"] for n, r in h["fleet"].items()}
        # warm EVERY replica directly (the router's affinity map would
        # otherwise leave one cold and fold its first XLA compile into
        # the fault window)
        shared_max = loadgen.spec_max_len(args.shared_spec)
        for rep in h["fleet"].values():
            for n in {shared_max, 6}:
                req = urllib.request.Request(
                    f"{rep['url']}/generate",
                    data=json_mod.dumps({"ids": [7] * n,
                                         "new_tokens": 2}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=180) as resp:
                    resp.read()
        # the kill thread fires mid-burst at whichever replica is
        # actively serving; a concurrent watcher stamps the respawn's
        # readmission AS IT HAPPENS
        killed = {}           # victim -> kill instant
        recovered_at = [None]
        watch_stop = threading.Event()

        def kill_one():
            watch_stop.wait(args.kill_after)
            if watch_stop.is_set():
                return
            try:
                body = get_json("/healthz", timeout=5)
            except OSError:
                return
            fleet = body["fleet"]
            victim = next((n for n, rec in fleet.items()
                           if rec.get("active")), sorted(fleet)[0])
            pid = body["workers"][victim[1:]]["pid"]
            os.kill(pid, signal.SIGKILL)
            killed[victim] = time.monotonic()

        def watch_readmission():
            while not watch_stop.is_set() and recovered_at[0] is None:
                if killed:
                    try:
                        fleet = get_json("/healthz",
                                         timeout=5)["fleet"]
                    except OSError:
                        watch_stop.wait(0.3)
                        continue
                    victim = next(iter(killed))
                    rec = fleet[victim]
                    if rec["epoch"] > epochs0[victim] \
                            and rec["state"] == "healthy":
                        recovered_at[0] = time.monotonic()
                        return
                watch_stop.wait(0.2)

        killer = threading.Thread(target=kill_one, daemon=True,
                                  name="chaos-kill")
        watcher = threading.Thread(target=watch_readmission,
                                   daemon=True, name="readmit-watch")
        killer.start()
        watcher.start()
        report = loadgen.run_load(
            f"{url}/generate", args.duration, args.qps,
            mix={"interactive": 1.0}, new_tokens=4,
            prompt_len=args.shared_spec, seed=7, arrival="poisson")
        recover_deadline = time.monotonic() + 120
        while recovered_at[0] is None \
                and time.monotonic() < recover_deadline:
            time.sleep(0.3)
        watch_stop.set()
        killer.join(timeout=10)
        watcher.join(timeout=10)
        fleet = get_json("/healthz")["fleet"]
        # the page-accounting gate spans every replica: ask each one's
        # own /healthz for its orphan-sweep running total
        leaked = 0
        for rep in fleet.values():
            try:
                with urllib.request.urlopen(f"{rep['url']}/healthz",
                                            timeout=10) as resp:
                    body = json_mod.loads(resp.read())
                leaked += ((body.get("serving") or {}).get("kv")
                           or {}).get("leaked", 0)
            except OSError:
                pass      # a dead replica holds no pages to leak
        victim = next(iter(killed), None)
        record.update({
            "requests": report["requests"],
            "lost": report["client_dropped"],
            "errors": report["totals"]["error"],
            "shed": report["totals"]["shed"],
            "fault_window_goodput_rps": round(sum(
                c["goodput_rps"] for c in report["classes"].values()), 3),
            "victim": victim,
            "failovers": metric("pipeedge_router_failovers_total"),
            "retries": metric("pipeedge_router_retries_total"),
            "pages_leaked": leaked,
            "replica_epochs": {n: r["epoch"] for n, r in fleet.items()},
            "replica_states": {n: r["state"] for n, r in fleet.items()},
            "recovery_s": (round(recovered_at[0] - killed[victim], 3)
                           if recovered_at[0] and victim else None),
            "readmitted": recovered_at[0] is not None,
            "total_s": round(time.monotonic() - t0, 3),
        })
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        reader.join()
    print(json.dumps(record))
    if args.verbose:
        for t, line in reader.lines:
            print(f"[router +{t - t0:7.3f}] {line}", file=sys.stderr)
    # the router gate: nothing lost, nothing errored, the failover path
    # engaged (>= 1 re-route off the corpse), zero leaked pages, and
    # the victim respawned + readmitted before the harness deadline
    ok = (record["errors"] == 0 and record["lost"] == 0
          and record["failovers"] >= 1 and record["pages_leaked"] == 0
          and record["readmitted"])
    return 0 if ok else 1


def run_autoscale(args):
    """The self-driving-capacity chaos experiment (--target autoscale):
    a router fleet parked at its 1-replica floor in `--autoscale auto`
    under a seeded piecewise-linear load ramp (`loadgen --arrival
    ramp:LO:HI`). Gates (--expect scale): the controller scales up off
    the floor while the ramp is still offering load (capacity arrives
    before the surge ends, not after), drains back to the floor once
    the ramp falls away, loses and errors nothing, and leaks zero KV
    pages; every spawn is epoch-stamped. `--expect steady` instead
    offers a flat comfortable load and gates ZERO decisions — the
    flap-damper/false-positive control arm. Emits one JSON line."""
    import json as json_mod
    import urllib.request

    sys.path.insert(0, REPO)
    from tools import loadgen

    port = _free_ports(1)[0]
    url = f"http://127.0.0.1:{port}"
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    floor, ceiling = 1, max(2, args.replicas)
    # --max-active 1 makes one replica's honest capacity a few req/s,
    # so the ramp's plateau queues at the admission controller — the
    # queue-depth signal the controller scales on — without needing to
    # saturate the host
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve.py"),
           "--role", "router", "--replicas", str(floor),
           "-m", args.model_name, "-pt", args.partition,
           "--max-len", "64", "-t", "float32", "--port", str(port),
           "--kv-pages", str(args.kv_pages),
           "--kv-page-size", str(args.kv_page_size),
           "--max-active", "1",
           "--router-poll-interval", "0.2",
           "--fleet-scrape-interval", "0.3",
           "--autoscale", "auto",
           "--autoscale-min", str(floor),
           "--autoscale-max", str(ceiling),
           "--autoscale-confirm", "2",
           "--autoscale-cooldown", "2.0",
           "--autoscale-interval", "0.3",
           "--autoscale-dwell-down", "1.0",
           "--autoscale-queue-high", "2.0",
           "--autoscale-queue-low", "0.5"]
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, env=env, text=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    reader = _TimedReader(proc)

    def get_json(path, timeout=10.0):
        with urllib.request.urlopen(f"{url}{path}",
                                    timeout=timeout) as resp:
            return json_mod.loads(resp.read())

    record = {"target": "autoscale", "expect": args.expect,
              "floor": floor, "ceiling": ceiling}
    try:
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError("router died during startup")
            try:
                h = get_json("/healthz", timeout=5)
                if h.get("ok") and all(
                        r["state"] == "healthy"
                        for r in h["fleet"].values()):
                    break
            except OSError:
                pass
            time.sleep(0.5)
        else:
            raise RuntimeError("router fleet never became healthy")
        # warm the floor replica with the SAME shape the load will
        # send: the first request that crosses a KV page boundary pays
        # a multi-second XLA compile, and an unwarmed compile stall
        # masquerades as a capacity shortfall (queue depth spikes on a
        # fleet that is not actually hot)
        n_new = 4 if args.expect == "steady" else 24
        for rep in h["fleet"].values():
            req = urllib.request.Request(
                f"{rep['url']}/generate",
                data=json_mod.dumps({"ids": [7] * 6,
                                     "new_tokens": n_new}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=180) as resp:
                resp.read()
        load_t0 = time.monotonic()
        if args.expect == "steady":
            # the control arm: flat, comfortable load — the controller
            # must record ZERO decisions (no flaps on a clean fleet)
            report = loadgen.run_load(
                f"{url}/generate", args.duration, 1.0,
                mix={"interactive": 1.0}, deadline_from_slo=False,
                new_tokens=n_new, prompt_len="6", seed=7,
                arrival="uniform")
        else:
            # 24 decode tokens per request keeps one --max-active 1
            # replica's honest capacity around ~3 req/s, so the ramp's
            # plateau genuinely queues instead of sliding under the
            # queue-high threshold on a fast warm cache
            report = loadgen.run_load(
                f"{url}/generate", args.duration, None,
                mix={"interactive": 1.0}, deadline_from_slo=False,
                new_tokens=n_new, prompt_len="6", seed=7,
                arrival=args.ramp)
        load_s = time.monotonic() - load_t0
        # after the ramp: wait for the drain back to the floor (the
        # down path needs queue-low confirmation + dwell + cooldown)
        scale_down_s = None
        settle_deadline = time.monotonic() + (
            5.0 if args.expect == "steady" else 90.0)
        while time.monotonic() < settle_deadline:
            h = get_json("/healthz", timeout=5)
            a = h.get("autoscale") or {}
            if args.expect == "steady":
                time.sleep(0.5)
                continue
            if a.get("size") == floor and (
                    a.get("decisions") or {}).get("applied", 0) >= 2:
                scale_down_s = time.monotonic() - load_t0 - load_s
                break
            time.sleep(0.5)
        h = get_json("/healthz", timeout=5)
        asnap = h.get("autoscale") or {}
        # page accounting across every LIVE replica: the migrate-on-
        # drain path must strand nothing
        leaked = 0
        for rep in h["fleet"].values():
            try:
                with urllib.request.urlopen(f"{rep['url']}/healthz",
                                            timeout=10) as resp:
                    body = json_mod.loads(resp.read())
                leaked += ((body.get("serving") or {}).get("kv")
                           or {}).get("leaked", 0)
            except OSError:
                pass       # a drained replica holds no pages to leak
        spawn = reader.first("autoscale_spawn")
        drain = reader.first("autoscale_drain")
        spawns = [line for _, line in reader.lines
                  if line.startswith("autoscale_spawn")]
        epochs = [int(part.split("=", 1)[1]) for line in spawns
                  for part in line.split() if part.startswith("epoch=")]
        record.update({
            "requests": report["requests"],
            "offered_qps": report["offered_qps"],
            "ramp": report.get("ramp"),
            "lost": report["client_dropped"],
            "errors": report["totals"]["error"],
            "shed": report["totals"]["shed"],
            "attainment": {c: v["slo_attainment"]
                           for c, v in report["classes"].items()},
            "decisions": asnap.get("decisions"),
            "ticks": asnap.get("ticks"),
            "final_size": asnap.get("size"),
            "spawns": len(spawns),
            "spawn_epochs": epochs,
            "time_to_scale_up_s": (round(spawn[0] - load_t0, 3)
                                   if spawn else None),
            "scale_down_s": (round(scale_down_s, 3)
                             if scale_down_s is not None else None),
            "drained": drain is not None,
            "pages_leaked": leaked,
            "total_s": round(time.monotonic() - t0, 3),
        })
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        reader.join()
    print(json.dumps(record))
    if args.verbose:
        for t, line in reader.lines:
            print(f"[router +{t - t0:7.3f}] {line}", file=sys.stderr)
    decisions = record.get("decisions") or {}
    if args.expect == "steady":
        # the clean-fleet gate: the governor ticked, decided NOTHING,
        # and the fleet never left the floor
        ok = (record["errors"] == 0 and record["lost"] == 0
              and (record.get("ticks") or 0) > 0
              and sum(decisions.values()) == 0
              and record.get("final_size") == floor
              and record["pages_leaked"] == 0)
    else:
        # the ramp gate: scaled up WHILE the ramp was still offering
        # load, drained back to the floor after it, nothing lost or
        # errored, nothing leaked
        up_in_time = (record["time_to_scale_up_s"] is not None
                      and record["time_to_scale_up_s"] < args.duration)
        ok = (record["errors"] == 0 and record["lost"] == 0
              and up_in_time and record["drained"]
              and record.get("final_size") == floor
              and decisions.get("applied", 0) >= 2
              and record["pages_leaked"] == 0)
    return 0 if ok else 1


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--target", default="runtime",
                   choices=["runtime", "serve-disagg", "router-fleet",
                            "autoscale"],
                   help="runtime: a runtime.py DCN fleet (the original "
                        "experiments); serve-disagg: a --disaggregate "
                        "process serving fleet with --chaos armed on "
                        "the prefill worker's ship edge; router-fleet: "
                        "a --role router replica fleet with a mid-burst "
                        "replica SIGKILL; autoscale: a 1-replica-floor "
                        "fleet in --autoscale auto under a loadgen ramp "
                        "(--expect scale) or flat control load "
                        "(--expect steady)")
    p.add_argument("--world", type=int, default=3)
    p.add_argument("--victim", type=int, default=1,
                   help="rank DCN_CHAOS is armed in (must not be the "
                        "data rank)")
    p.add_argument("--chaos", default="kill@3",
                   help="DCN_CHAOS spec: kill@K | hang@K | drop@K | "
                        "delay@K:MS | restart@K:MS | flap@K:MS | "
                        "slow@K[-J]:MS | jitter@K[-J]:MS | corrupt@K")
    p.add_argument("--expect", default="recover",
                   choices=["recover", "abort", "heal", "quarantine",
                            "disagg", "router", "scale", "steady"],
                   help="recover: the run must complete; abort: the fleet "
                        "must stop naming the victim; heal: the run must "
                        "complete AND the victim must rejoin AND the "
                        "partition must heal (finite "
                        "time_to_full_capacity_s); quarantine: the run "
                        "must complete AND the peer-health plane must "
                        "quarantine the victim (gray-failure faults — "
                        "slow@K:MS with --on-peer-degraded quarantine)")
    p.add_argument("--on-peer-death", default="failover",
                   choices=["abort", "failover"])
    p.add_argument("--on-peer-rejoin", default="spare",
                   choices=["ignore", "spare", "heal"],
                   help="fleet rejoin policy (restart@K:MS faults)")
    p.add_argument("--on-peer-degraded", default="ignore",
                   choices=["ignore", "quarantine"],
                   help="fleet gray-failure policy (slow/jitter faults; "
                        "docs/FAULT_TOLERANCE.md gray failures)")
    p.add_argument("--degraded-confirm", type=int, default=1,
                   help="confirmation windows before quarantine (chaos "
                        "experiments default to the fastest honest "
                        "setting: suspect entry + 1 confirming window)")
    p.add_argument("--degraded-readmit", type=int, default=1,
                   help="recovered windows before probation readmission")
    p.add_argument("--rounds", type=int, default=1,
                   help="schedule rounds (heal applies at round "
                        "boundaries, so restart experiments need > 1)")
    p.add_argument("--reconnect-grace", type=float, default=0.0,
                   help="DCN_RECONNECT_GRACE for every rank (flap@K:MS "
                        "faults are survivable when this exceeds MS)")
    p.add_argument("-m", "--model-name", default="pipeedge/test-tiny-vit")
    p.add_argument("-pt", "--partition", default="1,4,5,8")
    p.add_argument("-r", "--rank-order", default="0,1")
    p.add_argument("-b", "--batch-size", type=int, default=24)
    p.add_argument("-u", "--ubatch-size", type=int, default=4)
    # interval*miss must exceed the worst GIL stall a BUSY rank can take
    # (stage build / jit compile can starve its beat thread for seconds)
    p.add_argument("--heartbeat-interval", type=float, default=1.0)
    p.add_argument("--heartbeat-miss", type=int, default=5)
    p.add_argument("--sched-timeout", type=float, default=120)
    p.add_argument("--timeout", type=float, default=300,
                   help="harness deadline for the whole experiment")
    p.add_argument("--verbose", action="store_true",
                   help="replay every rank's output lines to stderr")
    p.add_argument("--prefill-ranks", type=int, default=2,
                   help="serve-disagg: prefill worker processes")
    p.add_argument("--kv-pages", type=int, default=96,
                   help="serve-disagg: page-pool size")
    p.add_argument("--kv-page-size", type=int, default=8)
    p.add_argument("--qps", type=float, default=3.0,
                   help="serve-disagg: offered burst rate")
    p.add_argument("--duration", type=float, default=8.0,
                   help="serve-disagg: burst seconds")
    p.add_argument("--shared-spec", default="shared:16:24:2",
                   help="serve-disagg/router-fleet: loadgen "
                        "shared-prefix prompt distribution for the "
                        "burst")
    p.add_argument("--replicas", type=int, default=2,
                   help="router-fleet: supervised decode replicas")
    p.add_argument("--kill-after", type=float, default=2.5,
                   help="router-fleet: seconds into the burst before "
                        "the SIGKILL lands on the active replica")
    p.add_argument("--ramp", default="ramp:1:8:0.4",
                   help="autoscale: the loadgen --arrival ramp spec "
                        "offered during --expect scale (LO->HI->LO "
                        "req/s over --duration)")
    args = p.parse_args()
    if args.target in ("serve-disagg", "router-fleet", "autoscale"):
        if args.model_name == "pipeedge/test-tiny-vit":
            # the runtime default is a ViT; serving needs a decoder
            args.model_name = "pipeedge/test-tiny-gpt2"
        if args.target == "autoscale":
            if args.expect not in ("scale", "steady"):
                args.expect = "scale"
            return run_autoscale(args)
        if args.target == "router-fleet":
            return run_router_fleet(args)
        return run_serve_disagg(args)
    if args.victim == 0:
        p.error("--victim 0 is the data rank (the driver; killing it "
                "kills the experiment, not the pipeline)")

    addrs = ",".join(f"127.0.0.1:{port}"
                     for port in _free_ports(args.world))
    quant = ",".join("0" for _ in args.partition.split(",")[::2])
    common = ["-c", "dcn", "--platform", "cpu", "-m", args.model_name,
              "-b", str(args.batch_size), "-u", str(args.ubatch_size),
              "-pt", args.partition, "-q", quant, "-r", args.rank_order,
              "--dcn-addrs", addrs,
              "--sched-timeout", str(args.sched_timeout),
              "--on-peer-death", args.on_peer_death,
              "--on-peer-rejoin", args.on_peer_rejoin,
              "--on-peer-degraded", args.on_peer_degraded,
              "--degraded-confirm", str(args.degraded_confirm),
              "--degraded-readmit", str(args.degraded_readmit),
              "--rounds", str(args.rounds),
              "--heartbeat-interval", str(args.heartbeat_interval),
              "--heartbeat-miss", str(args.heartbeat_miss)]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.setdefault("DCN_CONNECT_TIMEOUT", "30")
    if args.reconnect_grace > 0:
        env["DCN_RECONNECT_GRACE"] = str(args.reconnect_grace)

    def launch(rank, extra_env=None):
        return subprocess.Popen(
            [sys.executable, os.path.join(REPO, "runtime.py"),
             str(rank), str(args.world)] + common,
            env=dict(env, **(extra_env or {})), text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    procs, readers = {}, {}
    t0 = time.monotonic()
    try:
        for rank in range(args.world):
            extra = ({"DCN_CHAOS": args.chaos} if rank == args.victim
                     else None)
            procs[rank] = launch(rank, extra)
            readers[rank] = _TimedReader(procs[rank])
        deadline = t0 + args.timeout
        data = procs[0]
        while time.monotonic() < deadline and data.poll() is None:
            time.sleep(0.25)
        timed_out = data.poll() is None
    finally:
        for rank, proc in procs.items():
            if proc.poll() is None:
                try:
                    # a SIGSTOPped (hang-chaos) victim still dies to KILL
                    os.kill(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
                proc.wait()
    for r in readers.values():
        r.join()

    # the fault instant: the chaos module logs right before acting —
    # skip the startup "chaos: installed <spec>" line, which arrives at
    # process launch and would fold model-build/jit time into every
    # detection latency (slow/jitter log an explicit arming line)
    fault = next(((t, line) for t, line in readers[args.victim].lines
                  if "chaos:" in line and "installed" not in line), None)
    # the data rank may detect the death itself ("entering failover") or
    # learn it from a survivor's CMD_DEAD ("announced dead")
    detect = (readers[0].first("entering failover")
              or readers[0].first("announced dead")
              or readers[0].first("died"))
    recover = readers[0].last("latency_sec=")
    replayed_line = readers[0].first("unacknowledged microbatch")
    replayed = None
    if replayed_line:
        for tok in replayed_line[1].split():
            if tok.isdigit():
                replayed = int(tok)
    # healing timeline (restart faults): the data rank prints one
    # machine-parseable line per admission and per heal
    rejoin = readers[0].first("rejoin_rank=")
    healed = readers[0].first("heal_round=")
    ttfc = None
    if healed:
        for tok in healed[1].split():
            if tok.startswith("time_to_full_capacity_s="):
                ttfc = float(tok.split("=", 1)[1])
    # gray-failure timeline (slow/jitter faults): the data rank prints
    # one machine-parseable line per quarantine and per readmission
    quarantine = readers[0].first("quarantine_rank=")
    readmit = readers[0].first("readmit_rank=")
    completed = (not timed_out and data.returncode == 0
                 and recover is not None)
    aborted = (not timed_out and data.returncode not in (None, 0)
               and readers[0].first("died") is not None)
    record = {
        "chaos": args.chaos,
        "victim": args.victim,
        "world": args.world,
        "mode": args.on_peer_death,
        "rejoin_mode": args.on_peer_rejoin,
        "expect": args.expect,
        "completed": completed,
        "aborted": aborted,
        "timed_out": timed_out,
        "data_rc": data.returncode,
        "detect_s": (round(detect[0] - fault[0], 3)
                     if detect and fault else None),
        "recover_s": (round(recover[0] - detect[0], 3)
                      if recover and detect and completed else None),
        # detect -> JOIN admission at the data rank
        "rejoin_s": (round(rejoin[0] - detect[0], 3)
                     if rejoin and detect else None),
        # admission -> partition healed at a round boundary
        "heal_s": (round(healed[0] - rejoin[0], 3)
                   if healed and rejoin else None),
        # the data rank's own detection->healed clock (finite only when
        # a heal actually closed the episode)
        "time_to_full_capacity_s": ttfc,
        # gray-failure timeline: fault -> quarantine (a planned bench at
        # a round boundary), quarantine -> probation readmission
        "quarantine_s": (round(quarantine[0] - fault[0], 3)
                         if quarantine and fault else None),
        "readmit_s": (round(readmit[0] - quarantine[0], 3)
                      if readmit and quarantine else None),
        "total_s": round(time.monotonic() - t0, 3),
        "replayed": replayed,
    }
    print(json.dumps(record))
    if args.verbose:
        for rank, reader in readers.items():
            for t, line in reader.lines:
                print(f"[rank{rank} +{t - t0:7.3f}] {line}",
                      file=sys.stderr)
    if args.expect == "heal":
        ok = completed and rejoin is not None and ttfc is not None
    elif args.expect == "quarantine":
        ok = completed and quarantine is not None
    elif args.expect == "recover":
        ok = completed
    else:
        ok = aborted
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
