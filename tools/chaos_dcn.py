"""Chaos harness for the DCN fleet: inject one deterministic fault, record
detection and recovery latency.

Launches a loopback fleet of `runtime.py` ranks (one OS process each, like
tests/test_dcn_runtime.py), arms `DCN_CHAOS` (pipeedge_tpu/comm/chaos.py)
in the victim rank's environment only, and timestamps every rank's output
lines to measure the fault-tolerance layer end to end:

- detect_s:  victim fault observed (process death / chaos log line) ->
             the data rank's death line ("entering failover" / "died")
- recover_s: detection -> run completion (`latency_sec=` from the data
             rank) — failover mode only; in abort mode the fleet stops
- replayed:  microbatches replayed after the failover re-schedule
- rejoin_s / heal_s / time_to_full_capacity_s: the healing timeline of a
             restart fault (detect -> rejoin admission -> partition
             healed at a round boundary); null when no rejoin happened

Emits one JSON line (plus pass-through logs with --verbose). Examples:

  # kill the last stage at its 3rd send; spare rank 2 takes over
  python tools/chaos_dcn.py --world 3 --victim 1 --chaos kill@3

  # no spare capacity: the fleet must abort naming the dead rank
  python tools/chaos_dcn.py --world 2 --victim 1 --chaos kill@2 \
      --expect abort

  # hang (SIGSTOP) a stage: only the heartbeat liveness plane can see it
  python tools/chaos_dcn.py --world 3 --victim 1 --chaos hang@3 \
      --heartbeat-interval 0.5

  # kill + restart after 2s: the rank rejoins (epoch 1) and the healed
  # fleet's final round runs the pre-failure partition again
  python tools/chaos_dcn.py --world 4 --victim 1 --chaos restart@3:2000 \
      --rounds 3 --on-peer-rejoin heal --expect heal

  # gray failure: an 80 ms per-send straggler never misses a beat; the
  # peer-health plane must quarantine it at a round boundary
  python tools/chaos_dcn.py --world 4 --victim 1 --chaos slow@2:80 \
      --rounds 4 --on-peer-degraded quarantine --expect quarantine
"""
import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class _TimedReader:
    """Drain a process's stdout, stamping each line's arrival time."""

    def __init__(self, proc):
        self.proc = proc
        self.lines = []          # (monotonic, line)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for line in self.proc.stdout:
            self.lines.append((time.monotonic(), line.rstrip("\n")))

    def first(self, needle):
        for t, line in self.lines:
            if needle in line:
                return t, line
        return None

    def last(self, needle):
        hit = None
        for t, line in self.lines:
            if needle in line:
                hit = (t, line)
        return hit

    def join(self):
        self._thread.join(timeout=5)


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--world", type=int, default=3)
    p.add_argument("--victim", type=int, default=1,
                   help="rank DCN_CHAOS is armed in (must not be the "
                        "data rank)")
    p.add_argument("--chaos", default="kill@3",
                   help="DCN_CHAOS spec: kill@K | hang@K | drop@K | "
                        "delay@K:MS | restart@K:MS | flap@K:MS | "
                        "slow@K[-J]:MS | jitter@K[-J]:MS | corrupt@K")
    p.add_argument("--expect", default="recover",
                   choices=["recover", "abort", "heal", "quarantine"],
                   help="recover: the run must complete; abort: the fleet "
                        "must stop naming the victim; heal: the run must "
                        "complete AND the victim must rejoin AND the "
                        "partition must heal (finite "
                        "time_to_full_capacity_s); quarantine: the run "
                        "must complete AND the peer-health plane must "
                        "quarantine the victim (gray-failure faults — "
                        "slow@K:MS with --on-peer-degraded quarantine)")
    p.add_argument("--on-peer-death", default="failover",
                   choices=["abort", "failover"])
    p.add_argument("--on-peer-rejoin", default="spare",
                   choices=["ignore", "spare", "heal"],
                   help="fleet rejoin policy (restart@K:MS faults)")
    p.add_argument("--on-peer-degraded", default="ignore",
                   choices=["ignore", "quarantine"],
                   help="fleet gray-failure policy (slow/jitter faults; "
                        "docs/FAULT_TOLERANCE.md gray failures)")
    p.add_argument("--degraded-confirm", type=int, default=1,
                   help="confirmation windows before quarantine (chaos "
                        "experiments default to the fastest honest "
                        "setting: suspect entry + 1 confirming window)")
    p.add_argument("--degraded-readmit", type=int, default=1,
                   help="recovered windows before probation readmission")
    p.add_argument("--rounds", type=int, default=1,
                   help="schedule rounds (heal applies at round "
                        "boundaries, so restart experiments need > 1)")
    p.add_argument("--reconnect-grace", type=float, default=0.0,
                   help="DCN_RECONNECT_GRACE for every rank (flap@K:MS "
                        "faults are survivable when this exceeds MS)")
    p.add_argument("-m", "--model-name", default="pipeedge/test-tiny-vit")
    p.add_argument("-pt", "--partition", default="1,4,5,8")
    p.add_argument("-r", "--rank-order", default="0,1")
    p.add_argument("-b", "--batch-size", type=int, default=24)
    p.add_argument("-u", "--ubatch-size", type=int, default=4)
    # interval*miss must exceed the worst GIL stall a BUSY rank can take
    # (stage build / jit compile can starve its beat thread for seconds)
    p.add_argument("--heartbeat-interval", type=float, default=1.0)
    p.add_argument("--heartbeat-miss", type=int, default=5)
    p.add_argument("--sched-timeout", type=float, default=120)
    p.add_argument("--timeout", type=float, default=300,
                   help="harness deadline for the whole experiment")
    p.add_argument("--verbose", action="store_true",
                   help="replay every rank's output lines to stderr")
    args = p.parse_args()
    if args.victim == 0:
        p.error("--victim 0 is the data rank (the driver; killing it "
                "kills the experiment, not the pipeline)")

    addrs = ",".join(f"127.0.0.1:{port}"
                     for port in _free_ports(args.world))
    quant = ",".join("0" for _ in args.partition.split(",")[::2])
    common = ["-c", "dcn", "--platform", "cpu", "-m", args.model_name,
              "-b", str(args.batch_size), "-u", str(args.ubatch_size),
              "-pt", args.partition, "-q", quant, "-r", args.rank_order,
              "--dcn-addrs", addrs,
              "--sched-timeout", str(args.sched_timeout),
              "--on-peer-death", args.on_peer_death,
              "--on-peer-rejoin", args.on_peer_rejoin,
              "--on-peer-degraded", args.on_peer_degraded,
              "--degraded-confirm", str(args.degraded_confirm),
              "--degraded-readmit", str(args.degraded_readmit),
              "--rounds", str(args.rounds),
              "--heartbeat-interval", str(args.heartbeat_interval),
              "--heartbeat-miss", str(args.heartbeat_miss)]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.setdefault("DCN_CONNECT_TIMEOUT", "30")
    if args.reconnect_grace > 0:
        env["DCN_RECONNECT_GRACE"] = str(args.reconnect_grace)

    def launch(rank, extra_env=None):
        return subprocess.Popen(
            [sys.executable, os.path.join(REPO, "runtime.py"),
             str(rank), str(args.world)] + common,
            env=dict(env, **(extra_env or {})), text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    procs, readers = {}, {}
    t0 = time.monotonic()
    try:
        for rank in range(args.world):
            extra = ({"DCN_CHAOS": args.chaos} if rank == args.victim
                     else None)
            procs[rank] = launch(rank, extra)
            readers[rank] = _TimedReader(procs[rank])
        deadline = t0 + args.timeout
        data = procs[0]
        while time.monotonic() < deadline and data.poll() is None:
            time.sleep(0.25)
        timed_out = data.poll() is None
    finally:
        for rank, proc in procs.items():
            if proc.poll() is None:
                try:
                    # a SIGSTOPped (hang-chaos) victim still dies to KILL
                    os.kill(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
                proc.wait()
    for r in readers.values():
        r.join()

    # the fault instant: the chaos module logs right before acting —
    # skip the startup "chaos: installed <spec>" line, which arrives at
    # process launch and would fold model-build/jit time into every
    # detection latency (slow/jitter log an explicit arming line)
    fault = next(((t, line) for t, line in readers[args.victim].lines
                  if "chaos:" in line and "installed" not in line), None)
    # the data rank may detect the death itself ("entering failover") or
    # learn it from a survivor's CMD_DEAD ("announced dead")
    detect = (readers[0].first("entering failover")
              or readers[0].first("announced dead")
              or readers[0].first("died"))
    recover = readers[0].last("latency_sec=")
    replayed_line = readers[0].first("unacknowledged microbatch")
    replayed = None
    if replayed_line:
        for tok in replayed_line[1].split():
            if tok.isdigit():
                replayed = int(tok)
    # healing timeline (restart faults): the data rank prints one
    # machine-parseable line per admission and per heal
    rejoin = readers[0].first("rejoin_rank=")
    healed = readers[0].first("heal_round=")
    ttfc = None
    if healed:
        for tok in healed[1].split():
            if tok.startswith("time_to_full_capacity_s="):
                ttfc = float(tok.split("=", 1)[1])
    # gray-failure timeline (slow/jitter faults): the data rank prints
    # one machine-parseable line per quarantine and per readmission
    quarantine = readers[0].first("quarantine_rank=")
    readmit = readers[0].first("readmit_rank=")
    completed = (not timed_out and data.returncode == 0
                 and recover is not None)
    aborted = (not timed_out and data.returncode not in (None, 0)
               and readers[0].first("died") is not None)
    record = {
        "chaos": args.chaos,
        "victim": args.victim,
        "world": args.world,
        "mode": args.on_peer_death,
        "rejoin_mode": args.on_peer_rejoin,
        "expect": args.expect,
        "completed": completed,
        "aborted": aborted,
        "timed_out": timed_out,
        "data_rc": data.returncode,
        "detect_s": (round(detect[0] - fault[0], 3)
                     if detect and fault else None),
        "recover_s": (round(recover[0] - detect[0], 3)
                      if recover and detect and completed else None),
        # detect -> JOIN admission at the data rank
        "rejoin_s": (round(rejoin[0] - detect[0], 3)
                     if rejoin and detect else None),
        # admission -> partition healed at a round boundary
        "heal_s": (round(healed[0] - rejoin[0], 3)
                   if healed and rejoin else None),
        # the data rank's own detection->healed clock (finite only when
        # a heal actually closed the episode)
        "time_to_full_capacity_s": ttfc,
        # gray-failure timeline: fault -> quarantine (a planned bench at
        # a round boundary), quarantine -> probation readmission
        "quarantine_s": (round(quarantine[0] - fault[0], 3)
                         if quarantine and fault else None),
        "readmit_s": (round(readmit[0] - quarantine[0], 3)
                      if readmit and quarantine else None),
        "total_s": round(time.monotonic() - t0, 3),
        "replayed": replayed,
    }
    print(json.dumps(record))
    if args.verbose:
        for rank, reader in readers.items():
            for t, line in reader.lines:
                print(f"[rank{rank} +{t - t0:7.3f}] {line}",
                      file=sys.stderr)
    if args.expect == "heal":
        ok = completed and rejoin is not None and ttfc is not None
    elif args.expect == "quarantine":
        ok = completed and quarantine is not None
    elif args.expect == "recover":
        ok = completed
    else:
        ok = aborted
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
