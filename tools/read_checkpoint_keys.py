"""Dump checkpoint keys and shapes (reference tools/read_pth_files.py).

Supports the framework's npz weights files and torch .pth checkpoints.
"""
import argparse

import numpy as np


def main():
    parser = argparse.ArgumentParser(description="List checkpoint keys/shapes")
    parser.add_argument("file", type=str, help=".npz or .pth checkpoint")
    args = parser.parse_args()
    if args.file.endswith(".pth") or args.file.endswith(".pt"):
        import torch
        net = torch.load(args.file, map_location="cpu")
        state = net.get("model", net) if isinstance(net, dict) else net
        for key, value in state.items():
            print(key, tuple(value.size()), sep="   ")
    else:
        with np.load(args.file) as weights:
            for key in weights.files:
                print(key, weights[key].shape, sep="   ")


if __name__ == "__main__":
    main()
