"""End-to-end speculative decoding A/B: host-sync vs device-sync rounds.

The round-5 claim under measurement (docs/DECODE.md): on the tunneled
chip every host readback costs ~RTT, so host-sync speculative decoding
pays (gamma+1) round trips per round while `sync='device'` fuses the
whole round — draft catch-up, gamma-1 draft steps, verify span,
acceptance count — into ONE compiled program with ONE packed readback
(parallel/speculative.py). This bench records tokens/sec and measured
syncs/token for plain greedy, host-sync, and device-sync speculative
decoding with identical tokens.

The draft is an EARLY-EXIT self-draft (Draft&Verify-style): the first
`--draft-fraction` of the target's own blocks plus its shared embed/
final head. That makes the draft genuinely ~2x cheaper than the target
AND gives real (measured, not simulated) acceptance even on seeded
random weights — a random-init transformer's residual stream changes
slowly across blocks, so the truncated model's argmax frequently agrees
with the full model's. Acceptance is reported; all speedups are
interleaved same-session A/Bs.
"""
import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model-name", default="gpt2")
    p.add_argument("-b", "--batch", default=8, type=int)
    p.add_argument("--prompt-len", default=64, type=int)
    p.add_argument("--new-tokens", default=64, type=int)
    p.add_argument("--gammas", default="2,4")
    p.add_argument("--draft-fraction", default=0.5, type=float)
    p.add_argument("--max-len", default=256, type=int)
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--reps", default=3, type=int)
    args = p.parse_args()

    from pipeedge_tpu.utils import apply_env_platform, require_live_backend
    apply_env_platform()
    require_live_backend("speculative_decode_tokens_per_sec",
                         unit="tokens/sec")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pipeedge_tpu.models import registry
    from pipeedge_tpu.parallel import decode
    from pipeedge_tpu.parallel.speculative import SpeculativeDecoder

    cfg = registry.get_model_config(args.model_name)
    total = registry.get_model_layers(args.model_name)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    max_len = min(max(args.max_len,
                      args.prompt_len + args.new_tokens
                      + max(int(g) for g in args.gammas.split(","))),
                  cfg.max_position_embeddings or 10**9)
    _, params, _ = registry.module_shard_factory(
        args.model_name, None, 1, total, dtype=dtype, unroll=False)
    family = registry.get_model_entry(args.model_name).family.FAMILY
    target = decode.DecodePipeline(family, cfg, [(1, total)], [params],
                                   max_len=max_len, dtype=dtype)

    # early-exit self-draft: first K of the target's own stacked blocks
    # with the shared embed + final head
    n_draft = max(1, int(cfg.num_hidden_layers * args.draft_fraction))
    d_cfg = dataclasses.replace(cfg, num_hidden_layers=n_draft)
    d_params = dict(params)
    d_params["blocks"] = jax.tree_util.tree_map(
        lambda x: x[:n_draft], params["blocks"])
    draft = decode.DecodePipeline(family, d_cfg, [(1, 4 * n_draft)],
                                  [d_params], max_len=max_len, dtype=dtype)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size,
                       size=(args.batch, args.prompt_len))
    n = args.new_tokens

    def timed(fn):
        out = fn()                     # warm (compile)
        want = np.asarray(out)         # fence
        best = []
        for _ in range(args.reps):
            tik = time.monotonic()
            got = np.asarray(fn())     # wall time incl. the final fetch
            best.append(time.monotonic() - tik)
            np.testing.assert_array_equal(got, want)
        return want, float(np.median(best))

    plain_out, plain_s = timed(lambda: target.generate(ids, n))
    gammas = {}
    for g_str in args.gammas.split(","):
        g = int(g_str)
        host = SpeculativeDecoder(target, draft, gamma=g, sync="host")
        dev = SpeculativeDecoder(target, draft, gamma=g, sync="device")
        host_out, host_s = timed(lambda: host.generate(ids, n))
        dev_out, dev_s = timed(lambda: dev.generate(ids, n))
        # the round-5 mechanism claim: device and host sync modes are
        # token-identical (same target programs)
        np.testing.assert_array_equal(dev_out, host_out)
        # speculative-vs-plain is bitwise-exact for f32 caches (the
        # tests); at bf16 the K-token verify span's reduction order
        # differs from serial steps, so argmax can flip on near-ties —
        # pervasive on random-init (near-uniform) logits, rare at real
        # logit margins. MEASURED here, not asserted:
        agree = float(np.mean(np.asarray(dev_out) == np.asarray(plain_out)))
        gammas[g] = {
            "plain_token_agreement": round(agree, 4),
            "host": {"tokens_per_sec": round(args.batch * n / host_s, 1),
                     "syncs": host.last_sync_count,
                     "syncs_per_token": round(host.last_sync_count / n, 3)},
            "device": {"tokens_per_sec": round(args.batch * n / dev_s, 1),
                       "syncs": dev.last_sync_count,
                       "syncs_per_token": round(dev.last_sync_count / n, 3)},
            "acceptance": (round(host.last_acceptance_rate, 3)
                           if host.last_acceptance_rate is not None
                           else None),
            "device_vs_host": round(host_s / dev_s, 2),
            "device_vs_plain": round(plain_s / dev_s, 2),
        }

    best_g = max(gammas, key=lambda g: gammas[g]["device"]["tokens_per_sec"])
    print(json.dumps({
        "metric": "speculative_decode_tokens_per_sec",
        "value": gammas[best_g]["device"]["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": None,    # the reference has no decode subsystem
        "plain_tokens_per_sec": round(args.batch * n / plain_s, 1),
        "gammas": {str(g): v for g, v in gammas.items()},
        "model": args.model_name, "draft_blocks": n_draft,
        "target_blocks": cfg.num_hidden_layers,
        "batch": args.batch, "prompt_len": args.prompt_len,
        "new_tokens": n, "dtype": args.dtype,
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
