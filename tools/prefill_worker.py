"""Standalone prefill worker: one rank of the cross-process prefill fleet.

The decode side (tools/serve.py `--disaggregate process`) is DCN rank 0;
each worker is a rank 1..N of the same address list. The worker builds
its OWN `DecodePipeline` (same model/partition as the decode executor),
joins the ship plane over real DCN sockets (PR 6 transport), and serves
prefill LEASES (pipeedge_tpu/kv/fleet.py): recv prompt -> prompt pass ->
ack with the wire-v2 KV ship bundle (CRC-verified on the decode side).

Fault surface (docs/FAULT_TOLERANCE.md, disaggregated serving):
- `DCN_CHAOS` (kill/slow/corrupt/...) arms deterministic faults on this
  worker's SENDS — the ship edge is a first-class chaos target.
- A restarted worker (orchestrator respawn, or chaos `restart@K:MS`)
  comes back with `DCN_EPOCH` incremented and JOINs; the decode-side
  fleet readmits it, and any ship the dead incarnation left in flight
  is fenced (stale epoch at the transport, stale lease attempt above).
- The worker exits when the decode rank dies (its reason to exist) or
  on SIGTERM.

Usage (normally spawned by serve.py, not by hand):

  python tools/prefill_worker.py RANK WORLD --dcn-addrs host:p0,host:p1 \
      -m pipeedge/test-tiny-gpt2 -pt 1,4,5,8 --max-len 48
"""
import argparse
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("rank", type=int)
    p.add_argument("world", type=int)
    p.add_argument("--dcn-addrs", required=True,
                   help="comma-separated host:port per rank (rank 0 is "
                        "the decode side)")
    p.add_argument("-m", "--model-name", default="gpt2")
    p.add_argument("-pt", "--partition", default=None)
    p.add_argument("--max-len", default=1024, type=int)
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--attend-floor", default=64, type=int)
    p.add_argument("--heartbeat-interval", default=1.0, type=float,
                   help="beat the decode rank (0 disables); a missed-"
                        "beat death on either side tears the edge down "
                        "cleanly")
    p.add_argument("--heartbeat-miss", default=5, type=int)
    p.add_argument("--http-port", default=0, type=int,
                   help="observability listener port (GET /metrics, "
                        "/healthz, /debug/spans) — the router's fleet "
                        "collector and trace_report --fleet scrape it; "
                        "0 disables")
    args = p.parse_args()
    if not 0 < args.rank < args.world:
        p.error(f"rank must be in [1, {args.world - 1}] (rank 0 is the "
                "decode side)")

    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    import jax.numpy as jnp

    from pipeedge_tpu.comm import chaos, dcn
    from pipeedge_tpu.kv.fleet import PrefillWorkerLoop
    from pipeedge_tpu.parallel.decode import build_decode_pipeline

    # listener up FIRST: the decode side's dials and heartbeats reach
    # this rank while the (slow) model build below is still running —
    # early leases just queue until the loop starts draining them
    # base_port is the no---dcn-addrs default branch only (dead while
    # the flag is required); every rank must seed the SAME base so a
    # future optional-addrs mode still agrees on peer addresses
    # span ring on from the start: /debug/spans federates this rank's
    # prefill spans into trace_report --fleet timelines
    from pipeedge_tpu import telemetry
    telemetry.configure(rank=args.rank)
    http_server = None
    if args.http_port:
        http_server = _start_http(args.http_port, args.rank)

    addrs = dcn.parse_rank_addrs(args.dcn_addrs, args.world, 29600)
    ctx = dcn.DistDcnContext(args.world, args.rank, addrs)
    ctx.init()
    chaos.maybe_install(ctx)    # DCN_CHAOS faults on the ship edge

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    partition = None
    if args.partition:
        nums = [int(x) for x in args.partition.split(",")]
        partition = list(zip(nums[::2], nums[1::2]))
    pipe = build_decode_pipeline(
        args.model_name, partition, max_len=args.max_len, dtype=dtype,
        attend_floor=args.attend_floor)
    loop = PrefillWorkerLoop(pipe, ctx, decode_rank=0)
    ctx.register_peer_death_handler(
        lambda rank: loop.stop() if rank == 0 else None)
    # a restarted incarnation (DCN_EPOCH > 0) must JOIN to clear the
    # decode side's death fence before any lease can reach it
    if ctx.epoch > 0:
        ctx.announce_join([0])
    if args.heartbeat_interval > 0:
        ctx.start_heartbeat([0], interval=args.heartbeat_interval,
                            miss_threshold=args.heartbeat_miss)
    signal.signal(signal.SIGTERM, lambda *a: loop.stop())
    # machine-parseable readiness line (serve.py supervisor + chaos
    # harness key on it)
    print(f"prefill worker rank {args.rank} ready "
          f"(epoch={ctx.epoch}, pid={os.getpid()})", flush=True)
    try:
        loop.run()
    finally:
        print(f"prefill worker rank {args.rank} exiting "
              f"({loop.leases_served} lease(s) served)", flush=True)
        if http_server is not None:
            http_server.shutdown()
        ctx.shutdown()


def _start_http(port: int, rank: int):
    """Tiny observability listener (daemon thread): the same three
    read-only endpoints every other fleet process serves — /metrics
    (Prometheus text), /healthz, /debug/spans (ring drain with clock-
    offset stamps). No mutation surface: leases arrive over DCN only."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from pipeedge_tpu.telemetry import collector as fleet_obs
    from pipeedge_tpu.telemetry import metrics as prom

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):   # noqa: N802 — stdlib name
            pass

        def _send(self, code, body, ctype="application/json"):
            data = (body if isinstance(body, bytes)
                    else json.dumps(body).encode("utf8")
                    if not isinstance(body, str) else body.encode("utf8"))
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):   # noqa: N802 — stdlib name
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                self._send(200, prom.REGISTRY.render(),
                           ctype="text/plain; version=0.0.4")
            elif path == "/healthz":
                self._send(200, {"ok": True, "role": "prefill_worker",
                                 "rank": rank, "pid": os.getpid()})
            elif path == "/debug/spans":
                drain = "drain=0" not in self.path
                self._send(200, fleet_obs.debug_spans_payload(drain=drain))
            else:
                self._send(404, {"error": f"no route {path}"})

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="prefill-http").start()
    return server


if __name__ == "__main__":
    main()
