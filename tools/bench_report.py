"""Trajectory differ + CI regression gate over benchkit records.

Compares two trajectory records — or two multi-scenario artifacts
(BENCH_r0N.json), matched by scenario — metric by metric with per-metric
noise bands, prints ONE JSON line, and with `--gate` exits nonzero on
any regression: the per-PR proof that a claimed win (or an innocent
refactor) did not quietly cost goodput, attainment, latency, MFU, or
agreement.

Gated metrics (direction-aware):
- `throughput`                      higher is better
- `latency_ms.p50/p95/p99`          lower is better
- `mfu.calibrated`                  higher is better
- `quality.top1_agreement_vs_exact` higher is better
- `serve.goodput_rps.<class>`       higher is better
- `serve.slo_attainment.<class>`    higher is better
- `serve.shed.error`                ZERO tolerance (any error regresses)

Noise bands: each metric's band starts from the per-metric default,
which a baseline record may REPLACE per metric via its own
`noise_bands` map ({metric-path-prefix: band}, longest prefix wins) —
the committed-baseline author's way to TIGHTEN a band below the
default for metrics that record has shown to be stable (ROADMAP item:
calibrated noise bands instead of one-size-fits-all). The effective
band is then the LARGEST of that, (a) the baseline record's own
relative spread when it carries samples (`throughput.spread` — the
honest per-session wobble the record measured about itself), and
(b) any `--noise NAME=FRACTION` override. Overrides match by plain
string prefix on the metric path (longest match wins): `--noise
serve.goodput=0.5` covers every `serve.goodput_rps.<class>`, `--noise
latency_ms=2.0` covers all three percentiles, `--noise
throughput=0.5` covers only `throughput`. An override that matches NO
metric in any compared scenario is reported to stderr — a typo must
not silently leave the default band in force.
A change within the band is noise; beyond it against the metric's
direction is a regression; beyond it in favor is an improvement
(reported, never gated).

Config fingerprints: records compare apples-to-apples only when their
config fingerprints match. A mismatch is a warning by default (CPU smoke
vs chip headline have different configs on purpose) and an error under
`--strict-config`.

Exit codes: 0 clean (or no --gate), 1 regression(s) under --gate,
2 input/usage error (unreadable record, no common scenarios, fingerprint
mismatch under --strict-config).

Examples:
    # two rounds of the multi-scenario artifact
    python tools/bench_report.py BENCH_r06.json --baseline BENCH_r05.json

    # CI bench-smoke gate against the committed baseline, generous
    # throughput band (shared runners), tight attainment band
    python tools/bench_report.py bench_records.json \
        --baseline tools/bench_baseline.json --gate \
        --noise throughput=0.6 --noise serve.goodput_rps=0.6
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu.benchkit import schema  # noqa: E402

# metric path prefix -> (direction, default noise band). Direction +1:
# higher is better; -1: lower is better. First matching prefix wins
# (ordered longest-first at lookup).
METRIC_DEFAULTS: Dict[str, Tuple[int, float]] = {
    "throughput": (+1, 0.10),
    "latency_ms.p50": (-1, 0.25),
    "latency_ms.p95": (-1, 0.35),
    "latency_ms.p99": (-1, 0.50),
    "mfu.calibrated": (+1, 0.10),
    "quality.top1_agreement_vs_exact": (+1, 0.005),
    "serve.goodput_rps": (+1, 0.20),
    # attainment is machine-independent (a fraction of admitted
    # requests, not a rate) — 5% is plenty even on shared runners
    "serve.slo_attainment": (+1, 0.05),
    "serve.shed.error": (-1, 0.0),
    "kv.errors": (-1, 0.0),
    "kv.decode_p99_ms": (-1, 0.50),
    "kv.chunked.burst_decode_p99_ms": (-1, 0.50),
    "kv.chunked.goodput_rps": (+1, 0.20),
    "kv.chunked.attainment": (+1, 0.05),
}


def extract_metrics(record: dict) -> Dict[str, float]:
    """Flatten a trajectory record into {metric_path: value} for every
    gateable metric present and non-null."""
    out: Dict[str, float] = {}

    def put(path: str, val) -> None:
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[path] = float(val)

    thr = record.get("throughput") or {}
    put("throughput", thr.get("value"))
    lat = record.get("latency_ms") or {}
    for q in ("p50", "p95", "p99"):
        put(f"latency_ms.{q}", lat.get(q))
    mfu = record.get("mfu") or {}
    put("mfu.calibrated", mfu.get("calibrated"))
    quality = record.get("quality") or {}
    put("quality.top1_agreement_vs_exact",
        quality.get("top1_agreement_vs_exact"))
    serve = record.get("serve") or {}
    for cls, val in (serve.get("goodput_rps") or {}).items():
        put(f"serve.goodput_rps.{cls}", val)
    for cls, val in (serve.get("slo_attainment") or {}).items():
        put(f"serve.slo_attainment.{cls}", val)
    put("serve.shed.error", (serve.get("shed") or {}).get("error"))
    kv = record.get("kv") or {}
    put("kv.errors", kv.get("errors"))
    for phase, val in (kv.get("decode_p99_ms") or {}).items():
        put(f"kv.decode_p99_ms.{phase}", val)
    chunked = kv.get("chunked") or {}
    put("kv.chunked.burst_decode_p99_ms",
        chunked.get("burst_decode_p99_ms"))
    put("kv.chunked.goodput_rps", chunked.get("goodput_rps"))
    put("kv.chunked.attainment", chunked.get("attainment"))
    return out


def _override_band(overrides: Dict[str, float],
                   path: str) -> Optional[float]:
    """Plain string-prefix match, longest prefix wins (the documented
    --noise semantics: 'serve.goodput' covers serve.goodput_rps.*)."""
    for prefix in sorted(overrides, key=len, reverse=True):
        if path.startswith(prefix):
            return overrides[prefix]
    return None


def metric_direction(path: str) -> int:
    for prefix in sorted(METRIC_DEFAULTS, key=len, reverse=True):
        if path == prefix or path.startswith(prefix + "."):
            return METRIC_DEFAULTS[prefix][0]
    return +1


def noise_band(path: str, baseline: dict,
               overrides: Dict[str, float]) -> float:
    """max(record band, record's own measured spread, override), where
    the record band is the baseline record's per-metric `noise_bands`
    entry (longest-prefix match) when present — it REPLACES the
    per-metric default, so a committed baseline can tighten a band
    below the one-size-fits-all default — else the default."""
    override = _override_band(overrides, path)
    band = 0.10
    for prefix in sorted(METRIC_DEFAULTS, key=len, reverse=True):
        if path == prefix or path.startswith(prefix + "."):
            band = METRIC_DEFAULTS[prefix][1]
            break
    record_bands = baseline.get("noise_bands")
    if isinstance(record_bands, dict):
        record_band = _override_band(
            {k: float(v) for k, v in record_bands.items()}, path)
        if record_band is not None:
            band = record_band
    if path == "throughput":
        thr = baseline.get("throughput") or {}
        spread = thr.get("spread")
        if (isinstance(spread, (list, tuple)) and len(spread) == 2
                and thr.get("value")):
            rel = abs(spread[1] - spread[0]) / max(1e-9, thr["value"])
            band = max(band, rel)
    if override is not None:
        band = max(band, override)
    return band


def compare_records(base: dict, new: dict,
                    overrides: Optional[Dict[str, float]] = None) -> dict:
    """Per-metric verdicts for one scenario pair. Metrics present in the
    baseline but MISSING from the new record are regressions (a metric
    cannot silently vanish past the gate); metrics new in `new` are
    reported as `new` and never gated."""
    overrides = overrides or {}
    base_m = extract_metrics(base)
    new_m = extract_metrics(new)
    metrics: Dict[str, dict] = {}
    regressed: List[str] = []
    for path in sorted(set(base_m) | set(new_m)):
        b, n = base_m.get(path), new_m.get(path)
        if b is None:
            metrics[path] = {"new": n, "verdict": "new"}
            continue
        if n is None:
            metrics[path] = {"base": b, "verdict": "missing"}
            regressed.append(path)
            continue
        band = noise_band(path, base, overrides)
        direction = metric_direction(path)
        if b:
            delta = (n - b) / abs(b)
        else:
            # zero baseline: any move is infinitely large relative to it
            # (e.g. serve.shed.error going 0 -> 3 must regress)
            delta = 0.0 if n == b else float("inf") * (1 if n > b else -1)
        worse = -delta * direction  # positive = worse, as a fraction
        if worse > band:
            verdict = "regressed"
            regressed.append(path)
        elif -worse > band:
            verdict = "improved"
        else:
            verdict = "ok"
        metrics[path] = {
            "base": b, "new": n,
            "delta_pct": (round(delta * 100, 2)
                          if abs(delta) != float("inf") else None),
            "band_pct": round(band * 100, 2),
            "verdict": verdict,
        }
    return {
        "scenario": new.get("scenario", base.get("scenario")),
        "config_match": (base.get("config_fingerprint")
                         == new.get("config_fingerprint")),
        "metrics": metrics,
        "regressed": regressed,
        "ok": not regressed,
    }


def _load_records(path: str) -> Dict[str, dict]:
    with open(path, encoding="utf8") as fh:
        doc = json.load(fh)
    records = schema.records_from_any(doc)
    for scenario, record in records.items():
        problems = schema.validate_record(record)
        if problems:
            raise ValueError(f"{path}: invalid {scenario!r} record: "
                             f"{problems}")
    return records


def _parse_noise(pairs) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for pair in pairs or ():
        name, _, frac = pair.partition("=")
        try:
            out[name] = float(frac)
        except ValueError:
            raise SystemExit(f"--noise expects NAME=FRACTION, got "
                             f"{pair!r}") from None
        if not 0.0 <= out[name] <= 10.0:
            raise SystemExit(f"--noise fraction out of range: {pair!r}")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("new", help="new record / multi-scenario artifact")
    p.add_argument("--baseline", required=True,
                   help="baseline record / artifact to diff against")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 when any common scenario regresses "
                        "(the CI bench-smoke mode)")
    p.add_argument("--noise", action="append", metavar="NAME=FRACTION",
                   help="per-metric-prefix noise-band override, e.g. "
                        "throughput=0.5 (repeatable; max with defaults)")
    p.add_argument("--strict-config", action="store_true",
                   help="fail (exit 2) when a compared pair's config "
                        "fingerprints differ instead of warning")
    p.add_argument("--scenario", action="append",
                   help="restrict the diff to these scenarios "
                        "(repeatable; default: every common one)")
    p.add_argument("--indent", action="store_true",
                   help="pretty-print instead of the one-line record")
    args = p.parse_args(argv)
    overrides = _parse_noise(args.noise)

    try:
        base_all = _load_records(args.baseline)
        new_all = _load_records(args.new)
    except (OSError, ValueError) as exc:
        print(f"bench_report: {exc}", file=sys.stderr)
        return 2
    common = sorted(set(base_all) & set(new_all))
    if args.scenario:
        missing = set(args.scenario) - set(common)
        if missing:
            print(f"bench_report: scenario(s) not present in both "
                  f"inputs: {sorted(missing)}", file=sys.stderr)
            return 2
        common = sorted(args.scenario)
    if not common:
        print(f"bench_report: no common scenarios between "
              f"{args.baseline} ({sorted(base_all)}) and "
              f"{args.new} ({sorted(new_all)})", file=sys.stderr)
        return 2

    scenarios = {}
    regressed: List[str] = []
    seen_paths: set = set()
    for scenario in common:
        diff = compare_records(base_all[scenario], new_all[scenario],
                               overrides)
        scenarios[scenario] = diff
        seen_paths.update(diff["metrics"])
        if not diff["config_match"]:
            msg = (f"bench_report: {scenario}: config fingerprints "
                   "differ (baseline "
                   f"{base_all[scenario].get('config_fingerprint')}, new "
                   f"{new_all[scenario].get('config_fingerprint')})")
            if args.strict_config:
                print(msg, file=sys.stderr)
                return 2
            print(f"{msg} — diffing anyway", file=sys.stderr)
        regressed.extend(f"{scenario}:{m}" for m in diff["regressed"])

    # a --noise override that matched nothing is almost certainly a typo
    # (the band the operator thinks is in force isn't) — say so
    for name in sorted(overrides):
        if not any(path.startswith(name) for path in seen_paths):
            print(f"bench_report: --noise {name}=... matched no metric "
                  f"(known paths: {', '.join(sorted(seen_paths))})",
                  file=sys.stderr)

    report = {
        "baseline": args.baseline,
        "new": args.new,
        "scenarios": scenarios,
        "scenarios_only_in_baseline": sorted(set(base_all) - set(new_all)),
        "scenarios_only_in_new": sorted(set(new_all) - set(base_all)),
        "regressed": regressed,
        "ok": not regressed,
    }
    print(json.dumps(report, indent=2 if args.indent else None,
                     sort_keys=True))
    if regressed:
        print("bench_report: REGRESSED: " + ", ".join(regressed),
              file=sys.stderr)
        return 1 if args.gate else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
