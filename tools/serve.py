"""Minimal HTTP serving front end over the continuous batcher.

Beyond-reference serving surface (the reference runtime is single-shot
batch inference; SURVEY.md §2.4): a stdlib-only JSON/HTTP server that
drives `ContinuousBatcher` continuously — requests admit as they
arrive, share the pipeline via wave scheduling, and prompt prefixes
registered once via /prefix are reused by any number of /generate
requests (prompt caching).

Endpoints (all JSON):
- GET  /healthz            -> {"ok", "model", "stages", "speculative",
                               "stats": {ticks, stage_steps, tokens,
                               active, pending, prefixes}}; HTTP 503
                               once the serving worker has died
- POST /prefix   {"ids": [t0, t1, ...]}
                           -> {"prefix_id": "p0", "len": N}
- POST /generate {"ids": [[...], ...] | [...], "new_tokens": N,
                  "temperature"?: f, "top_k"?: n, "seed"?: n,
                  "eos_token"?: n, "prefix_id"?: "p0"}
                           -> {"ids": [[prompt+continuation], ...]}
                              (suffix+continuation when prefix_id given)

Single worker thread owns the batcher (JAX dispatch is asynchronous, so
one thread keeps every stage busy); HTTP handler threads submit under a
condition variable and wait for their request id to complete. Tokens
are identical to solo `DecodePipeline.generate` runs with the same
settings — the batcher's contract (tests/test_serve.py).

Usage: python tools/serve.py -m gpt2 [--port 8321] [--platform cpu] ...
"""
import argparse
import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _Service:
    """Owns the pipeline + batcher; one worker thread ticks continuously.

    With a `spec` (SpeculativeDecoder), greedy requests that ask for it
    (`"speculative": true`) run draft/verify rounds instead of joining
    the wave — same lock, so they serialize with batcher ticks."""

    def __init__(self, pipe, max_active=None, max_prefixes=8, spec=None):
        from collections import OrderedDict

        from pipeedge_tpu.parallel.batcher import ContinuousBatcher
        self.pipe = pipe
        self.spec = spec
        self.batcher = ContinuousBatcher(pipe, max_active=max_active)
        self.cond = threading.Condition()
        self.prefixes = OrderedDict()   # LRU-bounded: handles hold full
        self.spec_prefixes = OrderedDict()   # max_len KV buffers
        self.max_prefixes = max_prefixes
        self._next_rid = 0
        self._next_pid = 0
        self._stop = False
        self._dead: Optional[BaseException] = None
        self.worker = threading.Thread(target=self._loop, daemon=True)
        self.worker.start()

    def _loop(self):
        while True:
            with self.cond:
                while not self._stop and not (
                        self.batcher.pending or self.batcher.active):
                    self.cond.wait()
                if self._stop:
                    return
                try:
                    self.batcher.tick()
                except BaseException as exc:   # noqa: BLE001 — a wedged
                    # worker would hang every waiter forever; record the
                    # failure so they raise instead
                    self._dead = exc
                    self.cond.notify_all()
                    raise
                if self.batcher.results:
                    self.cond.notify_all()

    def add_prefix(self, ids):
        with self.cond:
            # precompute BOTH handles before registering either, so a
            # draft-side failure cannot leave a half-registered prefix
            # (usable plainly, 400ing speculatively). The target handle
            # is shared — the draft model's K/V is the only extra state.
            target = self.pipe.precompute_prefix(ids)
            draft = (self.spec.draft.precompute_prefix(ids)
                     if self.spec is not None else None)
            pid = f"p{self._next_pid}"
            self._next_pid += 1
            self.prefixes[pid] = target
            if draft is not None:
                self.spec_prefixes[pid] = {"target": target,
                                           "draft": draft}
            while len(self.prefixes) > self.max_prefixes:
                old, _ = self.prefixes.popitem(last=False)  # evict oldest
                self.spec_prefixes.pop(old, None)
            return pid, target["len"]

    def generate_speculative(self, ids, new_tokens, prefix_id=None):
        """Greedy speculative decoding (token-identical to plain greedy;
        the draft only changes the dispatch count). Holds the service
        lock for the whole generation: a speculative request owns the
        pipeline while it runs and plain requests queue behind it —
        speculation trades concurrency for per-request latency here."""
        import numpy as np
        if self.spec is None:
            raise KeyError("server started without --draft-model; "
                           "speculative generation unavailable")
        with self.cond:
            if self._dead is not None:
                raise RuntimeError(f"serving worker died: {self._dead!r}")
            prefix = None
            if prefix_id is not None:
                if prefix_id not in self.spec_prefixes:
                    raise KeyError(
                        f"unknown prefix_id {prefix_id!r} for speculative "
                        "generation (register via /prefix while the "
                        "draft model is configured)")
                self.prefixes.move_to_end(prefix_id)   # LRU touch
                prefix = self.spec_prefixes[prefix_id]
            return np.asarray(self.spec.generate(ids, new_tokens,
                                                 prefix=prefix))

    def generate(self, ids, new_tokens, **kw):
        pid = kw.pop("prefix_id", None)
        with self.cond:
            if self._dead is not None:
                raise RuntimeError(f"serving worker died: {self._dead!r}")
            if pid is not None:
                if pid not in self.prefixes:
                    raise KeyError(f"unknown prefix_id {pid!r} (evicted "
                                   "or never registered)")
                self.prefixes.move_to_end(pid)     # LRU touch
                kw["prefix"] = self.prefixes[pid]
            rid = self._next_rid
            self._next_rid += 1
            self.batcher.submit(rid, ids, new_tokens, **kw)
            self.cond.notify_all()
            while rid not in self.batcher.results:
                if self._dead is not None:
                    raise RuntimeError(
                        f"serving worker died: {self._dead!r}")
                self.cond.wait()
            return self.batcher.results.pop(rid)

    def stop(self):
        with self.cond:
            self._stop = True
            self.cond.notify_all()


def make_handler(service, model_name):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):      # quiet server
            pass

        def _send(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                # LOCK-FREE best-effort snapshot: a probe must answer
                # even while a speculative generation or prefix
                # registration holds the service lock (GIL-atomic int/
                # len reads; momentary inconsistency is fine for health)
                dead = service._dead is not None
                stats = dict(service.batcher.stats,
                             active=service.batcher.active,
                             pending=len(service.batcher.pending),
                             prefixes=len(service.prefixes))
                self._send(503 if dead else 200,
                           {"ok": not dead, "model": model_name,
                            "stages": len(service.pipe.stages),
                            "speculative": service.spec is not None,
                            "stats": stats})
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/prefix":
                    pid, plen = service.add_prefix(req["ids"])
                    self._send(200, {"prefix_id": pid, "len": plen})
                elif self.path == "/generate":
                    ids = req["ids"]
                    if ids and not isinstance(ids[0], list):
                        ids = [ids]
                    if req.get("speculative"):
                        if req.get("temperature") or req.get("top_k") \
                                or req.get("eos_token") is not None:
                            raise ValueError(
                                "speculative generation is greedy-exact; "
                                "it does not compose with sampling/eos")
                        out = service.generate_speculative(
                            ids, int(req["new_tokens"]),
                            prefix_id=req.get("prefix_id"))
                    else:
                        out = service.generate(
                            ids, int(req["new_tokens"]),
                            temperature=float(req.get("temperature", 0.0)),
                            top_k=int(req.get("top_k", 0)),
                            seed=int(req.get("seed", 0)),
                            eos_token=req.get("eos_token"),
                            prefix_id=req.get("prefix_id"))
                    self._send(200, {"ids": out.tolist()})
                else:
                    self._send(404, {"error": "unknown path"})
            except (KeyError, ValueError, TypeError, IndexError) as exc:
                self._send(400, {"error": str(exc)})
            except RuntimeError as exc:
                self._send(503, {"error": str(exc)})

    return Handler


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model-name", default="gpt2")
    p.add_argument("-pt", "--partition", default=None)
    p.add_argument("--max-len", default=1024, type=int)
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--kv-bits", default=0, type=int, choices=[0, 8])
    p.add_argument("--attend-floor", default=64, type=int)
    p.add_argument("--draft-model", default=None,
                   help="enable speculative generation: requests with "
                        '"speculative": true run greedy draft/verify '
                        "rounds against this (smaller, same-vocabulary) "
                        "model — token-identical to plain greedy")
    p.add_argument("--gamma", default=4, type=int,
                   help="speculative draft lookahead per round")
    p.add_argument("--max-active", default=None, type=int)
    p.add_argument("--max-prefixes", default=8, type=int,
                   help="LRU bound on registered prompt prefixes (each "
                        "handle retains full max_len KV buffers)")
    p.add_argument("--port", default=8321, type=int)
    args = p.parse_args()

    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    import jax.numpy as jnp

    from pipeedge_tpu.parallel.decode import build_decode_pipeline

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    partition = None
    if args.partition:
        nums = [int(x) for x in args.partition.split(",")]
        partition = list(zip(nums[::2], nums[1::2]))
    pipe = build_decode_pipeline(
        args.model_name, partition, max_len=args.max_len, dtype=dtype,
        cache_bits=args.kv_bits, attend_floor=args.attend_floor)
    spec = None
    if args.draft_model:
        if args.kv_bits:
            p.error("--draft-model does not compose with --kv-bits (int8 "
                    "span verification is not bit-identical to serial "
                    "int8 steps)")
        from pipeedge_tpu.parallel.speculative import SpeculativeDecoder
        d_pipe = build_decode_pipeline(
            args.draft_model, None, max_len=args.max_len, dtype=dtype,
            attend_floor=args.attend_floor)
        spec = SpeculativeDecoder(pipe, d_pipe, gamma=args.gamma)

    service = _Service(pipe, max_active=args.max_active,
                       max_prefixes=args.max_prefixes, spec=spec)
    server = ThreadingHTTPServer(("127.0.0.1", args.port),
                                 make_handler(service, args.model_name))
    print(f"serving {args.model_name} ({len(pipe.stages)} stages) on "
          f"127.0.0.1:{args.port}", flush=True)
    try:
        server.serve_forever()
    finally:
        service.stop()


if __name__ == "__main__":
    main()
