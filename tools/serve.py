"""HTTP serving front end over the pipelined decode executors.

Beyond-reference serving surface (the reference runtime is single-shot
batch inference; SURVEY.md §2.4): a stdlib-only JSON/HTTP server that
drives a `ContinuousBatcher` (wave executor) or a `StageWorkerExecutor`
(one worker thread pinned per pipeline stage) continuously — requests
admit as they arrive, share the pipeline, and prompt prefixes
registered once via /prefix are reused by any number of /generate
requests (prompt caching).

Overload is handled as a fault, not a steady state (docs/SERVING.md):
every /generate rides the SLO-aware admission plane
(`pipeedge_tpu/serving/`) — per-class token buckets, a bounded
earliest-deadline-first queue, and watermark-driven brownout — so a
surge shed excess load with 503 + a Retry-After computed from the
observed service rate instead of degrading every request. Requests may
carry `"class"` ("interactive" | "batch" | "best_effort", default
interactive) and `"deadline_ms"` (budget from receipt); the deadline
propagates into the executors, which cancel expired work at the next
decode-step boundary (HTTP 504, `pipeedge_deadline_exceeded_total`).

Endpoints (all JSON unless noted):
- GET  /healthz            -> {"ok", "model", "stages", "speculative",
                               "executor", "degraded": false | {"dead_rank",
                               "since_s", "retry_after"},
                               "serving": {deadline_exceeded_total,
                               "admission": {queue_depth, in_flight,
                               shed_classes, service_rate_rps, ...},
                               "brownout": {level, name, floor, ...}},
                               "peer_health": {rank: {state, score,
                               windows}} (the gray-failure scorer's
                               per-peer view when one runs here, {}
                               otherwise — docs/FAULT_TOLERANCE.md),
                               "stats": {tokens, active,
                               pending, prefixes,
                               degraded_entered_total,
                               failover_replays_total,
                               rejoined_ranks_total, last_dead_rank, ...;
                               stage mode adds per-worker
                               stage_steps/busy/queued}};
                               the degraded object carries a "phase"
                               ("degraded" | "healing");
                               HTTP 503 once a serving worker has died
- GET  /metrics            -> Prometheus text format (the observability
                              plane, docs/OBSERVABILITY.md): request count/
                              latency histogram, tokens served, per-edge
                              activation wire-byte counters, degraded/
                              failover counters — plus every monitoring
                              key's (instant|window|global) matrix as
                              gauges when a monitoring session is open,
                              and whatever the runtime's DCN hooks fed
                              into the shared registry (wire bytes,
                              negotiated edge bitwidths, heartbeats)
- POST /degraded {"degraded": bool, "dead_rank"?: n, "retry_after"?: s,
                  "healing"?: bool, "healed"?: bool, "rank"?: n}
                           -> {"degraded": bool} — the failover
                              orchestrator's hook: while degraded, new
                              work is answered 503 + Retry-After and
                              /healthz names the dead rank; an in-flight
                              request whose executor fails during the
                              window is replayed once after recovery.
                              Lifecycle (docs/FAULT_TOLERANCE.md): the
                              orchestrator posts {"degraded": true, ...}
                              at the death, {"degraded": true, "healing":
                              true} once the rank rejoins (window still
                              open, /healthz phase flips to "healing"),
                              and {"degraded": false, "healed": true,
                              "rank": n} when capacity is restored — that
                              last form clears the window AND counts the
                              rank on pipeedge_serve_rejoined_ranks_total
- POST /debug/dump {"rid"?: "q17"}
                           -> {"path": ..., "written_total": n} — write a
                              flight-recorder postmortem bundle NOW
                              (docs/OBSERVABILITY.md): the event ring, a
                              request-scoped span slice, and the
                              admission/brownout state. Bundles are also
                              written automatically on 504s, sheds,
                              degraded windows, and SLO-breach brownout
                              steps; /healthz's "flight" block names the
                              latest bundle path.
- POST /prefix   {"ids": [t0, t1, ...]}
                           -> {"prefix_id": "p0", "len": N}
- POST /generate {"ids": [[...], ...] | [...], "new_tokens": N,
                  "temperature"?: f, "top_k"?: n, "seed"?: n,
                  "eos_token"?: n, "prefix_id"?: "p0",
                  "stream"?: true, "speculative"?: true}
                           -> {"ids": [[prompt+continuation], ...],
                               "rid": "q17"}
                              (suffix+continuation when prefix_id given;
                              "rid" is the minted request id — the trace
                              key for `trace_report --request`, also
                              carried by 503/504 error bodies)

With `"stream": true` the response is chunked `application/x-ndjson`:
one line per decode step `{"step": i, "tokens": [[...]]}` as the token
lands (raw picked tokens — post-eos rows are NOT yet masked), then a
final line `{"ids": ..., "first_token_ms": t, "steps": n}` carrying the
authoritative (eos-masked) result, identical to the non-streaming
response. First-token latency is measured server-side from request
receipt to the first step's readback.

Executors (`--executor`):
- `wave` (default): one worker thread ticks the batcher
  (`ContinuousBatcher`) — strict wave semantics, JAX async dispatch
  keeps every stage busy from a single host thread.
- `stage`: one worker thread PER pipeline stage
  (`StageWorkerExecutor`) — host-side dispatch of different stages
  overlaps, and the last stage's token picks / eos readbacks never
  stall earlier stages' dispatch. healthz reports per-worker stats.

Paged KV plane (`--kv-pages N`, docs/SERVING.md): the executors swap
their dense per-request cache slots for page tables over one shared
pool (pipeedge_tpu/kv/) — admission charges a KV TOKEN budget
(prompt + max-new-tokens pages) instead of max_active slots, prompt
prefixes are shared across requests automatically through a token-hash
trie (/prefix then registers only the token list), and the brownout
ladder gains an evict-cold-pages rung. `--disaggregate local|wire`
additionally splits serving into a prefill fleet (a dedicated pipeline
running only prompt passes) and the decode executor, shipping finished
KV pages over the wire-v2 codec (`--kv-ship-bits 8` for int8 wire
bytes) — token streams stay identical to colocated serving. /healthz
gains a `serving.kv` block (pool/prefix snapshots).

Speculative requests (`"speculative": true`, needs --draft-model) run
greedy draft/verify rounds under a DEDICATED lock: they serialize with
each other (bounding draft+verify cache memory at one in-flight
speculative generation) but NOT with plain requests or result waits —
JAX dispatch is thread-safe, so the batcher keeps serving while a
speculative generation runs (round-4 advice).

Tokens are identical to solo `DecodePipeline.generate` runs with the
same settings — the executors' shared contract (tests/test_serve.py).

Usage: python tools/serve.py -m gpt2 [--port 8321] [--executor stage] ...
"""
import argparse
import json
import os
import queue as queue_mod
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipeedge_tpu import health as peer_health  # noqa: E402
from pipeedge_tpu import telemetry  # noqa: E402
from pipeedge_tpu.serving import (AdmissionController,  # noqa: E402
                                  AdmissionShed, BrownoutLadder,
                                  DeadlineExceeded, REQUEST_CLASSES,
                                  Watermarks, default_policies,
                                  parse_class_map)
from pipeedge_tpu.telemetry import collector as fleet_obs  # noqa: E402
from pipeedge_tpu.telemetry import flight  # noqa: E402
from pipeedge_tpu.telemetry import metrics as prom  # noqa: E402
from pipeedge_tpu.utils.threads import make_condition, make_lock  # noqa: E402

# request outcomes the per-class counter tracks (the request-class x
# outcome matrix — pre-declared at service construction, pipelint PL501)
REQUEST_OUTCOMES = ("ok", "shed", "deadline", "degraded", "error")

# hop-propagation header (serving/router.py mints the fleet-level rid
# and carries it here; a replica mints its own `q<n>` only when the
# header is absent — direct, unrouted requests)
RID_HEADER = "X-PipeEdge-Rid"


def _header_rid(headers) -> Optional[str]:
    """A sane caller-supplied rid from the request headers, else None
    (it lands in span rings, logs, and postmortem filenames — bound
    and sanitize it)."""
    raw = headers.get(RID_HEADER)
    if not raw:
        return None
    rid = raw.strip()
    if not rid or len(rid) > 128 or not rid.isprintable():
        return None
    return rid


def _rid_headers(rid) -> tuple:
    """Response-header echo of the request id (ops cross-reference a
    client complaint to a bundle without body parsing)."""
    return ((RID_HEADER, rid),) if rid else ()


class ServiceDegraded(RuntimeError):
    """The service is in a failover window (a backing stage died): new
    work should come back later instead of queueing into the hole."""

    def __init__(self, dead_rank, retry_after: float):
        where = f" (rank {dead_rank} dead)" if dead_rank is not None else ""
        super().__init__(
            f"service degraded during failover{where}; retry after "
            f"{retry_after:g}s")
        self.dead_rank = dead_rank
        self.retry_after = retry_after


class _Service:
    """Owns the pipeline + executor; HTTP handler threads submit requests
    and wait for (or stream) their results."""

    def __init__(self, pipe, max_active=None, max_prefixes=8, spec=None,
                 executor="wave", edge_itemsize=2,
                 admission_enabled=True, queue_capacity=64,
                 class_rates=None, class_deadlines_s=None,
                 brownout_enabled=True, brownout_marks=None,
                 clamp_new_tokens=16, governor_interval=0.25,
                 postmortem_dir=None, kv_pages=0, kv_page_size=16,
                 prefill_fleet=None, prefill_supervisor=None,
                 chunked_prefill=0, step_join=False,
                 prefill_budget=None, clamp_chunk_tokens=0,
                 slo_objective=0.99, slo_burn_fast=30.0,
                 slo_burn_slow=300.0, slo_burn_threshold=10.0):
        from collections import OrderedDict, deque

        from pipeedge_tpu.parallel.batcher import (ContinuousBatcher,
                                                   StageWorkerExecutor)
        self.pipe = pipe
        self.spec = spec
        self.executor = executor
        # -- paged KV plane (docs/SERVING.md, pipeedge_tpu/kv) ----------
        # kv_pages > 0 swaps the executors' dense per-request cache
        # slots for page tables over one shared pool (+ the prefix
        # trie); admission then runs on a KV TOKEN budget. The optional
        # prefill fleet (--disaggregate) runs prompt passes on its OWN
        # pipeline and ships KV pages in, so decode waves never share
        # stage-time with prefills.
        self.kv_backend = None
        if kv_pages:
            from pipeedge_tpu.kv import PagedKvBackend
            self.kv_backend = PagedKvBackend(pipe, kv_pages,
                                             kv_page_size)
            if spec is not None:
                # page the speculative draft/verify caches onto the
                # plane: target rounds reserve pages from the SAME pool
                # decode requests use (one capacity accountant), the
                # draft model gets its own small pool over its own
                # pipeline geometry (pipeedge_tpu/parallel/speculative)
                from pipeedge_tpu.kv.pool import KvPagePool
                spec.attach_paged(self.kv_backend,
                                  KvPagePool(spec.draft, kv_pages,
                                             kv_page_size))
        self.prefill_fleet = prefill_fleet
        self.prefill_supervisor = prefill_supervisor
        self._prefill_unavailable = None
        self.m_prefill_colocated = None
        if prefill_fleet is not None and self.kv_backend is None:
            raise ValueError("--disaggregate needs --kv-pages (shipped "
                             "KV lands in the paged pool)")
        if prefill_fleet is not None:
            from pipeedge_tpu.kv.fleet import PrefillUnavailable
            self._prefill_unavailable = PrefillUnavailable
            # colocated-fallback accounting (PL501: the reason matrix is
            # known here). "unavailable" = every prefill rank/retry
            # exhausted (docs/FAULT_TOLERANCE.md disaggregated serving);
            # "brownout" = the colocate_prefill rung turned shipping off
            self.m_prefill_colocated = prom.REGISTRY.counter(
                "pipeedge_kv_prefill_colocated_total",
                "prompt passes run colocated in the decode executor "
                "while disaggregation was configured, by reason")
            for reason in ("unavailable", "brownout"):
                self.m_prefill_colocated.declare(reason=reason)
        self.cond = make_condition("serve.results")
        # -- /metrics + healthz counters (one source of truth) ----------
        # the registry instruments below ARE the state: healthz's stats
        # read them back (stats()), so both surfaces always agree — even
        # across a _Service rebuild in the same process (get_or_create
        # returns the surviving instruments)
        self._edge_itemsize = int(edge_itemsize)
        self.m_requests = prom.REGISTRY.counter(
            "pipeedge_serve_requests_total",
            "generate requests by endpoint and outcome status")
        # full endpoint x outcome matrix from the first scrape (PL501)
        for endpoint in ("/generate", "/generate-speculative"):
            for status in ("200", "503", "504", "error"):
                self.m_requests.declare(endpoint=endpoint, status=status)
        self.m_tokens = prom.REGISTRY.counter(
            "pipeedge_serve_tokens_total", "tokens generated (rows x steps)")
        self.m_latency = prom.REGISTRY.histogram(
            "pipeedge_serve_request_latency_seconds",
            "end-to-end generate latency (request receipt -> result)")
        # request-class x outcome matrix (the request-tracing plane's
        # per-class view; full matrix renders from the first scrape)
        self.m_class_outcome = prom.REGISTRY.counter(
            "pipeedge_requests_by_class_total",
            "generate requests by request class and outcome "
            "(ok / shed / deadline / degraded / error)")
        for cls in REQUEST_CLASSES:
            for outcome in REQUEST_OUTCOMES:
                self.m_class_outcome.declare(**{"class": cls,
                                                "outcome": outcome})
        # flight recorder (docs/OBSERVABILITY.md): always-on event ring +
        # postmortem bundles on 504 / shed / failover / SLO breach
        self.flight = flight.configure(rank=0, out_dir=postmortem_dir)
        # local SLO burn-rate engine (ticked by the governor loop): the
        # per-class outcome counter above feeds the pre-declared
        # pipeedge_slo_burn_rate{class,window} matrix; a fast-window
        # breach writes ONE slo_burn postmortem per overload episode
        self.burn = fleet_obs.BurnRateEngine(
            objective=slo_objective, fast_window_s=slo_burn_fast,
            slow_window_s=slo_burn_slow, threshold=slo_burn_threshold,
            on_breach=self._on_slo_burn)
        self.m_degraded = prom.REGISTRY.counter(
            "pipeedge_serve_degraded_entered_total",
            "failover windows opened via POST /degraded")
        self.m_replays = prom.REGISTRY.counter(
            "pipeedge_serve_failover_replays_total",
            "in-flight requests replayed after a degraded window closed")
        self.m_rejoined = prom.REGISTRY.counter(
            "pipeedge_serve_rejoined_ranks_total",
            "degraded windows closed as HEALED (capacity restored by a "
            "rank rejoining), by rank")
        self.m_last_dead = prom.REGISTRY.gauge(
            "pipeedge_serve_last_dead_rank",
            "rank named by the most recent degraded window (-1 = none)")
        self.m_last_dead.set(-1)
        # distinct name from runtime.py's pipeedge_edge_wire_bytes_total
        # (measured DCN socket bytes, direction/peer labels): these are
        # estimated device-edge activation bytes — merging the two under
        # one family would let sum() silently add different quantities
        self.m_edge_bytes = prom.REGISTRY.counter(
            "pipeedge_serve_edge_wire_bytes_total",
            "per-edge activation bytes moved by completed requests "
            "(prefill + decode steps, estimated from shapes)")
        # the full per-edge matrix renders from the first scrape, not the
        # first request
        for i in range(len(pipe.stages) - 1):
            self.m_edge_bytes.declare(edge=f"{i}->{i + 1}")
        # speculative generations hold THIS lock, not self.cond: plain
        # requests and result waits proceed concurrently (the pipeline's
        # jitted programs are thread-safe; serializing speculative
        # requests with each other bounds their cache memory)
        self.spec_lock = make_lock("serve.speculative")
        self.prefixes = OrderedDict()   # LRU-bounded: handles hold full
        self.spec_prefixes = OrderedDict()   # max_len KV buffers
        self.max_prefixes = max_prefixes
        self._next_rid = 0
        self._next_pid = 0
        self._stop = False
        self._dead: Optional[BaseException] = None
        # failover window (enter_degraded/exit_degraded): while set, new
        # work is refused with 503 + Retry-After and healthz reports the
        # dead rank; unlike `_dead` it is expected to clear
        self.degraded_info: Optional[dict] = None
        # graceful drain (POST /drain, routed fleets): new admits are
        # refused 503 + Retry-After while in-flight requests complete;
        # the router migrates warm KV and detaches when active hits 0
        self.draining = False
        # replay gate: set on every window close so in-flight requests
        # waiting out a failover wake IMMEDIATELY on recovery instead of
        # polling (the _await_recovery contract)
        self._recovered = threading.Event()
        # observed heal durations (window open -> healed close): the
        # basis of the DERIVED Retry-After when the orchestrator's
        # /degraded post doesn't carry one
        self._heal_s = deque(maxlen=8)
        # -- iteration-level scheduling knobs (docs/SERVING.md) ---------
        # chunked_prefill > 0 splits long prompt passes into fixed-token
        # chunks interleaved with decode steps; step_join wakes the
        # admission queue at every decode-step boundary so joiners ride
        # the next tick instead of the next completion. `_on_step` is a
        # bound closure because the admission controller is constructed
        # AFTER the executors (it needs their concurrency bound).
        self.chunked_prefill = int(chunked_prefill)
        self.step_join = bool(step_join)
        if executor == "stage":
            self.exec = StageWorkerExecutor(pipe, max_active=max_active,
                                            kv=self.kv_backend,
                                            chunk_tokens=self.chunked_prefill,
                                            step_join=self.step_join,
                                            on_step=self._on_step)
            self.batcher = None
            self.worker = None
        elif executor == "wave":
            self.exec = None
            self.batcher = ContinuousBatcher(pipe, max_active=max_active,
                                             kv=self.kv_backend,
                                             chunk_tokens=self.chunked_prefill,
                                             prefill_budget=prefill_budget,
                                             step_join=self.step_join,
                                             on_step=self._on_step)
            self.worker = threading.Thread(target=self._loop, daemon=True)
            self.worker.start()
        else:
            raise ValueError(f"unknown executor {executor!r} "
                             "(expected 'wave' or 'stage')")
        # -- overload-protection plane (docs/SERVING.md) ----------------
        # admission concurrency mirrors the executor's own bound, so the
        # EDF queue is the ONLY place requests wait and the executor
        # admits a granted request immediately
        concurrency = (self.exec.max_active if self.exec is not None
                       else self.batcher.max_active)
        self.m_deadline = prom.REGISTRY.counter(
            "pipeedge_deadline_exceeded_total",
            "requests whose deadline expired mid-flight (cancelled at a "
            "decode-step boundary and answered 504)")
        self.admission: Optional[AdmissionController] = None
        if admission_enabled:
            # paged mode: `max_active` becomes a TOKEN budget — each
            # admit charges the request's prompt+max-new-tokens page
            # reservation, so many small requests share the capacity a
            # few dense slots used to pin (docs/SERVING.md)
            self.admission = AdmissionController(
                concurrency=concurrency, queue_capacity=queue_capacity,
                policies=default_policies(class_rates, class_deadlines_s),
                token_budget=(None if self.kv_backend is None
                              else self.kv_backend.pool.tokens_capacity))
        self.brownout: Optional[BrownoutLadder] = None
        self._governor = None
        self._gov_stop = threading.Event()
        self.governor_interval = float(governor_interval)
        if brownout_enabled:
            self.brownout = BrownoutLadder(
                brownout_marks if brownout_marks is not None
                else Watermarks(), clamp_new_tokens=clamp_new_tokens,
                clamp_chunk_tokens=clamp_chunk_tokens)
            if self.kv_backend is not None:
                # the evict_cold_pages rung's lever: reclaim cached-but-
                # idle prefix pages before any request class is shed
                self.brownout.evict_hook = self.kv_backend.evict_cold_all
        # the governor also owns the paged-KV orphan sweep (leak audit,
        # docs/FAULT_TOLERANCE.md) and the SLO burn-rate tick; the burn
        # engine always exists, so the thread always runs
        self._governor = threading.Thread(target=self._governor_loop,
                                          daemon=True,
                                          name="brownout-governor")
        self._governor.start()

    def _on_step(self):
        """Executor decode-step hook (--step-join): re-drive the EDF
        admission queue at every step boundary, so a joiner whose slot
        or token charge just freed is granted mid-request instead of
        waiting out the whole completion. Cheap no-op when the queue is
        empty; tolerant of construction order (the executors exist
        before the admission controller does)."""
        adm = getattr(self, "admission", None)
        if adm is not None:
            adm.notify_step()

    def _loop(self):
        while True:
            with self.cond:
                while not self._stop and not (
                        self.batcher.pending or self.batcher.active):
                    self.cond.wait()
                if self._stop:
                    return
                try:
                    self.batcher.tick()
                except BaseException as exc:   # noqa: BLE001 — a wedged
                    # worker would hang every waiter forever; record the
                    # failure so they raise instead
                    self._dead = exc
                    self.cond.notify_all()
                    raise
                if self.batcher.results:
                    self.cond.notify_all()

    @property
    def dead(self) -> Optional[BaseException]:
        if self._dead is not None:
            return self._dead
        return self.exec._dead if self.exec is not None else None

    def add_prefix(self, ids):
        with self.cond:
            self._check_admittable()
            if self.kv_backend is not None:
                # paged mode: registration is just the TOKEN LIST — the
                # prefix trie dedups the actual prefill across every
                # request that uses it (first use pays one prompt pass;
                # later uses share its pages), so no max_len KV buffers
                # are pinned per registration
                tokens = [int(t) for t in ids]
                if not tokens:
                    raise ValueError("prefix must be non-empty")
                pid = f"p{self._next_pid}"
                self._next_pid += 1
                self.prefixes[pid] = {"tokens": tokens,
                                      "len": len(tokens)}
                while len(self.prefixes) > self.max_prefixes:
                    self.prefixes.popitem(last=False)
                return pid, len(tokens)
            # precompute BOTH handles before registering either, so a
            # draft-side failure cannot leave a half-registered prefix
            # (usable plainly, 400ing speculatively). The target handle
            # is shared — the draft model's K/V is the only extra state.
            target = self.pipe.precompute_prefix(ids)
            draft = (self.spec.draft.precompute_prefix(ids)
                     if self.spec is not None else None)
            pid = f"p{self._next_pid}"
            self._next_pid += 1
            self.prefixes[pid] = target
            if draft is not None:
                self.spec_prefixes[pid] = {"target": target,
                                           "draft": draft}
            while len(self.prefixes) > self.max_prefixes:
                old, _ = self.prefixes.popitem(last=False)  # evict oldest
                self.spec_prefixes.pop(old, None)
            return pid, target["len"]

    def _check_dead(self):
        dead = self.dead
        if dead is not None:
            raise RuntimeError(f"serving worker died: {dead!r}")

    # -- replica-to-replica KV migration (docs/FAULT_TOLERANCE.md) ------

    def kv_export(self, ids):
        """POST /kv/export: this replica's warm KV pages for a prompt
        prefix, as a base64 wire-v2 ship blob (kv/ship.py) — the router
        ships them to a survivor during a graceful drain. Returns
        (blob_b64 | None, tokens_covered, pages)."""
        if self.kv_backend is None:
            raise ValueError("KV export needs --kv-pages (dense cache "
                             "slots have no page plane to export)")
        from pipeedge_tpu.kv import ship
        from pipeedge_tpu.serving.router import encode_ship_blob
        out = self.kv_backend.export_prefix([int(t) for t in ids])
        if out is None:
            return None, 0, 0
        frames, plen, pages = out
        return encode_ship_blob(frames), plen, pages

    def kv_import(self, ids, blob_b64):
        """POST /kv/import: install a shipped prefix into this
        replica's page pool + trie (idempotent — an already-cached
        prefix installs 0 pages). Returns pages installed."""
        if self.kv_backend is None:
            raise ValueError("KV import needs --kv-pages")
        from pipeedge_tpu.kv import ship
        from pipeedge_tpu.serving.router import decode_ship_blob
        tensors = decode_ship_blob(blob_b64)
        handle = ship.decode_kv_ship(tensors, self.pipe.dtype)
        return self.kv_backend.install_prefix([int(t) for t in ids],
                                              handle)

    # -- brownout governor ----------------------------------------------

    def _live_request_ids(self):
        """Snapshot of every live executor request id — the orphan
        sweep's liveness set. None = the snapshot raced a mutation
        (skip this sweep; the next tick retries)."""
        src = (self.exec._live if self.exec is not None
               else self.batcher._live_rids)
        for _ in range(3):
            try:
                live = set(src)
                break
            except RuntimeError:     # set mutated during copy
                continue
        else:
            return None
        if self.spec is not None:
            # paged speculative rounds reserve pages from the decode
            # plane's pool under their own owner ids — union them in so
            # a mid-generate speculative request survives the sweep
            live |= self.spec.live_rids()
        return live

    def _on_slo_burn(self, cls, burn):
        """BurnRateEngine breach hook (edge-triggered, governor thread):
        capture the serving state that burned the budget."""
        self.flight.note("slo_burn_breach", request_class=cls,
                         burn=round(burn, 3))
        ctx = self.bundle_context()
        ctx["slo_burn"] = {"class": cls, "burn_rate": round(burn, 4),
                           "objective": self.burn.objective,
                           "threshold": self.burn.threshold}
        self.flight.maybe_dump("slo_burn", context=ctx)

    def _governor_loop(self):
        """Periodic brownout tick: windowed p95 of the request-latency
        histogram (delta between scrapes of the SAME instrument /metrics
        renders) + admission queue depth drive the ladder; the degraded
        lifecycle floors it (healing implies at least level 1). The
        ladder's shed classes feed straight into admission. With a paged
        KV backend the loop doubles as the leak audit: every ~2s the
        pool's owner ledger is reconciled against executor liveness, so
        a submitter/shipper that died mid-request strands zero pages
        (pipeedge_kv_pages_leaked_total counts the reclaims)."""
        prev_counts, prev_n = self.m_latency.snapshot()
        last_level = self.brownout.level if self.brownout is not None else 0
        sweep_every = max(1, round(2.0 / self.governor_interval))
        ticks = 0
        while not self._gov_stop.wait(self.governor_interval):
            ticks += 1
            counts, n = self.m_latency.snapshot()
            delta = [c - p for c, p in zip(counts, prev_counts)]
            p95 = prom.percentile_from_counts(
                self.m_latency.buckets, delta, n - prev_n, 95.0)
            prev_counts, prev_n = counts, n
            depth = (self.admission.queue_depth
                     if self.admission is not None else 0)
            self.burn.update(fleet_obs.BurnRateEngine.counts_from_counter(
                self.m_class_outcome))
            if self.brownout is not None:
                self.brownout.set_floor(
                    1 if self.degraded_info is not None else 0)
                level = self.brownout.update(depth, p95)
                if self.admission is not None:
                    self.admission.set_shed_classes(
                        self.brownout.shed_classes())
                if level != last_level:
                    t = time.monotonic_ns()
                    telemetry.record("serve", f"brownout:{level}", t, t)
                    self.flight.note("brownout", level=level,
                                     queue_depth=depth, p95_s=p95)
                    if level >= 2 and level > last_level:
                        # stepping INTO the clamp/shed rungs is the
                        # SLO-breach trigger: capture the state that
                        # drove the ladder up
                        self.flight.maybe_dump(
                            "slo", context=self.bundle_context())
                    last_level = level
                if self.chunked_prefill:
                    # the clamp_tokens rung's second lever: shrink the
                    # prefill chunk size while hot so decode steps get
                    # more step boundaries per second (identity when the
                    # lever is unarmed — clamp_chunk_tokens == 0)
                    want = self.brownout.clamp_chunk(self.chunked_prefill)
                    ex = self.exec if self.exec is not None \
                        else self.batcher
                    if ex.chunk_tokens != want:
                        ex.set_chunk_tokens(want)
                        self.flight.note("chunk_clamp", chunk_tokens=want)
            if self.kv_backend is not None and ticks % sweep_every == 0:
                # liveness passed as a CALLABLE: the sweep snapshots
                # the owner ledger FIRST, liveness second — a request
                # admitted between the two reads is provably live, so
                # its in-use pages can never be taken for orphans
                leaked = self.kv_backend.sweep_orphans(
                    self._live_request_ids)
                if leaked:
                    self.flight.note("kv_pages_reclaimed", pages=leaked)
                if self.spec is not None:
                    d_leaked = self.spec.sweep_orphans()
                    if d_leaked:
                        self.flight.note("draft_pages_reclaimed",
                                         pages=d_leaked)

    # -- failover window ------------------------------------------------

    def _derived_retry_after(self) -> float:
        """Retry-After for a window the orchestrator opened WITHOUT a
        hint: the median observed heal time (how long capacity actually
        took to come back in this process's history), 5 s until a heal
        has been seen."""
        if self._heal_s:
            med = sorted(self._heal_s)[len(self._heal_s) // 2]
            return min(60.0, max(0.5, med))
        return 5.0

    def enter_degraded(self, dead_rank=None,
                       retry_after: Optional[float] = None):
        """Open a failover window: admission refuses new work with
        503 + Retry-After until `exit_degraded` (the orchestrator's signal
        that the backing pipeline recovered). `retry_after=None` derives
        the hint from observed heal telemetry (`_derived_retry_after`)."""
        if retry_after is None:
            retry_after = self._derived_retry_after()
        self._recovered.clear()
        with self.cond:
            self.degraded_info = {"dead_rank": dead_rank,
                                  "since": time.monotonic(),
                                  "retry_after": float(retry_after),
                                  "phase": "degraded"}
            self.cond.notify_all()
        self.m_degraded.inc()
        if dead_rank is not None:
            self.m_last_dead.set(int(dead_rank))
        # failover IS a flight-recorder trigger: the bundle carries the
        # brownout/admission state at the moment the window opened
        self.flight.note("degraded", dead_rank=dead_rank,
                         retry_after=retry_after)
        self.flight.maybe_dump("failover", context=self.bundle_context())

    def mark_healing(self):
        """The dead rank rejoined and the orchestrator is restoring the
        partition: the window stays open (new work still bounces with
        Retry-After — the heal lands at a round boundary, not instantly),
        but /healthz distinguishes `healing` from plain `degraded`. A
        no-op when no window is open (a stray healing signal must not
        resurrect a closed window)."""
        with self.cond:
            if self.degraded_info is not None:
                self.degraded_info["phase"] = "healing"
                self.cond.notify_all()

    def exit_degraded(self, healed: bool = False, rank=None):
        """Close the window. `healed=True` records the close as a
        capacity restoration (the orchestrator's {"degraded": false,
        "healed": true} form) on pipeedge_serve_rejoined_ranks_total —
        distinct from a plain manual clear — and feeds the window's
        duration into the heal-telemetry history future windows derive
        their Retry-After from."""
        with self.cond:
            was_open = self.degraded_info is not None
            if healed and was_open:
                self._heal_s.append(
                    time.monotonic() - self.degraded_info["since"])
            self.degraded_info = None
            self.cond.notify_all()
        self._recovered.set()     # wake replay waiters immediately
        self.flight.note("degraded_closed", healed=healed, rank=rank)
        if healed and was_open:
            # unlabeled on purpose: healthz stats() reads the same series
            # back (value() is per-label-set); the healed rank stays
            # visible as last_dead_rank history
            self.m_rejoined.inc()

    def _check_admittable(self):
        deg = self.degraded_info
        if deg is not None:
            raise ServiceDegraded(deg["dead_rank"], deg["retry_after"])
        if self.draining:
            # drains don't heal: the Retry-After tells the client to go
            # find another replica (the router already stopped routing
            # here; this is the race window's backstop)
            raise RuntimeError("draining: this replica admits no new "
                               "requests")

    def begin_drain(self):
        """POST /drain: stop admitting, let in-flight work finish. The
        ROUTER owns the rest of the lifecycle (migrate warm prefixes,
        detach, respawn) — this side only has to refuse new admits and
        report `active` honestly in /healthz."""
        self.draining = True
        self.flight.note("drain_begin")

    def _await_recovery(self) -> bool:
        """Block until the degraded window closes (True) or its retry
        budget runs out / the worker is truly dead (False). The replay
        gate for a request that was in flight when the failover began.

        Waits on the `_recovered` event `exit_degraded` signals, so a
        heal admits the replay IMMEDIATELY — the 2x retry_after budget is
        only the give-up bound, not a polling interval. The short wait
        slices exist solely to notice a TRUE executor death mid-window
        (nothing signals an event for that) without holding the handler
        thread for the whole budget."""
        with self.cond:
            deg = self.degraded_info
            if deg is None:
                return False   # the failure was not a failover window
        deadline = time.monotonic() + 2 * deg["retry_after"]
        while True:
            left = deadline - time.monotonic()
            if left <= 0 or self.dead is not None:
                return False
            if self._recovered.wait(timeout=min(0.5, left)):
                return (self.dead is None
                        and self.degraded_info is None)

    # -- admission plumbing (docs/SERVING.md) ---------------------------

    def speculative_allowed(self) -> bool:
        """Brownout rung 1 (`no_speculative`) is the ladder's first,
        cheapest degradation: speculative requests fall back to plain
        greedy (token-identical) instead of occupying the serialized
        draft/verify path."""
        return self.brownout is None or self.brownout.allow_speculative()

    def mint_rid(self) -> str:
        """Mint one request id — THE request identity every span, flight
        event, response body, and postmortem bundle correlates on
        (docs/OBSERVABILITY.md request tracing). The trace CONTEXT is
        built where the class/deadline are known (generate paths)."""
        with self.cond:
            n = self._next_rid
            self._next_rid += 1
        return f"q{n}"

    def kv_tokens(self, ids, new_tokens) -> int:
        """The admission token charge of one request under the paged KV
        plane: its prompt + max-new-tokens page reservation (0 when
        dense caches / no admission — slot-only admission)."""
        if self.kv_backend is None or self.admission is None or not ids:
            return 0
        return self.kv_backend.tokens_needed(
            max(len(r) for r in ids), int(new_tokens), len(ids))

    def admit(self, request_class: str, deadline_s=None, rid=None,
              tokens: int = 0):
        """Acquire an admission ticket (blocking, EDF order) + its
        absolute deadline. Returns (ticket, deadline); raises
        `AdmissionShed` (503 + dynamic Retry-After) on shed, KeyError on
        an unknown class (the handler's 400). The caller must hand the
        ticket to `generate(..., ticket=...)`, which releases it. `rid`
        request-tags the queue-wait span, the ticket, and the flight
        events, so a trace/bundle names WHO waited and who was shed.
        `tokens` is the KV-token charge under a token budget
        (`kv_tokens`)."""
        if self.admission is None:
            deadline = (None if deadline_s is None
                        else time.monotonic() + float(deadline_s))
            return None, deadline
        deadline = self.admission.deadline_for(request_class, deadline_s)
        # spans recorded by hand, not a context manager: an `admit:`
        # sample must mean "queue wait of an ADMITTED request" (the
        # report's admit_wait_ms) — a shed waiter's wasted wait records
        # under its `shed:` span instead of skewing that stat
        t0 = time.monotonic_ns()
        try:
            ticket = self.admission.admit(request_class, deadline,
                                          rid=rid, tokens=tokens)
        except AdmissionShed as exc:
            telemetry.record(
                "serve", f"shed:{exc.request_class}:{exc.reason}",
                t0, time.monotonic_ns(), rid=rid)
            self.flight.note("shed", rid=rid, cls=exc.request_class,
                             reason=exc.reason,
                             retry_after=exc.retry_after)
            # gate BEFORE assembling the context: a shed storm must not
            # pay a full serving snapshot per cooldown-suppressed dump
            if self.flight.would_dump("shed"):
                self.flight.maybe_dump("shed", rid=rid,
                                       context=self.bundle_context())
            raise
        telemetry.record("serve", f"admit:{request_class}",
                         t0, time.monotonic_ns(), rid=rid)
        self.flight.note("admit", rid=rid, cls=request_class,
                         wait_ms=round((time.monotonic_ns() - t0) / 1e6, 3))
        return ticket, deadline

    def bundle_context(self) -> dict:
        """The serving-state slice every postmortem bundle carries:
        admission + brownout snapshots, the degraded window, and the
        executor stats — what was true of the service when the trigger
        fired."""
        ctx = {"serving": self.serving_stats(), "stats": self.stats()}
        deg = self.degraded_info
        if deg is not None:
            ctx["degraded"] = {"dead_rank": deg["dead_rank"],
                               "phase": deg.get("phase"),
                               "since_s": round(time.monotonic()
                                                - deg["since"], 3)}
        ctx["latency_exemplars"] = self.m_latency.exemplars()
        return ctx

    def dump_postmortem(self, rid=None, trigger="manual"):
        """POST /debug/dump's implementation: write a bundle NOW (manual
        dumps bypass the cooldown). Returns the bundle path."""
        return self.flight.maybe_dump(trigger, rid=rid,
                                      context=self.bundle_context())

    def flight_stats(self) -> dict:
        """The /healthz `flight` block — shared with /metrics through the
        same counter family (pipeedge_postmortems_written_total)."""
        return {"postmortems_written_total": self.flight.written_total(),
                "last_postmortem": self.flight.last_path(),
                "events_dropped": self.flight.dropped}

    def retry_after_hint(self) -> float:
        """Best current 'come back in N seconds' estimate — the value
        every 503 path attaches: the open degraded window's hint, else
        the admission plane's queue-drain estimate."""
        deg = self.degraded_info
        if deg is not None:
            return deg["retry_after"]
        if self.admission is not None:
            return self.admission.retry_after()
        return 5.0

    def serving_stats(self) -> dict:
        """The /healthz `serving` block (admission + brownout state)."""
        s = {"deadline_exceeded_total": int(self.m_deadline.value())}
        if self.admission is not None:
            s["admission"] = self.admission.snapshot()
        if self.brownout is not None:
            s["brownout"] = self.brownout.snapshot()
        if self.chunked_prefill or self.step_join:
            # iteration-level scheduling state: the configured chunk
            # size, the EFFECTIVE one (brownout may have clamped it),
            # and how many chunk waves have run — the serve_kv bench's
            # chunked-arm evidence (docs/SERVING.md)
            ex = self.exec if self.exec is not None else self.batcher
            s["scheduler"] = {
                "chunked_prefill": self.chunked_prefill,
                "chunk_tokens": ex.chunk_tokens,
                "step_join": self.step_join,
                "prefill_chunks": int(
                    self.exec.snapshot()["prefill_chunks"]
                    if self.exec is not None
                    else self.batcher.stats["prefill_chunks"]),
            }
        if self.kv_backend is not None:
            s["kv"] = self.kv_backend.snapshot()
            s["kv"]["disaggregated"] = self.prefill_fleet is not None
            # the leak audit's health surface: running total of page
            # references the orphan sweep reclaimed (0 = no leaks)
            s["kv"]["leaked"] = s["kv"]["pool"]["leaked"]
            fleet_snapshot = getattr(self.prefill_fleet, "snapshot", None)
            if fleet_snapshot is not None:
                s["kv"]["prefill"] = fleet_snapshot()
                if self.m_prefill_colocated is not None:
                    s["kv"]["prefill"]["colocated"] = {
                        r: int(self.m_prefill_colocated.value(reason=r))
                        for r in ("unavailable", "brownout")}
            if self.prefill_supervisor is not None:
                s["kv"].setdefault("prefill", {})["workers"] = \
                    self.prefill_supervisor.snapshot()
        return s

    def generate_speculative(self, ids, new_tokens, prefix_id=None,
                             request_class="interactive",
                             deadline_s=None, ticket=None, rid=None):
        """Greedy speculative decoding (token-identical to plain greedy;
        the draft only changes the dispatch count). Holds only the
        dedicated spec lock during the generation — concurrent plain
        requests keep flowing through the executor. Admission applies
        like any generate (the deadline guards the QUEUE wait; the
        speculative loop itself has no mid-flight cancel boundary —
        docs/SERVING.md)."""
        t0 = time.monotonic()
        if rid is None:
            rid = self.mint_rid()
        tctx = telemetry.TraceContext(rid, request_class,
                                      deadline_ms=None if deadline_s is None
                                      else deadline_s * 1e3,
                                      parent="serve.speculative")
        released = self.admission is None
        try:
            strip = 0
            if self.kv_backend is not None and prefix_id is not None:
                # paged mode: the prefix becomes prepended tokens BEFORE
                # the token charge is computed (the page reservation
                # must cover the full prompt; the trie makes the shared
                # part nearly free to re-run)
                with self.cond:
                    self._check_dead()
                    self._check_admittable()
                    pkw = {"prefix_id": prefix_id}
                    ids, strip = self._expand_prefix(ids, pkw)
                prefix_id = None
            if ticket is None and self.admission is not None:
                # paged speculative rounds reserve up to gamma extra
                # verify positions past new_tokens — charge for them
                gamma = self.spec.gamma if self.spec is not None else 0
                ticket, _ = self.admit(
                    request_class, deadline_s, rid=rid,
                    tokens=self.kv_tokens(ids, int(new_tokens) + gamma))
            completed = False
            try:
                with telemetry.trace_scope(tctx):
                    out = self._generate_speculative_once(ids, new_tokens,
                                                          prefix_id,
                                                          rid=rid)
                    if strip:
                        out = out[:, strip:]
                completed = True
            finally:
                if not released:
                    # failures must not feed the service-rate estimator
                    # (they would inflate the rate Retry-After divides by)
                    self.admission.release(ticket, completed=completed)
                    released = True
        except AdmissionShed:
            self.m_requests.inc(endpoint="/generate-speculative",
                                status="503")
            self.m_class_outcome.inc(**{"class": request_class,
                                        "outcome": "shed"})
            raise
        except ServiceDegraded:
            self.m_requests.inc(endpoint="/generate-speculative",
                                status="503")
            self.m_class_outcome.inc(**{"class": request_class,
                                        "outcome": "degraded"})
            raise
        except BaseException:
            self.m_requests.inc(endpoint="/generate-speculative",
                                status="error")
            self.m_class_outcome.inc(**{"class": request_class,
                                        "outcome": "error"})
            raise
        self.m_latency.observe(time.monotonic() - t0, exemplar=rid)
        self.m_requests.inc(endpoint="/generate-speculative", status="200")
        self.m_class_outcome.inc(**{"class": request_class,
                                    "outcome": "ok"})
        self.m_tokens.inc(len(ids) * int(new_tokens))
        self._account_edge_bytes(ids, int(new_tokens))
        return out

    def _generate_speculative_once(self, ids, new_tokens, prefix_id,
                                   rid=None):
        import numpy as np
        if self.spec is None:
            raise KeyError("server started without --draft-model; "
                           "speculative generation unavailable")
        with self.cond:                     # resolve prefix briefly
            self._check_dead()
            self._check_admittable()
            prefix = None
            if prefix_id is not None:
                if prefix_id not in self.spec_prefixes:
                    raise KeyError(
                        f"unknown prefix_id {prefix_id!r} for speculative "
                        "generation (register via /prefix while the "
                        "draft model is configured)")
                self.prefixes.move_to_end(prefix_id)   # LRU touch
                prefix = self.spec_prefixes[prefix_id]
        with self.spec_lock, telemetry.span("serve", "speculative"):
            # rid threads through to the paged allocator as the page
            # owner id, so the governor's orphan sweep can name it
            return np.asarray(self.spec.generate(ids, new_tokens,
                                                 prefix=prefix, rid=rid))

    def prevalidate(self, ids, new_tokens, kw):
        """Resolve prefix_id and run the full admission validation WITHOUT
        submitting — the streaming path needs errors raised BEFORE the
        200/chunked headers commit (a status-checking client must see
        400, not a 200 whose body is an error line). Returns `(ids, kw)`
        with the prefix resolved: the dense handle in `kw["prefix"]`, or
        — paged mode — the prefix TOKENS prepended to `ids` (plus
        `kw["strip_prefix"]` so the response still omits them)."""
        from pipeedge_tpu.parallel.batcher import _build_request
        kw = dict(kw)
        with self.cond:
            self._check_dead()
            self._check_admittable()
            if self.kv_backend is not None:
                ids, strip = self._expand_prefix(ids, kw)
                if strip:
                    kw["strip_prefix"] = strip
            else:
                self._resolve_prefix(kw)
        _build_request(self.pipe, "__prevalidate__", ids, new_tokens,
                       kw.get("temperature", 0.0), kw.get("top_k", 0),
                       kw.get("seed", 0), kw.get("eos_token"),
                       kw.get("pad_token"), kw.get("prefix"))
        return ids, kw

    def _resolve_prefix(self, kw):
        pid = kw.pop("prefix_id", None)
        if pid is not None:
            if pid not in self.prefixes:
                raise KeyError(f"unknown prefix_id {pid!r} (evicted "
                               "or never registered)")
            self.prefixes.move_to_end(pid)     # LRU touch
            kw["prefix"] = self.prefixes[pid]

    def _expand_prefix(self, ids, kw):
        """Paged mode: a `prefix_id` becomes its registered tokens
        prepended to every prompt row — the prefix trie turns the
        repeated prefill into page reuse (one prompt pass fleet-wide,
        then shared pages). Returns (expanded ids, strip); callers
        slice `strip` columns off the result so the response matches
        the dense handle contract (suffix + continuation)."""
        pid = kw.pop("prefix_id", None)
        if pid is None:
            return ids, 0
        if pid not in self.prefixes:
            raise KeyError(f"unknown prefix_id {pid!r} (evicted "
                           "or never registered)")
        self.prefixes.move_to_end(pid)         # LRU touch
        tokens = self.prefixes[pid]["tokens"]
        return [list(tokens) + [int(t) for t in r] for r in ids], \
            len(tokens)

    def generate(self, ids, new_tokens, on_token=None,
                 request_class="interactive", deadline_s=None,
                 ticket=None, deadline=None, rid=None, **kw):
        """One admitted generation. `request_class`/`deadline_s` drive
        the admission plane; a pre-admitted `ticket` (+ its absolute
        `deadline`) comes from the streaming path, which must shed
        BEFORE the chunked headers commit. The deadline rides into the
        executor, whose decode-step expiry check fires the request's
        `cancel` flag — a mid-flight expiry surfaces as
        `DeadlineExceeded` (HTTP 504). `rid` is the minted request id
        (mint_rid); every span, flight event, and the executor's
        per-stage spans carry it."""
        t0 = time.monotonic()
        if rid is None:
            rid = self.mint_rid()
        tctx = telemetry.TraceContext(rid, request_class,
                                      deadline_ms=None if deadline_s is None
                                      else deadline_s * 1e3,
                                      parent="serve.generate")
        # paged mode: a prefix_id becomes prepended tokens BEFORE the
        # token charge is computed (the reservation must cover the full
        # prompt; the trie makes the shared part nearly free to run)
        strip = int(kw.pop("strip_prefix", 0))
        if self.kv_backend is not None and kw.get("prefix_id") is not None:
            with self.cond:
                ids, strip = self._expand_prefix(ids, kw)
        completed = False
        try:
            if ticket is None and deadline is None:
                # the streaming path pre-admits (its ticket, or with
                # --no-admission just the computed deadline) — don't
                # clobber a deadline that arrives without a ticket
                ticket, deadline = self.admit(
                    request_class, deadline_s, rid=rid,
                    tokens=self.kv_tokens(ids, new_tokens))
            try:
                if self.brownout is not None:
                    new_tokens = self.brownout.clamp(new_tokens)
                cancel = kw.get("cancel")
                if deadline is not None:
                    if cancel is None:
                        cancel = threading.Event()
                        kw["cancel"] = cancel
                    kw["deadline"] = deadline
                with telemetry.trace_scope(tctx), \
                        telemetry.span("serve", "generate", rid=rid):
                    out = self._generate_policied(ids, new_tokens,
                                                  on_token, kw, rid=rid)
                now = time.monotonic()
                if (deadline is not None and now >= deadline
                        and cancel.is_set()):
                    # the executor cancelled it at a decode-step
                    # boundary: the work was cut short, answer 504
                    completed = True   # it DID occupy a full slot
                    raise DeadlineExceeded(
                        request_class, deadline_s
                        if deadline_s is not None else deadline - t0)
                completed = True
            finally:
                # generate releases ANY ticket it holds: the streaming
                # handler hands its pre-admitted ticket over with the
                # request and never touches it again
                if ticket is not None and self.admission is not None:
                    self.admission.release(ticket, completed=completed)
        except AdmissionShed:
            self.m_requests.inc(endpoint="/generate", status="503")
            self.m_class_outcome.inc(**{"class": request_class,
                                        "outcome": "shed"})
            raise
        except DeadlineExceeded:
            self.m_deadline.inc()
            self.m_requests.inc(endpoint="/generate", status="504")
            self.m_class_outcome.inc(**{"class": request_class,
                                        "outcome": "deadline"})
            # a 504 is exactly the artifact the flight recorder exists
            # for: which stage/queue/brownout rung ate the budget
            self.flight.note("deadline", rid=rid, cls=request_class,
                             budget_s=deadline_s,
                             elapsed_ms=round((time.monotonic() - t0) * 1e3,
                                              3))
            self.flight.maybe_dump("deadline", rid=rid,
                                   context=self.bundle_context())
            raise
        except ServiceDegraded:
            self.m_requests.inc(endpoint="/generate", status="503")
            self.m_class_outcome.inc(**{"class": request_class,
                                        "outcome": "degraded"})
            raise
        except BaseException:
            self.m_requests.inc(endpoint="/generate", status="error")
            self.m_class_outcome.inc(**{"class": request_class,
                                        "outcome": "error"})
            raise
        elapsed = time.monotonic() - t0
        # the exemplar links a latency-histogram bucket back to THIS
        # request's trace id: a p99 spike on a dashboard resolves to a
        # trace_report --request invocation (docs/OBSERVABILITY.md)
        self.m_latency.observe(elapsed, exemplar=rid)
        self.m_requests.inc(endpoint="/generate", status="200")
        self.m_class_outcome.inc(**{"class": request_class,
                                    "outcome": "ok"})
        self.m_tokens.inc(len(ids) * int(new_tokens))
        self._account_edge_bytes(ids, int(new_tokens))
        self.flight.note("done", rid=rid, cls=request_class,
                         ms=round(elapsed * 1e3, 3))
        # paged prefix contract: the response omits the prepended prefix
        return out[:, strip:] if strip else out

    def _generate_policied(self, ids, new_tokens, on_token, kw, rid=None):
        with self.cond:
            self._check_dead()
            self._check_admittable()   # degraded: 503 + Retry-After
        try:
            return self._generate_once(ids, new_tokens, on_token, kw,
                                       rid=rid)
        except ServiceDegraded:
            raise
        except RuntimeError:
            # the executor failed while a failover window was open: the
            # request was in flight when the stage died. Replay it once
            # after recovery instead of surfacing the transient — except
            # streamed requests, whose partial output cannot be unsent.
            if on_token is not None or not self._await_recovery():
                raise
            self.m_replays.inc()
            self.flight.note("replay", rid=rid)
            # derived executor id: the failed attempt may still hold the
            # original rid in the executor's live set, and the replay's
            # spans should be distinguishable from the first try's while
            # staying greppable by prefix
            return self._generate_once(ids, new_tokens, on_token, kw,
                                       rid=None if rid is None
                                       else f"{rid}.replay")

    def _account_edge_bytes(self, ids, new_tokens: int) -> None:
        """Per-edge activation traffic of one completed request: every
        inter-stage boundary moves a [B, S, H] prefill payload plus a
        [B, 1, H] payload per decode step (host-driven device edges — the
        serving analogue of the DCN wire counters)."""
        n_edges = len(self.pipe.stages) - 1
        if n_edges <= 0:
            return
        hidden = getattr(self.pipe.cfg, "hidden_size", 0)
        prompt_len = max(len(r) for r in ids) if ids else 0
        per_edge = (len(ids) * (prompt_len + max(0, new_tokens - 1))
                    * hidden * self._edge_itemsize)
        for i in range(n_edges):
            self.m_edge_bytes.inc(per_edge, edge=f"{i}->{i + 1}")

    def _generate_once(self, ids, new_tokens, on_token, kw, rid=None):
        # the trace rid doubles as the EXECUTOR request id: the mapping
        # between an HTTP request and its executor lifecycle is identity,
        # and the executors' per-stage spans tag it for free (_run_stage)
        if rid is None:
            rid = self.mint_rid()
        if self.prefill_fleet is not None and kw.get("shipped") is None:
            # disaggregated: the prompt pass runs on the PREFILL fleet's
            # own pipeline and ships KV pages in — the decode executor
            # below only ever runs decode steps, so one tenant's long
            # prompt no longer stretches everyone else's inter-token
            # latency (docs/SERVING.md disaggregation). EXCEPT when the
            # prefix trie already covers the prompt's full pages: then
            # the only prompt work left is a short suffix span, cheaper
            # run in place than re-prefilled remotely and re-shipped.
            route_local = False
            if self.brownout is not None \
                    and not self.brownout.allow_disaggregate():
                # brownout rung 4 (colocate_prefill): the plane is hot
                # enough that the ship edge's latency + fault surface
                # costs more than prefill isolation buys — degrade
                # disaggregate -> colocated deliberately
                route_local = True
                self.m_prefill_colocated.inc(reason="brownout")
                self.flight.note("prefill_colocated", rid=rid,
                                 reason="brownout")
            if not route_local and len(ids) == 1:
                toks = [int(t) for t in ids[0]]
                matched = self.kv_backend.shared_prompt_tokens(toks)
                route_local = (matched > 0 and matched >= len(toks)
                               - self.kv_backend.page_size)
            if not route_local:
                try:
                    kw["shipped"] = self.prefill_fleet.prefill(ids,
                                                               rid=rid)
                except self._prefill_unavailable as exc:
                    # every prefill rank/retry exhausted: the request
                    # SURVIVES — the decode executor runs the prompt
                    # pass itself (token-identical; the p99 isolation is
                    # what degrades, not the request)
                    self.m_prefill_colocated.inc(reason="unavailable")
                    self.flight.note("prefill_colocated", rid=rid,
                                     reason="unavailable",
                                     error=str(exc))
        if self.exec is not None:
            with self.cond:
                self._check_dead()
                self._resolve_prefix(kw)
            self.exec.submit(rid, ids, new_tokens, on_token=on_token, **kw)
            return self.exec.wait(rid)
        with self.cond:
            self._check_dead()
            self._resolve_prefix(kw)
            self.batcher.submit(rid, ids, new_tokens, on_token=on_token,
                                **kw)
            self.cond.notify_all()
            while rid not in self.batcher.results:
                self._check_dead()
                self.cond.wait()
            return self.batcher.results.pop(rid)

    def stats(self):
        """Lock-free best-effort snapshot for /healthz (GIL-atomic reads;
        momentary inconsistency is fine for health)."""
        if self.exec is not None:
            s = self.exec.snapshot()
            s["pending"] = 0          # admission blocks in submit threads
            s["prefixes"] = len(self.prefixes)
        else:
            s = dict(self.batcher.stats,
                     active=self.batcher.active,
                     pending=len(self.batcher.pending),
                     prefixes=len(self.prefixes))
        # degraded/failover history: read back from the SAME registry
        # instruments /metrics renders, so the two surfaces cannot diverge
        s["degraded_entered_total"] = int(self.m_degraded.value())
        s["failover_replays_total"] = int(self.m_replays.value())
        s["rejoined_ranks_total"] = int(self.m_rejoined.value())
        last = self.m_last_dead.value()
        s["last_dead_rank"] = (None if last is None or last < 0
                               else int(last))
        return s

    def stop(self):
        self._gov_stop.set()
        if self.admission is not None:
            self.admission.close()   # shed every queued waiter (shutdown)
        with self.cond:
            self._stop = True
            self.cond.notify_all()
        if self.exec is not None:
            self.exec.stop()
        # tear the ship plane down LAST: in-flight prefills were already
        # failed fast by the executor stop above
        close = getattr(self.prefill_fleet, "close", None)
        if close is not None:
            close()
        if self.prefill_supervisor is not None:
            self.prefill_supervisor.stop()


def make_handler(service, model_name):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"      # chunked transfer needs 1.1

        def log_message(self, *a):      # quiet server
            pass

        def _send(self, code, obj, headers=()):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def _chunk(self, obj):
            data = json.dumps(obj).encode() + b"\n"
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        def _stream_generate(self, ids, new_tokens, kw,
                             request_class="interactive", deadline_s=None,
                             rid=None):
            """Chunked x-ndjson response: one line per decode step as the
            token lands, then the authoritative final line. The worker
            pushes DEVICE token arrays into a queue; the readback (the
            blocking part) happens here in the handler thread, so
            streaming never stalls the executor.

            A client that disconnects mid-stream (write fails) sets the
            request's `cancel` flag: the executor completes the request
            at its next pick instead of decoding to the cap, so dead
            requests free their admission slot / cache memory early
            (repeated disconnects could otherwise occupy every
            max_active slot with vanished clients)."""
            import numpy as np
            t0 = time.monotonic()
            # validate BEFORE headers commit: bad requests still 400
            # (raises into do_POST's error mapping) and don't spend
            # admission tokens; then ADMIT before headers commit too — a
            # shed must surface as a real 503 + Retry-After, not a 200
            # whose body is an error line. After this point failures
            # surface as a terminal {"error": ...} stream line.
            ids, kw = service.prevalidate(ids, new_tokens, kw)
            if rid is None:
                rid = service.mint_rid()
            try:
                ticket, deadline = service.admit(
                    request_class, deadline_s, rid=rid,
                    tokens=service.kv_tokens(ids, new_tokens))
            except AdmissionShed:
                # the non-streaming path counts its shed inside
                # generate(); a streaming shed never reaches generate(),
                # so both counters are settled here — the class x outcome
                # matrix must reconcile against the 503s either way
                service.m_requests.inc(endpoint="/generate", status="503")
                service.m_class_outcome.inc(**{"class": request_class,
                                               "outcome": "shed"})
                raise
            try:
                cancel = threading.Event()
                kw.update(cancel=cancel, request_class=request_class,
                          ticket=ticket, deadline=deadline, rid=rid)
                q = queue_mod.Queue()
                worker = threading.Thread(
                    target=self._run_generate,
                    args=(ids, new_tokens, kw, q), daemon=True)
                # once started, generate() owns the ticket's release
                worker.start()
            except BaseException:
                if ticket is not None:
                    service.admission.release(ticket, completed=False)
                raise
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header(RID_HEADER, rid)
            self.end_headers()
            steps = 0
            first_ms = None
            while True:
                kind, payload = q.get()
                if kind in ("error", "result"):
                    final = ({"error": str(payload), "rid": rid}
                             if kind == "error"
                             else {"ids": payload.tolist(),
                                   "first_token_ms": first_ms,
                                   "steps": steps, "rid": rid})
                    if not cancel.is_set():
                        try:
                            self._chunk(final)
                        except OSError:
                            cancel.set()
                    break
                step, token = payload
                # the blocking device readback happens HERE, in the
                # handler thread — the executor worker only enqueued the
                # device array and moved on
                tok = np.asarray(token).tolist()
                if first_ms is None:
                    first_ms = round((time.monotonic() - t0) * 1e3, 3)
                if not cancel.is_set():
                    try:
                        self._chunk({"step": step, "tokens": tok})
                    except OSError:
                        # client went away: cancel the generation but keep
                        # draining the queue until the worker's terminal
                        # result/error (it completes early at its next
                        # pick, releasing the executor slot)
                        cancel.set()
                steps += 1
            if not cancel.is_set():
                try:
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    pass    # disconnect after the final line: nothing owed

        def _run_generate(self, ids, new_tokens, kw, q):
            try:
                out = service.generate(
                    ids, new_tokens,
                    on_token=lambda step, tok: q.put(("token", (step, tok))),
                    **kw)
                q.put(("result", out))
            except BaseException as exc:   # noqa: BLE001 — surfaced as a
                q.put(("error", exc))      # terminal stream line

        def do_GET(self):
            if self.path == "/metrics":
                import monitoring
                extra = prom.render_monitoring_snapshot(
                    monitoring.snapshot())
                body = prom.REGISTRY.render(extra=extra).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.split("?", 1)[0] == "/debug/spans":
                # per-process span-ring drain (trace_report --fleet
                # federation; ?drain=0 peeks without clearing)
                drain = "drain=0" not in self.path
                self._send(200,
                           fleet_obs.debug_spans_payload(drain=drain))
            elif self.path == "/healthz":
                dead = service.dead is not None
                deg = service.degraded_info
                degraded = False
                if deg is not None:
                    degraded = {"dead_rank": deg["dead_rank"],
                                "since_s": round(time.monotonic()
                                                 - deg["since"], 3),
                                "retry_after": deg["retry_after"],
                                # "degraded" (hole open) vs "healing"
                                # (rank rejoined, restore in progress)
                                "phase": deg.get("phase", "degraded")}
                self._send(503 if dead else 200,
                           {"ok": not dead, "model": model_name,
                            "stages": len(service.pipe.stages),
                            "speculative": service.spec is not None,
                            "executor": service.executor,
                            "degraded": degraded,
                            "draining": service.draining,
                            "serving": service.serving_stats(),
                            "flight": service.flight_stats(),
                            # per-peer gray-failure scores when a
                            # peer-health scorer runs in this process
                            # (docs/FAULT_TOLERANCE.md); {} otherwise
                            "peer_health": peer_health.snapshot(),
                            "stats": service.stats()})
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            rid = None       # minted for /generate; names error bodies too
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/debug/dump":
                    # on-demand postmortem bundle (manual trigger — never
                    # cooldown-suppressed): optionally scoped to one rid
                    path = service.dump_postmortem(rid=req.get("rid"))
                    self._send(200, {"path": path,
                                     "written_total":
                                     service.flight.written_total()})
                elif self.path == "/degraded":
                    # the failover orchestrator's switch (see module doc):
                    # degraded -> healing -> healed lifecycle
                    if req.get("degraded", True):
                        if req.get("healing"):
                            service.mark_healing()
                        else:
                            # no hint -> DERIVE the Retry-After from the
                            # observed heal history (_derived_retry_after)
                            ra = req.get("retry_after")
                            service.enter_degraded(
                                dead_rank=req.get("dead_rank"),
                                retry_after=(None if ra is None
                                             else float(ra)))
                    else:
                        service.exit_degraded(
                            healed=bool(req.get("healed")),
                            rank=req.get("rank"))
                    self._send(200, {"degraded":
                                     service.degraded_info is not None})
                elif self.path == "/prefix":
                    pid, plen = service.add_prefix(req["ids"])
                    self._send(200, {"prefix_id": pid, "len": plen})
                elif self.path == "/drain":
                    # the router's graceful-drain entry (replica side):
                    # stop admitting, keep finishing; /healthz's
                    # stats.active reports the remaining in-flight work
                    service.begin_drain()
                    self._send(200, {"draining": True,
                                     "active": service.stats().get(
                                         "active", 0)})
                elif self.path == "/kv/export":
                    blob, plen, pages = service.kv_export(req["ids"])
                    self._send(200, {"blob": blob, "tokens_covered": plen,
                                     "pages": pages})
                elif self.path == "/kv/import":
                    pages = service.kv_import(req["ids"], req["blob"])
                    self._send(200, {"installed_pages": pages})
                elif self.path == "/generate":
                    ids = req["ids"]
                    if ids and not isinstance(ids[0], list):
                        ids = [ids]
                    # admission identity: every /generate carries a class
                    # (default interactive) and may carry a deadline
                    # budget in ms from receipt (docs/SERVING.md)
                    request_class = req.get("class", "interactive")
                    if request_class not in REQUEST_CLASSES:
                        raise ValueError(
                            f"unknown request class {request_class!r} "
                            f"(expected one of {sorted(REQUEST_CLASSES)})")
                    deadline_s = None
                    if req.get("deadline_ms") is not None:
                        deadline_s = float(req["deadline_ms"]) / 1e3
                        if deadline_s <= 0:
                            raise ValueError("deadline_ms must be > 0")
                    # the rid arrives on X-PipeEdge-Rid when a router
                    # (or any tracing caller) already minted it — honor
                    # it so the fleet-wide trace stays one tree; mint
                    # HERE only when absent, before any admission
                    # decision: every outcome (200/503/504) names the
                    # same rid, so a loadgen worst-N entry or a 504 body
                    # cross-references the trace and postmortem bundles
                    rid = _header_rid(self.headers) or service.mint_rid()
                    if req.get("speculative"):
                        if req.get("temperature") or req.get("top_k") \
                                or req.get("eos_token") is not None \
                                or req.get("stream"):
                            raise ValueError(
                                "speculative generation is greedy-exact "
                                "whole-rounds; it does not compose with "
                                "sampling/eos/stream")
                        if not service.speculative_allowed():
                            # brownout rung 1 (no_speculative): fall back
                            # to plain greedy — token-identical, but the
                            # serialized draft/verify path stays free
                            out = service.generate(
                                ids, int(req["new_tokens"]),
                                request_class=request_class,
                                deadline_s=deadline_s, rid=rid,
                                temperature=0.0, top_k=0, seed=0,
                                eos_token=None,
                                prefix_id=req.get("prefix_id"))
                        else:
                            out = service.generate_speculative(
                                ids, int(req["new_tokens"]),
                                prefix_id=req.get("prefix_id"),
                                request_class=request_class,
                                deadline_s=deadline_s, rid=rid)
                        self._send(200, {"ids": out.tolist(), "rid": rid},
                                   headers=_rid_headers(rid))
                    else:
                        kw = dict(
                            temperature=float(req.get("temperature", 0.0)),
                            top_k=int(req.get("top_k", 0)),
                            seed=int(req.get("seed", 0)),
                            eos_token=req.get("eos_token"),
                            prefix_id=req.get("prefix_id"))
                        if req.get("stream"):
                            self._stream_generate(
                                ids, int(req["new_tokens"]), kw,
                                request_class, deadline_s, rid=rid)
                        else:
                            out = service.generate(
                                ids, int(req["new_tokens"]),
                                request_class=request_class,
                                deadline_s=deadline_s, rid=rid, **kw)
                            self._send(200, {"ids": out.tolist(),
                                             "rid": rid},
                                       headers=_rid_headers(rid))
                else:
                    self._send(404, {"error": "unknown path"})
            except (KeyError, ValueError, TypeError, IndexError) as exc:
                self._send(400, {"error": str(exc)})
            except AdmissionShed as exc:
                # overload backpressure: the Retry-After is COMPUTED from
                # the observed service rate ("come back when the queue you
                # would join has drained"), not a constant
                self._send(503, {"error": str(exc), "shed": True,
                                 "class": exc.request_class,
                                 "reason": exc.reason, "rid": rid},
                           headers=(("Retry-After",
                                     f"{exc.retry_after:g}"),)
                           + _rid_headers(rid))
            except DeadlineExceeded as exc:
                # the deadline expired while EXECUTING: the executor
                # cancelled it at a decode-step boundary (no Retry-After —
                # re-sending the same budget would expire the same way).
                # The rid cross-references the postmortem bundle this 504
                # just triggered (flight recorder).
                self._send(504, {"error": str(exc),
                                 "deadline_exceeded": True,
                                 "class": exc.request_class, "rid": rid},
                           headers=_rid_headers(rid))
            except ServiceDegraded as exc:
                # a degraded window is transient by contract: tell the
                # client exactly when to come back instead of hanging it
                self._send(503, {"error": str(exc),
                                 "degraded": True,
                                 "dead_rank": exc.dead_rank, "rid": rid},
                           headers=(("Retry-After",
                                     f"{exc.retry_after:g}"),)
                           + _rid_headers(rid))
            except RuntimeError as exc:
                # every 503 carries a Retry-After (docs/SERVING.md audit):
                # even a dead-worker 503 names the best current estimate
                self._send(503, {"error": str(exc)},
                           headers=(("Retry-After",
                                     f"{service.retry_after_hint():g}"),))

    return Handler


def _parse_class_map(pairs, what, parser):
    """`interactive=2.5`-style repeated CLI pairs -> {class: float}."""
    try:
        out = parse_class_map(pairs, what)
    except ValueError as exc:
        parser.error(str(exc))
    return out or None


def _inject_stall(pipe, spec, parser):
    """`--inject-stall STAGE:MS` — wrap every callable of one pipeline
    stage with a fixed sleep. A deterministic, attributable stall for the
    traced-serve smoke: it lands INSIDE that stage's `exec{i}` span, so
    `trace_report --request` must name exactly this stage as the
    dominant stall (the acceptance gate)."""
    import functools
    try:
        stage_s, ms_s = spec.split(":", 1)
        idx, delay_s = int(stage_s), float(ms_s) / 1e3
        st = pipe.stages[idx]
    except (ValueError, IndexError):
        parser.error(f"--inject-stall expects STAGE:MS with STAGE < "
                     f"{len(pipe.stages)}, got {spec!r}")
        return

    def slow(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            time.sleep(delay_s)
            return fn(*a, **kw)
        return wrapper

    for key, fn in list(st.items()):
        if callable(fn):
            st[key] = slow(fn)
    print(f"chaos: injecting {ms_s}ms stall into every step of stage "
          f"{idx}", flush=True)


class WorkerSupervisor:
    """Spawns and supervises a fleet of child worker PROCESSES: respawn
    on death with crash-loop backoff and an epoch bump per incarnation.
    Subclasses name the fleet (`LABEL`/`TAG`) and provide the per-rank
    argv/env/ready-line contract — `PrefillWorkerSupervisor` runs the
    prefill fleet of `--disaggregate process`, `ReplicaSupervisor` the
    decode replicas of `--role router`
    (docs/FAULT_TOLERANCE.md lifecycles)."""

    LABEL = "worker"       # human/log name ("prefill worker rank 1 died")
    TAG = "worker"         # stdout tee prefix ("[worker r1] ...")

    RESPAWN_DELAY_S = 0.5
    RESPAWN_BACKOFF_MAX_S = 30.0
    FAST_DEATH_S = 5.0     # an incarnation dying this fast escalates

    def __init__(self, ranks, respawn=True):
        import subprocess
        self._subprocess = subprocess
        self.ranks = tuple(ranks)
        self.respawn = bool(respawn)
        self._procs = {}                  # rank -> Popen
        self._epoch = {r: 0 for r in self.ranks}
        self._ready = {r: threading.Event() for r in self.ranks}
        # crash-loop protection: a worker that dies FAST (startup
        # failure, host OOM) doubles its respawn delay up to the cap —
        # each respawn pays a full interpreter + model build, so a
        # deterministic failure must not thrash the host at 2 Hz; an
        # incarnation that lived a while resets the backoff
        self._backoff = {r: self.RESPAWN_DELAY_S for r in self.ranks}
        self._spawned_at = {r: 0.0 for r in self.ranks}
        self._respawn_after = {r: 0.0 for r in self.ranks}
        # ranks retired by the autoscaler: their epoch records are
        # RETAINED so a future add_rank continues the sequence (+1) and
        # the rejoin stays fenced against every dead incarnation
        self._retired = set()
        self._stop = threading.Event()
        self._lock = make_lock(f"serve.{self.TAG}_sup")
        self._watchers = []
        for r in self.ranks:
            self._spawn(r)
        self._supervisor = threading.Thread(target=self._watch_loop,
                                            daemon=True,
                                            name=f"{self.TAG}-supervisor")
        self._supervisor.start()

    # -- the per-fleet contract (subclasses) -----------------------------

    def _argv(self, rank):
        raise NotImplementedError

    def _env(self, rank):
        env = dict(os.environ)
        # every incarnation carries its epoch: a respawned worker's
        # JOIN/readmission is fenced against its dead predecessor
        env["DCN_EPOCH"] = str(self._epoch[rank])
        return env

    def _is_ready(self, rank, line):
        raise NotImplementedError

    # -- lifecycle -------------------------------------------------------

    def _spawn(self, rank):
        import subprocess
        proc = subprocess.Popen(
            self._argv(rank), env=self._env(rank), text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        with self._lock:
            # stop() may have swept _procs while this Popen was in
            # flight (the respawn/shutdown race): a spawn the shutdown
            # can no longer see must be terminated HERE, not leaked
            if self._stop.is_set():
                proc.terminate()
                return
            self._procs[rank] = proc
            self._spawned_at[rank] = time.monotonic()
        t = threading.Thread(target=self._pump, args=(rank, proc),
                             daemon=True, name=f"{self.TAG}-out-r{rank}")
        t.start()
        # pump threads exit when their worker's stdout closes: prune
        # the dead ones so a long-lived server doesn't accumulate one
        # Thread record per respawn
        self._watchers = [w for w in self._watchers if w.is_alive()]
        self._watchers.append(t)
        print(f"{self.LABEL} rank {rank} spawned "
              f"(pid={proc.pid}, epoch={self._epoch[rank]})", flush=True)

    def _pump(self, rank, proc):
        # tee worker output through the server's stdout (prefixed): the
        # chaos harness and CI key on the workers' chaos/ready lines
        for line in proc.stdout:
            print(f"[{self.TAG} r{rank}] {line}", end="", flush=True)
            if self._is_ready(rank, line):
                self._ready[rank].set()

    def _watch_loop(self):
        dead_pending = set()       # deaths observed, respawn not yet due
        while not self._stop.wait(self.RESPAWN_DELAY_S):
            now = time.monotonic()
            for rank in self.ranks:
                with self._lock:
                    proc = self._procs.get(rank)
                if proc is None or proc.poll() is None:
                    continue
                if rank not in dead_pending:
                    # observe the death ONCE: escalate the backoff only
                    # for fast deaths (crash loop), reset otherwise
                    lived = now - self._spawned_at[rank]
                    if lived < self.FAST_DEATH_S:
                        self._backoff[rank] = min(
                            self.RESPAWN_BACKOFF_MAX_S,
                            self._backoff[rank] * 2)
                    else:
                        self._backoff[rank] = self.RESPAWN_DELAY_S
                    self._respawn_after[rank] = now + self._backoff[rank]
                    dead_pending.add(rank)
                    print(f"{self.LABEL} rank {rank} died "
                          f"(rc={proc.returncode}; respawn backoff "
                          f"{self._backoff[rank]:g}s)", flush=True)
                    if not self.respawn:
                        with self._lock:
                            self._procs.pop(rank, None)
                        continue
                if not self.respawn or self._stop.is_set() \
                        or now < self._respawn_after[rank]:
                    continue
                dead_pending.discard(rank)
                self._ready[rank].clear()
                self._epoch[rank] += 1
                self._spawn(rank)

    def wait_ready(self, timeout=180.0):
        deadline = time.monotonic() + timeout
        for rank in self.ranks:
            if not self._ready[rank].wait(
                    max(0.0, deadline - time.monotonic())):
                raise RuntimeError(
                    f"{self.LABEL} rank {rank} never became ready "
                    f"within {timeout}s")

    def restart(self, rank):
        """Planned restart (the router's drain endgame): terminate the
        incarnation; the watch loop observes the death and respawns it
        with the next epoch — the same path an unplanned death takes,
        so readmission is identical either way."""
        with self._lock:
            proc = self._procs.get(rank)
        if proc is not None and proc.poll() is None:
            proc.terminate()

    # -- autoscale membership (docs/FAULT_TOLERANCE.md autoscale) --------

    def _on_add_rank(self, rank):
        """Subclass hook: provision per-rank resources (a port, an
        argv slot) BEFORE the new rank's first spawn."""

    def add_rank(self, rank=None):
        """Autoscale scale-out: bring a new rank into the supervised
        set, preferring the lowest retired rank id. A resurrected rank
        continues its epoch sequence (+1), so its join is fenced
        against every dead incarnation exactly like a respawn; a
        brand-new rank starts at epoch 0. Returns the rank spawned."""
        with self._lock:
            if rank is None:
                spare = sorted(self._retired)
                rank = spare[0] if spare else \
                    (max(self._epoch) + 1 if self._epoch else 0)
            if rank in self.ranks:
                raise ValueError(f"rank {rank} is already active")
            self._retired.discard(rank)
            if rank in self._epoch:
                self._epoch[rank] += 1
            else:
                self._epoch[rank] = 0
            self._ready[rank] = threading.Event()
            self._backoff[rank] = self.RESPAWN_DELAY_S
            self._spawned_at[rank] = 0.0
            self._respawn_after[rank] = 0.0
            self._on_add_rank(rank)
            self.ranks = tuple(list(self.ranks) + [rank])
        self._spawn(rank)
        return rank

    def retire_rank(self, rank):
        """Autoscale scale-in endgame: take `rank` out of the
        supervised set WITHOUT respawn and terminate its incarnation.
        The proc record is popped under the lock BEFORE the terminate,
        so the watch loop can never observe the death and resurrect
        it. Epoch records are retained (see add_rank)."""
        with self._lock:
            if rank not in self.ranks:
                return False
            self.ranks = tuple(r for r in self.ranks if r != rank)
            proc = self._procs.pop(rank, None)
            self._ready[rank].clear()
            self._retired.add(rank)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except self._subprocess.TimeoutExpired:
                proc.kill()
        print(f"{self.LABEL} rank {rank} retired "
              f"(epoch={self._epoch[rank]})", flush=True)
        return True

    def snapshot(self):
        with self._lock:
            return {str(r): {"pid": p.pid, "epoch": self._epoch[r],
                             "alive": p.poll() is None}
                    for r, p in self._procs.items()}

    def stop(self):
        self._stop.set()
        self._supervisor.join(timeout=5)
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except self._subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)


class PrefillWorkerSupervisor(WorkerSupervisor):
    """The prefill fleet of `--disaggregate process`
    (tools/prefill_worker.py ranks 1..N of the ship plane's DCN world).
    A worker that dies — crash, OOM, chaos kill — is respawned with
    DCN_EPOCH incremented, so its JOIN clears the decode side's death
    fence and the fleet readmits it (docs/FAULT_TOLERANCE.md
    disaggregated serving lifecycle). Chaos: PIPEEDGE_PREFILL_CHAOS (a
    DCN_CHAOS spec) arms deterministic faults in ONE worker's env
    (PIPEEDGE_PREFILL_CHAOS_RANK, default 1) for the first incarnation
    only — respawns come up clean, exactly like the restart@K:MS
    contract."""

    LABEL = "prefill worker"
    TAG = "prefill"

    def __init__(self, worker_cmd, ranks, respawn=True, http_ports=None):
        self._cmd = list(worker_cmd)      # without rank; appended per rank
        # rank -> observability HTTP port (each worker serves /metrics +
        # /debug/spans there, so the fleet collector and trace_report
        # --fleet reach prefill processes too)
        self._http_ports = dict(http_ports or {})
        super().__init__(ranks, respawn=respawn)

    def _argv(self, rank):
        argv = [sys.executable] + self._cmd[:1] + [str(rank)] \
            + self._cmd[1:]
        port = self._http_ports.get(rank)
        if port:
            argv += ["--http-port", str(port)]
        return argv

    def snapshot(self):
        out = super().snapshot()
        for rank, port in self._http_ports.items():
            rec = out.get(str(rank))
            if rec is not None:
                rec["http_url"] = f"http://127.0.0.1:{port}"
        return out

    def _env(self, rank):
        env = super()._env(rank)
        chaos = os.getenv("PIPEEDGE_PREFILL_CHAOS")
        chaos_rank = int(os.getenv("PIPEEDGE_PREFILL_CHAOS_RANK", "1"))
        if chaos and rank == chaos_rank and self._epoch[rank] == 0:
            env["DCN_CHAOS"] = chaos
        return env

    def _is_ready(self, rank, line):
        # exact machine line only: a bare substring ("ready") would
        # also match e.g. "...already initialized" warnings from
        # the model build and release wait_ready() mid-build
        return line.startswith(f"prefill worker rank {rank} ready")


class ReplicaSupervisor(WorkerSupervisor):
    """The decode replicas behind `--role router`: each rank is a full
    `serve.py --role replica` process on its own port. A replica that
    dies respawns with the next epoch after crash-loop backoff; the
    router's health polls readmit it once it proves itself (the
    registry's readmit confirmation — docs/FAULT_TOLERANCE.md replica
    lifecycle). `restart(rank)` is the drain endgame: planned
    detach rides the same death-observation path."""

    LABEL = "decode replica"
    TAG = "replica"

    def __init__(self, base_cmd, host, ports, respawn=True):
        self._base_cmd = list(base_cmd)
        self._host = host
        self._ports = list(ports)
        super().__init__(range(len(ports)), respawn=respawn)

    def _argv(self, rank):
        return [sys.executable] + self._base_cmd + [
            "--host", self._host, "--port", str(self._ports[rank])]

    def _is_ready(self, rank, line):
        # the replica's own "serving ... on HOST:PORT" line; the port
        # makes it rank-unique
        return (line.startswith("serving ")
                and f" on {self._host}:{self._ports[rank]}" in line)

    def _on_add_rank(self, rank):
        # a resurrected rank reuses its old port (the listener is
        # gone — nothing holds it); a brand-new rank gets a fresh one
        while len(self._ports) <= rank:
            self._ports += _free_ports(1, self._host)

    def url_of(self, rank):
        return f"http://{self._host}:{self._ports[rank]}"


def _free_ports(n, host="127.0.0.1"):
    import socket as socket_mod
    socks = [socket_mod.create_server((host, 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def make_router_handler(router, model_name, collector=None,
                        autoscaler=None):
    """HTTP surface of `--role router`: the same endpoint shapes a
    single replica serves (clients need no code change), backed by the
    DecodeRouter instead of a local pipeline. `collector` (a
    FleetCollector) backs GET /fleet — the one aggregated scrape
    surface across router + replicas + prefill workers. `autoscaler`
    (an AutoscaleRunner) adds the capacity controller's snapshot to
    /healthz and /fleet — the block the chaos harness polls for
    decision counts."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"      # chunked transfer needs 1.1

        def log_message(self, *a):      # quiet server
            pass

        def _send(self, code, obj, headers=()):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def _chunk(self, obj):
            data = json.dumps(obj).encode() + b"\n"
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        def do_GET(self):
            if self.path == "/metrics":
                body = prom.REGISTRY.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/fleet":
                if collector is None:
                    self._send(503, {"error": "fleet collector disabled "
                                              "(--fleet-scrape-interval "
                                              "0)"},
                               headers=(("Retry-After", "5"),))
                else:
                    snap = collector.fleet_snapshot()
                    if autoscaler is not None:
                        snap["autoscale"] = \
                            autoscaler.controller.snapshot()
                    self._send(200, snap)
            elif self.path.split("?", 1)[0] == "/debug/spans":
                # the router's own span ring (trace_report --fleet
                # federation; ?drain=0 peeks without clearing)
                drain = "drain=0" not in self.path
                self._send(200,
                           fleet_obs.debug_spans_payload(drain=drain))
            elif self.path == "/healthz":
                code, body = router.healthz()
                body["model"] = model_name
                if autoscaler is not None:
                    body["autoscale"] = \
                        autoscaler.controller.snapshot()
                headers = ((("Retry-After", "1"),) if code == 503
                           else ())
                self._send(code, body, headers=headers)
            else:
                self._send(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/generate":
                    if req.get("stream"):
                        self._stream(req)
                        return
                    status, body, headers = router.dispatch(req)
                    self._send(status, body, headers=headers)
                elif self.path == "/prefix":
                    pid, plen = router.register_prefix(req["ids"])
                    self._send(200, {"prefix_id": pid, "len": plen})
                elif self.path == "/drain":
                    out = router.drain_replica(
                        req["replica"],
                        migrate=bool(req.get("migrate", True)))
                    self._send(200 if out.get("drained") else 409, out)
                else:
                    self._send(404, {"error": f"unknown path {self.path}"})
            except (KeyError, ValueError, TypeError, IndexError) as exc:
                self._send(400, {"error": str(exc)})
            except RuntimeError as exc:
                self._send(503, {"error": str(exc)},
                           headers=(("Retry-After", "1"),))

        def _stream(self, req):
            """Relay a streaming generation: the router's generator
            owns failover; this method only moves lines to the socket
            (a mid-stream replica death is invisible here beyond the
            suppressed replay latency)."""
            streaming = False
            it = router.stream(req)
            for item in it:
                if item[0] == "status":
                    _, code, headers = item
                    if code == 200:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("Transfer-Encoding", "chunked")
                        for name, value in headers:
                            # the identity echo (X-PipeEdge-Rid /
                            # -Replica) rides the stream headers too
                            self.send_header(name, value)
                        self.end_headers()
                        streaming = True
                    else:
                        nxt = next(it, None)
                        body = (nxt[1] if nxt is not None
                                and nxt[0] == "line" else {})
                        self._send(code, body, headers=headers)
                        return
                else:
                    try:
                        self._chunk(item[1])
                    except OSError:
                        # client went away: closing the generator tears
                        # down the upstream replica connection too
                        it.close()
                        return
            if streaming:
                try:
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    pass

    return Handler


def _run_router(args):
    """`--role router` entry: spawn/adopt the replica fleet, start the
    health poller, serve the routed HTTP surface. Model-free — the
    router never imports jax or loads weights."""
    from pipeedge_tpu.serving.router import DecodeRouter, RouterPolicy
    policy = RouterPolicy(
        poll_interval_s=args.router_poll_interval,
        health_timeout_s=args.router_health_timeout,
        request_timeout_s=args.route_timeout,
        route_retries=args.route_retries,
        hedge_ms=args.hedge_ms,
        drain_timeout_s=args.drain_timeout)
    supervisor = None
    if args.replica_addrs:
        replicas = {}
        for i, addr in enumerate(args.replica_addrs.split(",")):
            addr = addr.strip()
            replicas[f"r{i}"] = (addr if addr.startswith("http")
                                 else f"http://{addr}")
    else:
        ports = _free_ports(args.replicas, args.host)
        base_cmd = [
            os.path.abspath(__file__), "--role", "replica",
            "-m", args.model_name,
            "--max-len", str(args.max_len), "-t", args.dtype,
            "--kv-bits", str(args.kv_bits),
            "--attend-floor", str(args.attend_floor),
            "--executor", args.executor,
            "--max-prefixes", str(args.max_prefixes),
            "--queue-capacity", str(args.queue_capacity),
            "--kv-pages", str(args.kv_pages),
            "--kv-page-size", str(args.kv_page_size),
            "--chunked-prefill", str(args.chunked_prefill),
            "--governor-interval", str(args.governor_interval),
            "--brownout-queue-high", str(args.brownout_queue_high),
            "--brownout-queue-low", str(args.brownout_queue_low),
            "--brownout-p95-high", str(args.brownout_p95_high),
            "--brownout-p95-low", str(args.brownout_p95_low),
            "--brownout-dwell-up", str(args.brownout_dwell_up),
            "--brownout-dwell-down", str(args.brownout_dwell_down),
            "--brownout-clamp-tokens", str(args.brownout_clamp_tokens),
            "--brownout-clamp-chunk", str(args.brownout_clamp_chunk),
            "--slo-objective", str(args.slo_objective),
            "--slo-burn-fast", str(args.slo_burn_fast),
            "--slo-burn-slow", str(args.slo_burn_slow),
            "--slo-burn-threshold", str(args.slo_burn_threshold)]
        if args.partition:
            base_cmd += ["-pt", args.partition]
        if args.max_active is not None:
            base_cmd += ["--max-active", str(args.max_active)]
        if args.prefill_budget is not None:
            base_cmd += ["--prefill-budget", str(args.prefill_budget)]
        if args.step_join:
            base_cmd += ["--step-join"]
        if args.no_admission:
            base_cmd += ["--no-admission"]
        if args.no_brownout:
            base_cmd += ["--no-brownout"]
        if args.draft_model:
            base_cmd += ["--draft-model", args.draft_model,
                         "--gamma", str(args.gamma)]
        for kvp in (args.class_rate or []):
            base_cmd += ["--class-rate", kvp]
        for kvp in (args.class_deadline or []):
            base_cmd += ["--class-deadline", kvp]
        if args.inject_stall:
            base_cmd += ["--inject-stall", args.inject_stall]
        supervisor = ReplicaSupervisor(
            base_cmd, args.host, ports,
            respawn=not args.no_replica_respawn)
        replicas = {f"r{i}": f"http://{args.host}:{port}"
                    for i, port in enumerate(ports)}
    router = DecodeRouter(replicas, policy=policy, supervisor=supervisor)
    # the router is a peer process of the fleet observatory: span ring
    # for /debug/spans, flight recorder for slo_burn postmortems
    telemetry.configure(rank=0)
    router_flight = flight.configure(rank=0,
                                     out_dir=args.postmortem_dir)
    collector = None
    if args.fleet_scrape_interval > 0:
        def _on_breach(cls, burn):
            router_flight.note("slo_burn_breach", rid=None,
                               request_class=cls,
                               burn=round(burn, 3))
            router_flight.maybe_dump(
                "slo_burn",
                context={"class": cls, "burn_rate": round(burn, 4),
                         "window": "short",
                         "objective": args.slo_objective,
                         "threshold": args.slo_burn_threshold,
                         "fleet": router.registry.snapshot()})
        burn = fleet_obs.BurnRateEngine(
            objective=args.slo_objective,
            fast_window_s=args.slo_burn_fast,
            slow_window_s=args.slo_burn_slow,
            threshold=args.slo_burn_threshold,
            on_breach=_on_breach)
        collector = fleet_obs.FleetCollector(
            router.scrape_targets,
            interval_s=args.fleet_scrape_interval,
            history=args.fleet_history,
            burn=burn)
    autoscaler = None
    if args.autoscale != "off":
        # the closed capacity loop (serving/autoscale.py): signals come
        # from the fleet collector's aggregated scrape, actuators are
        # the supervisor (spawn with the next epoch) + the router's
        # drain-without-respawn path. advise mode runs the identical
        # loop but only logs — the A/B control arm.
        from pipeedge_tpu.serving import autoscale as autoscale_mod
        apol = autoscale_mod.CapacityPolicy(
            min_size=args.autoscale_min,
            max_size=args.autoscale_max,
            confirm=args.autoscale_confirm,
            cooldown_s=args.autoscale_cooldown,
            dwell_up_s=args.autoscale_dwell_up,
            dwell_down_s=args.autoscale_dwell_down,
            queue_high=args.autoscale_queue_high,
            queue_low=args.autoscale_queue_low,
            burn_high=args.autoscale_burn_high,
            burn_low=args.autoscale_burn_low)

        def _fleet_size():
            return len(router.registry.names())

        def _plan_capacity(direction, cur, target):
            # the dry-run: an un-runnable move renders as `held`
            if supervisor is None:
                return {"ok": False,
                        "reason": "static fleet (--replica-addrs)"}
            if direction == "up":
                return {"ok": True, "direction": "up", "to": target}
            snap = router.registry.snapshot()
            healthy = [n for n, rec in snap.items()
                       if rec["state"] == "healthy"]
            if len(healthy) < 2:
                return {"ok": False,
                        "reason": "no healthy survivor to absorb "
                                  "the drain"}
            # newest healthy replica leaves first (LIFO): the warmest
            # caches stay with the longest-lived replicas
            victim = max(healthy,
                         key=lambda n: int(n[1:]) if n[1:].isdigit()
                         else -1)
            return {"ok": True, "direction": "down", "victim": victim,
                    "to": target}

        def _apply_capacity(plan):
            if plan["direction"] == "up":
                rank = supervisor.add_rank()
                name = f"r{rank}"
                url = supervisor.url_of(rank)
                router.add_replica(name, url, rank=rank)
                print(f"autoscale_spawn replica={name} rank={rank} "
                      f"epoch={supervisor.snapshot()[str(rank)]['epoch']} "
                      f"url={url}", flush=True)
            else:
                victim = plan["victim"]
                out = router.remove_replica(victim)
                rank = out.get("rank")
                if rank is not None:
                    supervisor.retire_rank(rank)
                print(f"autoscale_drain replica={victim} rank={rank} "
                      f"migrated={out.get('migrated_prefixes', 0)}",
                      flush=True)

        controller = autoscale_mod.CapacityController(
            apol, mode=args.autoscale, size_fn=_fleet_size,
            plan_fn=_plan_capacity, apply_fn=_apply_capacity,
            label="replicas")

        def _signals():
            fleet = collector.fleet_snapshot()
            return autoscale_mod.signals_from_fleet(fleet, _fleet_size())

        autoscaler = autoscale_mod.AutoscaleRunner(
            controller, _signals, interval_s=args.autoscale_interval)
    if supervisor is not None:
        for i, name in enumerate(replicas):
            router.bind_rank(name, i)
        supervisor.wait_ready(timeout=600.0)
    router.start()
    if collector is not None:
        collector.start()
    if autoscaler is not None:
        autoscaler.start()
        print(f"autoscale mode={args.autoscale} "
              f"min={args.autoscale_min} max={args.autoscale_max} "
              f"confirm={args.autoscale_confirm} "
              f"cooldown={args.autoscale_cooldown:g}", flush=True)
    server = ThreadingHTTPServer(
        (args.host, args.port),
        make_router_handler(router, args.model_name,
                            collector=collector,
                            autoscaler=autoscaler))
    print(f"serving router ({len(replicas)} replicas) on "
          f"{args.host}:{args.port}", flush=True)
    try:
        server.serve_forever()
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        if collector is not None:
            collector.stop()
        router.stop()
        if supervisor is not None:
            supervisor.stop()


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model-name", default="gpt2")
    p.add_argument("-pt", "--partition", default=None)
    p.add_argument("--max-len", default=1024, type=int)
    p.add_argument("-t", "--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--kv-bits", default=0, type=int, choices=[0, 8])
    p.add_argument("--attend-floor", default=64, type=int)
    p.add_argument("--int8-decode-attend", default=None,
                   choices=["0", "1", "2", "auto"],
                   help="int8-KV decode attention kernel opt-in for the "
                        "serving pipeline (needs --kv-bits 8): 0 = XLA "
                        "dequant route, 1 = v1 kernel, 2 = v2, auto = "
                        "width-policy v2. Default: PIPEEDGE_INT8_DECODE_"
                        "ATTEND, else on (auto) when the int8 compute "
                        "path is enabled (docs/QUANTIZATION.md)")
    p.add_argument("--executor", default="wave", choices=["wave", "stage"],
                   help="wave: one thread ticks the batcher; stage: one "
                        "worker thread pinned per pipeline stage "
                        "(healthz reports per-worker stats)")
    p.add_argument("--draft-model", default=None,
                   help="enable speculative generation: requests with "
                        '"speculative": true run greedy draft/verify '
                        "rounds against this (smaller, same-vocabulary) "
                        "model — token-identical to plain greedy")
    p.add_argument("--gamma", default=4, type=int,
                   help="speculative draft lookahead per round")
    p.add_argument("--max-active", default=None, type=int)
    p.add_argument("--max-prefixes", default=8, type=int,
                   help="LRU bound on registered prompt prefixes (each "
                        "handle retains full max_len KV buffers; with "
                        "--kv-pages only the token lists are stored — "
                        "the prefix trie owns the KV)")
    p.add_argument("--port", default=8321, type=int)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address for the HTTP server and the "
                        "ship/lease listeners (default loopback; use "
                        "a NIC address or 0.0.0.0 for non-loopback "
                        "replicas)")
    # -- routed decode fleet (docs/SERVING.md router topology) ----------
    p.add_argument("--role", default="single",
                   choices=["single", "router", "replica"],
                   help="single: one decode process serving directly "
                        "(the historical mode); router: a model-free "
                        "front-end that health-checks and routes across "
                        "N decode replicas (spawned and supervised, or "
                        "external via --replica-addrs); replica: a "
                        "decode process behind a router (same serving "
                        "surface as single, plus drain/migration)")
    p.add_argument("--replicas", default=2, type=int,
                   help="decode replica processes the router spawns "
                        "and supervises (ignored with --replica-addrs)")
    p.add_argument("--replica-addrs", default=None,
                   metavar="HOST:PORT,...",
                   help="route across EXTERNAL replicas at these "
                        "addresses instead of spawning any (no respawn "
                        "supervision — lifecycle is the operator's)")
    p.add_argument("--no-replica-respawn", action="store_true",
                   help="do not respawn dead decode replicas (default: "
                        "respawn with crash-loop backoff + epoch bump "
                        "and readmit after clean health polls)")
    p.add_argument("--router-poll-interval", default=0.5, type=float,
                   help="seconds between /healthz polls per replica")
    p.add_argument("--router-health-timeout", default=2.0, type=float,
                   help="health-poll timeout; a slow poll scores as "
                        "degraded, a failed one as a miss")
    p.add_argument("--route-timeout", default=120.0, type=float,
                   help="per-attempt request timeout at the router")
    p.add_argument("--route-retries", default=2, type=int,
                   help="re-route attempts to a DIFFERENT replica after "
                        "a connect failure or mid-stream death")
    p.add_argument("--hedge-ms", default=0.0, type=float,
                   help="tail hedging for non-streaming interactive "
                        "requests: if the primary replica has not "
                        "answered within this many ms, race a second "
                        "replica and keep the first answer (0 = off)")
    p.add_argument("--drain-timeout", default=60.0, type=float,
                   help="seconds POST /drain waits for a replica's "
                        "in-flight requests before migrating its "
                        "prefix pages anyway")
    # -- closed-loop capacity (docs/FAULT_TOLERANCE.md autoscale) -------
    p.add_argument("--autoscale", default="off",
                   choices=["off", "advise", "auto"],
                   help="(router) closed-loop capacity control over the "
                        "supervised replica fleet: scale-out spawns a "
                        "replica with the next epoch (warm-up gated "
                        "before it takes traffic), scale-in drains + "
                        "migrates KV prefixes then retires the process. "
                        "advise = run the identical decision loop but "
                        "only log (the A/B control arm); auto = act")
    p.add_argument("--autoscale-min", default=1, type=int,
                   help="replica floor the autoscaler never drains below")
    p.add_argument("--autoscale-max", default=2, type=int,
                   help="replica ceiling it never spawns above")
    p.add_argument("--autoscale-confirm", default=3, type=int,
                   help="consecutive same-direction observation windows "
                        "before a decision is eligible (one hot scrape "
                        "moves nothing)")
    p.add_argument("--autoscale-cooldown", default=10.0, type=float,
                   metavar="S",
                   help="seconds between decisions; each direction "
                        "REVERSAL doubles the effective cooldown "
                        "(flap damper, capped at 8x)")
    p.add_argument("--autoscale-interval", default=1.0, type=float,
                   metavar="S", help="governor tick period")
    p.add_argument("--autoscale-dwell-up", default=0.0, type=float,
                   metavar="S",
                   help="seconds up-pressure must persist before "
                        "scale-out (on top of --autoscale-confirm)")
    p.add_argument("--autoscale-dwell-down", default=5.0, type=float,
                   metavar="S",
                   help="seconds calm must persist before scale-in")
    p.add_argument("--autoscale-queue-high", default=4.0, type=float,
                   help="summed admission queue depth PER REPLICA that "
                        "counts as up pressure")
    p.add_argument("--autoscale-queue-low", default=0.5, type=float,
                   help="per-replica queue depth below which the fleet "
                        "counts as calm (dead band against queue-high)")
    p.add_argument("--autoscale-burn-high", default=1.0, type=float,
                   help="short-window SLO burn rate that counts as up "
                        "pressure")
    p.add_argument("--autoscale-burn-low", default=0.25, type=float,
                   help="burn rate below which the fleet counts as calm")
    # -- paged KV plane + disaggregation (docs/SERVING.md) --------------
    p.add_argument("--kv-pages", default=0, type=int,
                   help="enable the paged KV plane: N fixed-size pages "
                        "per stage shared by every request (page tables "
                        "+ cross-request prefix trie); admission then "
                        "runs on a KV TOKEN budget of N x --kv-page-size "
                        "instead of max_active slots. 0 = dense "
                        "per-request cache slots (the historical mode)")
    p.add_argument("--kv-page-size", default=16, type=int,
                   help="cache positions per KV page")
    p.add_argument("--chunked-prefill", default=0, type=int, metavar="N",
                   help="split prompt passes longer than N tokens into "
                        "N-token chunks interleaved with decode steps "
                        "at every executor step boundary (needs "
                        "--kv-pages; bounds decode-step latency under "
                        "long-prompt bursts). 0 = run-to-completion "
                        "prefill (the historical mode)")
    p.add_argument("--prefill-budget", default=None, type=int,
                   metavar="TOKENS",
                   help="prompt tokens the wave executor may start per "
                        "decode step when chunking (default: the chunk "
                        "size — one chunk per step)")
    p.add_argument("--step-join", action="store_true",
                   help="re-drive the admission queue at every decode-"
                        "step boundary, so queued requests join mid-"
                        "generation instead of at the next completion")
    p.add_argument("--disaggregate", default="off",
                   choices=["off", "local", "wire", "process"],
                   help="split serving into a prefill fleet and a decode "
                        "fleet (needs --kv-pages): prompt passes run on "
                        "a DEDICATED pipeline and ship finished KV pages "
                        "into the decode executor — 'local' hands arrays "
                        "over in-process, 'wire' pushes real bytes "
                        "through the v2 codec + a loopback socket "
                        "(see --kv-ship-bits), 'process' spawns REAL "
                        "separate prefill worker processes over DCN "
                        "sockets with the fault-tolerant lease/ack ship "
                        "protocol (retry, re-dispatch, colocated "
                        "fallback — docs/FAULT_TOLERANCE.md)")
    p.add_argument("--prefill-ranks", default=1, type=int,
                   help="worker processes of --disaggregate process "
                        "(leases re-dispatch across them on faults)")
    p.add_argument("--prefill-lease-timeout", default=30.0, type=float,
                   help="seconds a dispatched prompt pass may go "
                        "unacked before it re-dispatches")
    p.add_argument("--prefill-attempts", default=3, type=int,
                   help="total lease dispatches per prompt before the "
                        "request degrades to colocated prefill")
    p.add_argument("--no-prefill-respawn", action="store_true",
                   help="do not respawn dead prefill workers (default: "
                        "respawn with DCN_EPOCH+1 and readmit via JOIN)")
    p.add_argument("--prefill-heartbeat-interval", default=1.0,
                   type=float,
                   help="ship-plane heartbeat interval (0 disables; "
                        "catches hung workers whose sockets stay open)")
    p.add_argument("--kv-ship-bits", default=0, type=int, choices=[0, 8],
                   help="quantize shipped KV pages on the wire (int8 "
                        "block-scaled, 4x fewer bytes; 0 = exact — the "
                        "token-parity setting)")
    p.add_argument("--prefill-concurrency", default=2, type=int,
                   help="in-flight prompt passes the prefill fleet runs "
                        "concurrently")
    # -- overload protection (docs/SERVING.md) --------------------------
    p.add_argument("--no-admission", action="store_true",
                   help="disable the SLO-aware admission plane (requests "
                        "block in executor backpressure like pre-serving "
                        "builds; deadlines still propagate)")
    p.add_argument("--queue-capacity", default=64, type=int,
                   help="bound on the EDF admission queue; overflow sheds "
                        "the latest-deadline waiter with 503 + Retry-After")
    p.add_argument("--class-rate", action="append", metavar="CLASS=RPS",
                   help="per-class sustained token-bucket admit rate "
                        "(repeatable; default: unlimited)")
    p.add_argument("--class-deadline", action="append",
                   metavar="CLASS=SECONDS",
                   help="per-class DEFAULT deadline budget applied when a "
                        "request carries no deadline_ms (repeatable)")
    p.add_argument("--no-brownout", action="store_true",
                   help="disable the watermark-driven brownout ladder")
    p.add_argument("--brownout-queue-high", default=8, type=int)
    p.add_argument("--brownout-queue-low", default=1, type=int)
    p.add_argument("--brownout-p95-high", default=2.0, type=float,
                   help="windowed request-latency p95 (s) above which the "
                        "ladder steps up")
    p.add_argument("--brownout-p95-low", default=0.5, type=float)
    p.add_argument("--brownout-dwell-up", default=0.5, type=float,
                   help="seconds the hot condition must persist per "
                        "step up (hysteresis)")
    p.add_argument("--brownout-dwell-down", default=2.0, type=float)
    p.add_argument("--brownout-clamp-tokens", default=16, type=int,
                   help="new_tokens clamp at brownout level >= 2")
    p.add_argument("--brownout-clamp-chunk", default=0, type=int,
                   metavar="TOKENS",
                   help="chunked-prefill chunk-size clamp at brownout "
                        "level >= 2 (0 = lever unarmed; only applies "
                        "with --chunked-prefill)")
    p.add_argument("--governor-interval", default=0.25, type=float,
                   help="brownout governor tick (s)")
    p.add_argument("--trace-spans", default=None, metavar="OUT",
                   help="record request/stage spans and write a Perfetto-"
                        "loadable trace JSON to OUT on shutdown "
                        "(tools/trace_report.py analyzes it)")
    p.add_argument("--postmortem-dir", default=None, metavar="DIR",
                   help="directory for flight-recorder postmortem bundles "
                        "(default: env PIPEEDGE_POSTMORTEM_DIR or "
                        "./postmortems); bundles are written on 504s, "
                        "sheds, failover, SLO breach, and POST /debug/dump")
    p.add_argument("--fleet-scrape-interval", default=1.0, type=float,
                   metavar="S",
                   help="(router) period of the fleet collector's "
                        "/metrics scrape across replicas and prefill "
                        "workers — feeds GET /fleet and the SLO burn-"
                        "rate engine (<= 0 disables; /fleet then 503s)")
    p.add_argument("--fleet-history", default=120, type=int,
                   help="(router) scrape samples retained per target "
                        "(the /fleet rate window is bounded by "
                        "history * scrape interval)")
    p.add_argument("--slo-objective", default=0.99, type=float,
                   help="per-class SLO objective (good-request fraction) "
                        "the burn-rate engine budgets against")
    p.add_argument("--slo-burn-fast", default=30.0, type=float,
                   metavar="S",
                   help="short burn-rate window (s) — breaching "
                        "threshold here triggers one slo_burn "
                        "postmortem bundle per episode")
    p.add_argument("--slo-burn-slow", default=300.0, type=float,
                   metavar="S", help="long burn-rate window (s)")
    p.add_argument("--slo-burn-threshold", default=10.0, type=float,
                   help="short-window burn rate that counts as a breach "
                        "(10 = burning a 30d budget in ~3d)")
    p.add_argument("--inject-stall", default=None, metavar="STAGE:MS",
                   help="chaos hook (tests/CI only): sleep MS ms inside "
                        "every step of pipeline stage STAGE — the "
                        "deterministic stall the traced-serve smoke "
                        "asserts trace_report --request can name")
    args = p.parse_args()

    # parse-time composition checks — BEFORE any model build, so a bad
    # flag pair fails in milliseconds with both flags named, not after
    # minutes of weight loading (and never as a bare mid-construction
    # refusal from _Service)
    if args.disaggregate != "off" and not args.kv_pages:
        p.error("--disaggregate needs --kv-pages (shipped KV lands in "
                "the paged pool)")
    if args.chunked_prefill < 0:
        p.error("--chunked-prefill must be >= 0")
    if args.chunked_prefill and not args.kv_pages:
        p.error("--chunked-prefill needs --kv-pages (chunk waves write "
                "prompt spans at an offset into the request's page "
                "table; dense cache slots have no span-at-offset path)")
    if args.prefill_budget is not None and not args.chunked_prefill:
        p.error("--prefill-budget only applies with --chunked-prefill")
    if args.prefill_budget is not None and args.prefill_budget < 1:
        p.error("--prefill-budget must be >= 1")
    if args.role == "router":
        if args.disaggregate != "off":
            p.error("--role router does not compose with --disaggregate "
                    "yet (run disaggregation inside each replica is a "
                    "scoped follow-up; see docs/SERVING.md)")
        if args.replica_addrs is None and args.replicas < 1:
            p.error("--replicas must be >= 1 (or pass --replica-addrs)")
        if args.hedge_ms < 0:
            p.error("--hedge-ms must be >= 0")
        if args.route_retries < 0:
            p.error("--route-retries must be >= 0")
        if args.autoscale != "off":
            if args.replica_addrs is not None:
                p.error("--autoscale needs a SUPERVISED fleet (it "
                        "spawns and retires replica processes); "
                        "--replica-addrs fleets are the operator's "
                        "lifecycle")
            if args.fleet_scrape_interval <= 0:
                p.error("--autoscale needs the fleet collector "
                        "(--fleet-scrape-interval > 0) — its scrape is "
                        "the controller's signal plane")
            if not 1 <= args.autoscale_min <= args.autoscale_max:
                p.error("need 1 <= --autoscale-min <= --autoscale-max")
            if args.autoscale_confirm < 1:
                p.error("--autoscale-confirm must be >= 1")
            if args.autoscale_interval <= 0:
                p.error("--autoscale-interval must be > 0")
    elif args.replica_addrs is not None:
        p.error("--replica-addrs only applies with --role router")
    elif args.autoscale != "off":
        p.error("--autoscale only applies with --role router (runtime "
                "--rounds fleets get the pipeline-level half via "
                "runtime.py --autoscale-ranks)")

    if args.role == "router":
        # the router is a model-free proxy: no jax, no weights — it
        # routes, health-checks, drains, and migrates
        return _run_router(args)

    from pipeedge_tpu.utils import apply_env_platform
    apply_env_platform()
    import jax.numpy as jnp

    from pipeedge_tpu.parallel.decode import build_decode_pipeline

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    partition = None
    if args.partition:
        nums = [int(x) for x in args.partition.split(",")]
        partition = list(zip(nums[::2], nums[1::2]))
    pipe = build_decode_pipeline(
        args.model_name, partition, max_len=args.max_len, dtype=dtype,
        cache_bits=args.kv_bits, attend_floor=args.attend_floor,
        int8_decode_attend=args.int8_decode_attend)
    if args.inject_stall:
        _inject_stall(pipe, args.inject_stall, p)
    spec = None
    if args.draft_model:
        if args.kv_bits:
            p.error("--draft-model does not compose with --kv-bits (int8 "
                    "span verification is not bit-identical to serial "
                    "int8 steps)")
        from pipeedge_tpu.parallel.speculative import SpeculativeDecoder
        d_pipe = build_decode_pipeline(
            args.draft_model, None, max_len=args.max_len, dtype=dtype,
            attend_floor=args.attend_floor)
        spec = SpeculativeDecoder(pipe, d_pipe, gamma=args.gamma)
    prefill_fleet = None
    prefill_supervisor = None
    ship_ctx = None
    if args.disaggregate == "process":
        # REAL separate prefill processes over DCN sockets (this process
        # is rank 0 of the ship plane; workers are ranks 1..N). The
        # lease/ack protocol makes the split survivable: ship timeout /
        # CRC failure / worker death re-dispatch or degrade to colocated
        # prefill, and dead workers respawn with DCN_EPOCH+1 and JOIN
        # back in (docs/FAULT_TOLERANCE.md disaggregated serving)
        from pipeedge_tpu.comm import dcn
        from pipeedge_tpu.kv import RemotePrefillFleet
        world = 1 + args.prefill_ranks
        addrs = [(args.host, port)
                 for port in _free_ports(world, args.host)]
        addr_arg = ",".join(f"{h}:{port}" for h, port in addrs)
        worker_cmd = [
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "prefill_worker.py"),
            str(world), "--dcn-addrs", addr_arg,
            "-m", args.model_name, "--max-len", str(args.max_len),
            "-t", args.dtype, "--attend-floor", str(args.attend_floor),
            "--heartbeat-interval",
            str(args.prefill_heartbeat_interval)]
        if args.partition:
            worker_cmd += ["-pt", args.partition]
        # per-worker observability listeners (GET /metrics, /healthz,
        # /debug/spans): the replica's /healthz exposes each worker's
        # http_url, and the router's fleet collector scrapes them
        pf_http = dict(zip(range(1, world),
                           _free_ports(args.prefill_ranks, args.host)))
        prefill_supervisor = PrefillWorkerSupervisor(
            worker_cmd, ranks=range(1, world),
            respawn=not args.no_prefill_respawn,
            http_ports=pf_http)
        ship_ctx = dcn.DistDcnContext(world, 0, addrs)
        ship_ctx.init()
        prefill_supervisor.wait_ready()
        prefill_fleet = RemotePrefillFleet(
            ship_ctx, ranks=range(1, world), dtype=dtype,
            ship_bits=args.kv_ship_bits,
            lease_timeout_s=args.prefill_lease_timeout,
            max_attempts=args.prefill_attempts,
            max_concurrent=max(1, args.prefill_concurrency),
            heartbeat_interval=args.prefill_heartbeat_interval)
    elif args.disaggregate != "off":
        from pipeedge_tpu.kv import PrefillFleet
        # a DEDICATED pipeline: its prompt passes never contend with the
        # decode executor's stage programs for host dispatch order
        prefill_pipe = build_decode_pipeline(
            args.model_name, partition, max_len=args.max_len, dtype=dtype,
            attend_floor=args.attend_floor)
        prefill_fleet = PrefillFleet(
            prefill_pipe, path=args.disaggregate,
            ship_bits=args.kv_ship_bits,
            max_concurrent=args.prefill_concurrency)

    # spans are always on in serving processes: GET /debug/spans drains
    # the ring for trace_report --fleet federation without pre-arming.
    # --trace-spans keeps controlling only the shutdown trace dump.
    telemetry.configure(rank=0)
    from pipeedge_tpu.analysis import lockdep
    if args.trace_spans or lockdep.enabled():
        # SIGTERM must unwind through the finally below (the default
        # handler would kill the process before the trace — or the
        # PIPEEDGE_LOCKDEP atexit report — is written)
        import signal
        signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
    service = _Service(pipe, max_active=args.max_active,
                       max_prefixes=args.max_prefixes, spec=spec,
                       executor=args.executor,
                       edge_itemsize=2 if args.dtype == "bfloat16" else 4,
                       admission_enabled=not args.no_admission,
                       queue_capacity=args.queue_capacity,
                       class_rates=_parse_class_map(
                           args.class_rate, "--class-rate", p),
                       class_deadlines_s=_parse_class_map(
                           args.class_deadline, "--class-deadline", p),
                       brownout_enabled=not args.no_brownout,
                       brownout_marks=Watermarks(
                           queue_high=args.brownout_queue_high,
                           queue_low=args.brownout_queue_low,
                           p95_high_s=args.brownout_p95_high,
                           p95_low_s=args.brownout_p95_low,
                           dwell_up_s=args.brownout_dwell_up,
                           dwell_down_s=args.brownout_dwell_down),
                       clamp_new_tokens=args.brownout_clamp_tokens,
                       governor_interval=args.governor_interval,
                       postmortem_dir=args.postmortem_dir,
                       kv_pages=args.kv_pages,
                       kv_page_size=args.kv_page_size,
                       prefill_fleet=prefill_fleet,
                       prefill_supervisor=prefill_supervisor,
                       chunked_prefill=args.chunked_prefill,
                       step_join=args.step_join,
                       prefill_budget=args.prefill_budget,
                       clamp_chunk_tokens=args.brownout_clamp_chunk,
                       slo_objective=args.slo_objective,
                       slo_burn_fast=args.slo_burn_fast,
                       slo_burn_slow=args.slo_burn_slow,
                       slo_burn_threshold=args.slo_burn_threshold)
    if prefill_fleet is not None and hasattr(prefill_fleet,
                                             "flight_note"):
        # ship-plane faults (lease timeouts, zombie drops, worker
        # deaths/readmissions) land in the flight recorder's event ring
        prefill_fleet.flight_note = service.flight.note
    server = ThreadingHTTPServer((args.host, args.port),
                                 make_handler(service, args.model_name))
    print(f"serving {args.model_name} ({len(pipe.stages)} stages, "
          f"{args.executor} executor) on {args.host}:{args.port}",
          flush=True)
    try:
        server.serve_forever()
    finally:
        service.stop()
        if ship_ctx is not None:
            ship_ctx.shutdown()
        if args.trace_spans and telemetry.recorder() is not None:
            from pipeedge_tpu.telemetry import chrome_trace
            chrome_trace.dump_trace(telemetry.recorder().snapshot(),
                                    args.trace_spans)


if __name__ == "__main__":
    main()
